"""Prediction-assisted speculative match cycles (ROADMAP item 3).

The pipelined pass (scheduler/pipeline.py) overlaps phases *within* one
match pass; consecutive cycles still run strictly back-to-back — the
device idles from the moment cycle N's launches drain until cycle N+1's
solve dispatches.  Prediction-Assisted Online Distributed DL Workload
Scheduling (arXiv:2501.05563) shows most of that inter-decision idle is
recoverable by predicting task completions and speculatively executing
the next decision; Dynamic Fractional Resource Scheduling (arXiv:1106.4985)
frames predicted-duration-aware backfill as a scoring term rather than a
separate pass.  This module is both halves:

  * `QuantileRuntimePredictor` — per-(user, command-fingerprint) rolling
    quantile estimators over observed instance runtimes, fed from the
    store's instance-completion events.  Deliberately pluggable: anything
    with `predict_runtime_ms(user, command)` / `observe(...)` can stand
    in (ROADMAP item 5's learned model slots in here);

  * `CycleSpeculator` — at the end of cycle N (launches committed, the
    backend drain and inter-cycle idle ahead), rank + encode + DISPATCH
    cycle N+1's solve against the *predicted* offer set: running tasks
    the predictor expects to finish inside the horizon are assumed
    complete, their capacity folded back into their hosts' offers and
    their rows removed from the predicted DRU rank.  The solve runs on
    the device while the host idles between cycles.

THE COMMIT RULE (docs/architecture.md): a speculation is stamped at
dispatch with (a) the encode-cache epoch, (b) a `SpeculationGuard` token
registering the EXACT store events its predicted state implies (each
assumed completion's `instance/status: success` + `job/state: completed`),
and (c) the structural offer-set fingerprint.  At cycle N+1 start it
commits only if

  1. every registered event landed (the predictions came true),
  2. NO other store mutation landed (the guard marks the token stale on
     the first unexpected event — submissions, kills, failures, quota /
     share / config / pool changes, capacity deltas, everything),
  3. the encode-cache epoch and the offer-set structure are unchanged,
  4. a fresh `select_considerable` over the real, just-ranked queue is
     identical (uuid-for-uuid) to the speculative considerable window.

Under 1-4 the speculative solve's inputs equal a fresh solve's inputs, so
the committed placements are the placements cycle N+1 would have computed
— the speculation only moved the work earlier.  Anything else DROPS the
speculation (counted, reason-coded) and the cycle solves fresh: a stale
speculation is never repaired, so it is provably unable to commit.

Group-member completions are never assumed (their feasibility context
changes outside the guard's event algebra), and a pool's speculation is
skipped entirely while the predictor is cold for its running work.
"""
from __future__ import annotations

import collections
import hashlib
import itertools
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from cook_tpu.models.store import Event, JobStore
from cook_tpu.scheduler.flight_recorder import NULL_CYCLE
from cook_tpu.utils.metrics import global_registry

log = logging.getLogger(__name__)

# drop reasons (surfaced on CycleRecord.speculation_drop, the
# speculation.dropped metric's reason label, and /debug/predictions)
DROP_EPOCH_STALE = "epoch-stale"          # an unexpected store mutation
DROP_PREDICTION_MISS = "prediction-miss"  # an assumed completion never landed
DROP_OFFERS_CHANGED = "offers-changed"    # offer structure shifted (no event)
DROP_QUEUE_SHIFTED = "queue-shifted"      # fresh considerable window differs
DROP_PREDICTOR_COLD = "predictor-cold"    # no estimate for the running work
DROP_DISABLED = "disabled"                # runtime kill-switch off
DROP_SOLVE_ERROR = "solve-error"          # the speculative solve raised

# the phases whose sum is a cycle's start-to-first-launch latency (the
# metric speculation exists to lower): everything between cycle start and
# the launch fan-out.  `rank` is excluded — it runs identically (and often
# on its own trigger) whether or not the cycle was served speculatively.
PRE_LAUNCH_PHASES = ("tensor_build", "dispatch", "solve",
                     "speculation_commit")


def pre_launch_ms(record: dict) -> float:
    """Cycle-start-to-first-launch latency of one CycleRecord JSON dict
    (flight recorder schema) in milliseconds."""
    phases = record.get("phases", {})
    return sum(phases.get(name, 0.0) for name in PRE_LAUNCH_PHASES) * 1000.0


def command_fingerprint(command: str) -> str:
    """Stable, bounded key for a job command: the leading token (the
    program) plus a short digest of the full line, so `train.py --lr=3e-4`
    and `train.py --lr=1e-3` share history while arbitrary commands can't
    grow unbounded key material."""
    tokens = (command or "").split(None, 1)
    head = tokens[0][:48] if tokens else ""  # REST admits " " commands
    digest = hashlib.sha1((command or "").encode()).hexdigest()[:8]
    return f"{head}#{digest}"


class QuantileRuntimePredictor:
    """Per-(user, command-fingerprint) rolling-quantile runtime estimator.

    Rolling window of the newest `window` observed runtimes per key; the
    estimate is the `quantile`-th percentile (default p75 — mildly
    conservative: over-predicting a completion's lateness costs a dropped
    speculation, under-predicting costs nothing, so lean late).  Cold
    start: no estimate until `min_samples` observations.  LRU-bounded at
    `max_keys` (users x commands is unbounded on a long-lived leader).
    Thread-safe: observations arrive on store-watcher threads while the
    scheduler thread reads estimates.
    """

    def __init__(self, *, quantile: float = 0.75, window: int = 64,
                 min_samples: int = 3, max_keys: int = 50_000):
        if not 0.0 < quantile <= 1.0:
            raise ValueError(f"bad predictor quantile {quantile}")
        self.quantile = quantile
        self.window = window
        self.min_samples = max(1, min_samples)
        self.max_keys = max_keys
        self._lock = threading.Lock()
        self._samples: collections.OrderedDict[tuple, collections.deque] = \
            collections.OrderedDict()
        self._observations = 0
        self._store: Optional[JobStore] = None
        self._obs_counter = global_registry.counter(
            "prediction.observations",
            "instance runtimes observed into the runtime predictor")
        self._est_counter = global_registry.counter(
            "prediction.estimates",
            "runtime-estimate lookups, by result (hit = enough samples, "
            "cold = below min_samples)")
        self._keys_gauge = global_registry.gauge(
            "prediction.keys",
            "distinct (user, command-fingerprint) keys the runtime "
            "predictor currently tracks")

    # ------------------------------------------------------------- feeding

    def attach(self, store: JobStore) -> "QuantileRuntimePredictor":
        """Subscribe to the store's event feed: every successful terminal
        instance feeds its observed runtime (the completion path the
        flight recorder also rides)."""
        self._store = store
        store.add_watcher(self._on_event)
        return self

    def _on_event(self, event: Event) -> None:
        if event.kind != "instance/status" \
                or event.data.get("status") != "success":
            return
        store = self._store
        if store is None:
            return
        inst = (event.entities or {}).get("instance") \
            or store.instances.get(event.data.get("task_id"))
        if inst is None or inst.end_time_ms <= inst.start_time_ms:
            return
        job = store.jobs.get(inst.job_uuid)
        if job is None:
            return
        self.observe(job.user, job.command,
                     inst.end_time_ms - inst.start_time_ms)

    def observe(self, user: str, command: str, runtime_ms: float) -> None:
        if runtime_ms <= 0:
            return
        key = (user, command_fingerprint(command))
        with self._lock:
            samples = self._samples.get(key)
            if samples is None:
                samples = collections.deque(maxlen=self.window)
                self._samples[key] = samples
            samples.append(float(runtime_ms))
            self._samples.move_to_end(key)
            while len(self._samples) > self.max_keys:
                self._samples.popitem(last=False)
            self._observations += 1
            n_keys = len(self._samples)
        self._obs_counter.inc(1)
        self._keys_gauge.set(n_keys)

    # ------------------------------------------------------------ estimates

    def predict_runtime_ms(self, user: str, command: str,
                           *, quantile: Optional[float] = None
                           ) -> Optional[float]:
        """The key's rolling `quantile` runtime estimate, or None while
        cold (fewer than `min_samples` observations)."""
        key = (user, command_fingerprint(command))
        with self._lock:
            samples = self._samples.get(key)
            if samples is None or len(samples) < self.min_samples:
                self._est_counter.inc(1, {"result": "cold"})
                return None
            values = list(samples)
        self._est_counter.inc(1, {"result": "hit"})
        return float(np.quantile(np.asarray(values),
                                 quantile if quantile is not None
                                 else self.quantile))

    def stats_json(self) -> dict:
        with self._lock:
            return {
                "kind": "quantile",
                "quantile": self.quantile,
                "window": self.window,
                "min_samples": self.min_samples,
                "keys": len(self._samples),
                "observations": self._observations,
            }


# --------------------------------------------------------------- the guard


@dataclass
class _GuardToken:
    pool: str = ""
    expected: dict = field(default_factory=dict)  # key -> confirmed bool
    stale: bool = False
    stale_kind: str = ""


def _event_key(event: Event) -> tuple:
    """The guard's event algebra: (kind, id, qualifier) — precise enough
    that a predicted completion's success is distinguishable from the
    same task failing."""
    kind = event.kind
    if kind == "instance/status":
        return (kind, event.data.get("task_id"), event.data.get("status"))
    if kind == "job/state":
        return (kind, event.data.get("uuid"), event.data.get("state"))
    return (kind, event.data.get("uuid") or event.data.get("task_id"), "")


class SpeculationGuard:
    """Store-event epoch for speculative solves.

    `begin(pool)` opens a token BEFORE the speculative dispatch reads any
    store state; every event from then on either matches one of the
    token's registered expected keys (confirming a prediction) or marks
    the token stale.  `expect()` registers the keys once the dispatch has
    decided its assumptions — events landing in the tiny window between
    begin and expect conservatively count as stale.  `finish()` answers
    (committable, drop_reason) and retires the token.

    POOL SCOPING: every match input is pool-local (offers, ranked queue,
    per-pool quota/usage walks, per-pool DRU), so a job/instance event
    attributable to ANOTHER pool cannot change this pool's solve — it is
    ignored rather than vetoing (without this, one pool's completions
    would veto every other pool's speculation on a multi-pool leader).
    Only the four job-lifecycle kinds whose pool is derivable are scoped
    (instance/status, instance/created, job/state, job/created);
    everything with cross-pool reach — quota/share/config/pool mutations,
    pool moves, capacity deltas, group events — stays global and vetoes
    every in-flight token.
    """

    def __init__(self, store: Optional[JobStore] = None):
        self._lock = threading.Lock()
        self._store = store
        self._tokens: dict[int, _GuardToken] = {}
        self._ids = itertools.count(1)
        if store is not None:
            store.add_watcher(self._on_event)

    def begin(self, pool: str = "") -> int:
        with self._lock:
            token = next(self._ids)
            self._tokens[token] = _GuardToken(pool=pool)
            return token

    def _event_pool(self, event: Event) -> Optional[str]:
        """The pool an event is attributable to, or None (= global: the
        event vetoes every token)."""
        kind = event.kind
        if kind == "job/created":
            return event.data.get("pool") or None
        if kind in ("instance/status", "instance/created"):
            job_uuid = event.data.get("job")
        elif kind == "job/state":
            job_uuid = event.data.get("uuid")
        else:
            return None
        job = (event.entities or {}).get("job")
        if job is None and self._store is not None and job_uuid:
            # watchers run on the mutating thread under the store's
            # reentrant lock, so this read is safe
            job = self._store.jobs.get(job_uuid)
        return getattr(job, "pool", None)

    def expect(self, token: int, keys: Sequence[tuple]) -> None:
        with self._lock:
            state = self._tokens.get(token)
            if state is not None:
                for key in keys:
                    state.expected.setdefault(key, False)

    def cancel(self, token: int) -> None:
        with self._lock:
            self._tokens.pop(token, None)

    def finish(self, token: int) -> tuple[bool, str]:
        """(committable, drop_reason); retires the token.  Committable
        means: no unexpected mutation landed AND every expected event was
        observed — i.e. the store state now equals the state the
        speculation assumed."""
        with self._lock:
            state = self._tokens.pop(token, None)
        if state is None:
            return False, DROP_EPOCH_STALE
        if state.stale:
            return False, DROP_EPOCH_STALE
        if not all(state.expected.values()):
            return False, DROP_PREDICTION_MISS
        return True, ""

    def _on_event(self, event: Event) -> None:
        key = _event_key(event)
        event_pool = self._event_pool(event)
        with self._lock:
            for state in self._tokens.values():
                if key in state.expected:
                    state.expected[key] = True
                elif event_pool is not None and state.pool \
                        and event_pool != state.pool:
                    continue  # another pool's lifecycle event: pool-local
                    # inputs are untouched, the token stays committable
                elif not state.stale:
                    state.stale = True
                    state.stale_kind = event.kind


# ------------------------------------------------- predicted state facades


@dataclass(frozen=True)
class PredictedCompletion:
    """One running instance the predictor expects to finish inside the
    speculation horizon."""

    task_id: str
    job_uuid: str
    hostname: str
    cluster: str
    freed: tuple            # (mem, cpus, gpus, disk) returning to the host
    predicted_end_ms: float


class PredictedStoreView:
    """Read-only store facade with the assumed-complete instances (and
    their then-finished jobs) removed — the state the pool will be in if
    the predictions land.  Only the read surfaces the rank / considerable
    selection touch are overridden; everything else delegates."""

    def __init__(self, store: JobStore, assumed: Sequence[PredictedCompletion]):
        self._store = store
        self._tasks = {a.task_id for a in assumed}
        self._done_jobs = {a.job_uuid for a in assumed}

    def __getattr__(self, name):
        return getattr(self._store, name)

    def running_jobs(self, pool: str):
        return [j for j in self._store.running_jobs(pool)
                if j.uuid not in self._done_jobs]

    def running_instances(self, pool: str):
        return [i for i in self._store.running_instances(pool)
                if i.task_id not in self._tasks]

    def job_instances(self, job_uuid: str):
        return [i for i in self._store.job_instances(job_uuid)
                if i.task_id not in self._tasks]

    def user_usage(self, pool: str):
        from cook_tpu.models.entities import Resources

        usage: dict[str, Resources] = {}
        for job in self.running_jobs(pool):
            usage[job.user] = usage.get(job.user, Resources()) + job.resources
        return usage


class _PredictedCluster:
    """Cluster facade whose offers fold assumed-freed capacity back into
    the freeing host's row.  Everything except the offer scan delegates to
    the real cluster, so a committed speculation launches through the real
    executors, rate limiters, and kill locks."""

    def __init__(self, cluster, freed_by_host: dict):
        self._cluster = cluster
        self._freed = freed_by_host  # hostname -> [mem, cpus, gpus, disk]

    def __getattr__(self, name):
        return getattr(self._cluster, name)

    def pending_offers(self, pool: str):
        import dataclasses

        offers = self._cluster.pending_offers(pool)
        if not self._freed:
            return offers
        out = []
        for offer in offers:
            freed = self._freed.get(offer.hostname)
            if freed is None:
                out.append(offer)
            else:
                out.append(dataclasses.replace(
                    offer,
                    mem=offer.mem + freed[0],
                    cpus=offer.cpus + freed[1],
                    gpus=offer.gpus + freed[2],
                    disk=offer.disk + freed[3],
                ))
        return out


# ------------------------------------------------------------ the speculator


@dataclass
class SpeculativeSolve:
    """One in-flight speculation: the predicted prepare + dispatched solve
    and everything the commit rule validates against."""

    pool: str
    prepared: object                   # matcher.PreparedPool
    pending: object                    # PendingResult (solve in flight)
    token: int
    assumed: list
    encode_epoch: int
    offers_fp: int
    considerable_uuids: list[str]
    t_dispatch: float = 0.0
    # device-resident state generation at dispatch (device_state.py): a
    # bump (encode epoch invalidation, explicit clear) between dispatch
    # and commit means the speculative problem was built from dropped
    # resident tensors — the commit must not trust it
    resident_epoch: int = 0


@dataclass
class CommitResult:
    """Outcome of one cycle's commit attempt."""

    status: str                        # "hit" | "dropped" | "none"
    reason: str = ""                   # drop/skip reason ("" when hit/none)
    prepared: object = None
    assignment: Optional[np.ndarray] = None

    @property
    def ok(self) -> bool:
        return self.status == "hit"


class CycleSpeculator:
    """Owns the per-pool speculative pipeline: dispatch at cycle N's end,
    commit-or-drop at cycle N+1's start (see module docstring for the
    commit rule)."""

    def __init__(self, store: JobStore, clusters, predictor, *,
                 horizon_ms: float = 30_000.0, encode_cache=None,
                 telemetry=None, device_state=None):
        self.store = store
        self.clusters = clusters      # live reference (add_cluster appends)
        self.predictor = predictor
        self.horizon_ms = float(horizon_ms)
        self.encode_cache = encode_cache
        self.device_state = device_state
        self.telemetry = telemetry
        self.enabled = True           # runtime kill-switch
        self._match_config = None     # last dispatch's MatchConfig
        self.guard = SpeculationGuard(store)
        self._lock = threading.Lock()
        self._inflight: dict[str, SpeculativeSolve] = {}
        # why the NEXT commit attempt will find nothing in flight
        # (predictor-cold etc.), keyed by pool
        self._skip_reason: dict[str, str] = {}
        self._hits = 0
        self._dropped = 0
        self._dispatched = 0
        self._drop_reasons: collections.Counter = collections.Counter()
        self._dispatch_counter = global_registry.counter(
            "speculation.dispatched",
            "speculative next-cycle solves dispatched while the previous "
            "cycle drained, per pool")
        self._hit_counter = global_registry.counter(
            "speculation.hits",
            "match cycles served from a committed speculative solve, "
            "per pool")
        self._drop_counter = global_registry.counter(
            "speculation.dropped",
            "speculative solves dropped instead of committed, per "
            "pool/reason (epoch-stale = a store mutation invalidated the "
            "stamped state; never repaired)")

    # ------------------------------------------------------------- dispatch

    def predicted_completions(self, pool_name: str,
                              now_ms: int) -> tuple[list, bool]:
        """(assumed completions inside the horizon, saw_cold).  Group
        members are never assumed — their completion changes sibling
        feasibility context outside the guard's event algebra."""
        from cook_tpu.scheduler.matcher import job_mem_with_overhead

        assumed: list[PredictedCompletion] = []
        saw_cold = False
        for inst in self.store.running_instances(pool_name):
            job = self.store.jobs.get(inst.job_uuid)
            if job is None or job.group_uuid:
                continue
            estimate = self.predictor.predict_runtime_ms(job.user,
                                                         job.command)
            if estimate is None:
                saw_cold = True
                continue
            eta = inst.start_time_ms + estimate
            if eta <= now_ms + self.horizon_ms:
                r = job.resources
                assumed.append(PredictedCompletion(
                    task_id=inst.task_id,
                    job_uuid=inst.job_uuid,
                    hostname=inst.hostname,
                    cluster=inst.compute_cluster,
                    freed=(job_mem_with_overhead(job, self._match_config),
                           r.cpus, r.gpus, r.disk),
                    predicted_end_ms=eta,
                ))
        return assumed, saw_cold

    def dispatch(self, pool, config, state, *,
                 launch_filter=None, host_reservations=None,
                 host_attrs=None, offensive_job_filter=None,
                 predictor_for_rank=None, backfill_weight: float = 0.0,
                 backfill_norm_ms: float = 600_000.0) -> bool:
        """Speculatively prepare + dispatch `pool`'s NEXT match solve
        against the predicted offer set.  Called at the end of cycle N,
        after its launches (and their store events) have landed; the solve
        executes asynchronously through the drain / inter-cycle idle.
        Returns True when a speculation is now in flight."""
        from cook_tpu.scheduler.matcher import (
            dispatch_pool_solve,
            prepare_pool_problem,
        )
        from cook_tpu.scheduler.ranking import rank_pool

        name = pool.name
        self._cancel_inflight(name)
        if not self.enabled:
            self._skip_reason[name] = DROP_DISABLED
            return False
        if config.completion_multiplier > 0 and config.host_lifetime_mins > 0:
            # the estimated-completion constraint makes feasibility rows
            # clock- and predictor-state-dependent: a fresh solve at
            # cycle N+1 would encode them against a LATER now_ms (and a
            # predictor fed by the very completions we assume), so the
            # commit rule's exact-parity claim cannot hold — never
            # speculate while the constraint is active (the encode cache
            # bypasses itself in this mode for the same reason)
            self._skip_reason[name] = ""
            return False
        self._match_config = config
        now_ms = self.store.clock()
        # the guard token opens BEFORE any store read below: a mutation
        # racing the dispatch marks it stale (conservatively dropped)
        token = self.guard.begin(name)
        try:
            assumed, saw_cold = self.predicted_completions(name, now_ms)
            if not assumed:
                self.guard.cancel(token)
                self._skip_reason[name] = (DROP_PREDICTOR_COLD if saw_cold
                                           else "")
                return False
            expected = []
            for a in assumed:
                expected.append(("instance/status", a.task_id, "success"))
                expected.append(("job/state", a.job_uuid, "completed"))
            self.guard.expect(token, expected)
            view = PredictedStoreView(self.store, assumed)
            freed_by_cluster: dict[str, dict] = {}
            for a in assumed:
                hosts = freed_by_cluster.setdefault(a.cluster, {})
                slot = hosts.setdefault(a.hostname, [0.0, 0.0, 0.0, 0.0])
                for i in range(4):
                    slot[i] += a.freed[i]
            pclusters = [
                _PredictedCluster(c, freed_by_cluster.get(c.name, {}))
                for c in self.clusters
            ]
            # the predicted rank must mirror the REAL rank cycle's scoring
            # exactly (same backfill term, same filter) or the commit-time
            # considerable-equality check can never pass
            queue = rank_pool(view, pool,
                              offensive_job_filter=offensive_job_filter,
                              predictor=predictor_for_rank,
                              backfill_weight=backfill_weight,
                              backfill_norm_ms=backfill_norm_ms)
            if not queue.jobs:
                self.guard.cancel(token)
                self._skip_reason[name] = ""
                return False
            prepared = prepare_pool_problem(
                view, pool, queue, pclusters, config, state,
                launch_filter=launch_filter,
                host_reservations=host_reservations,
                host_attrs=host_attrs, flight=NULL_CYCLE,
                encode_cache=self.encode_cache,
                predictor=self.predictor,
                device_state=self.device_state,
            )
            if not prepared.solvable:
                self.guard.cancel(token)
                self._skip_reason[name] = ""
                return False
            pending = dispatch_pool_solve(prepared, config,
                                          telemetry=None)
        except Exception:  # noqa: BLE001 — speculation must never take
            # the real cycle down; the next cycle simply solves fresh
            log.exception("speculative dispatch failed (pool %s)", name)
            self.guard.cancel(token)
            self._skip_reason[name] = DROP_SOLVE_ERROR
            return False
        from cook_tpu.scheduler.encode_cache import offers_fingerprint

        spec = SpeculativeSolve(
            pool=name, prepared=prepared, pending=pending, token=token,
            assumed=assumed,
            encode_epoch=(self.encode_cache.epoch
                          if self.encode_cache is not None else 0),
            offers_fp=offers_fingerprint(prepared.cluster_offers),
            considerable_uuids=[j.uuid for j in prepared.considerable],
            t_dispatch=time.perf_counter(),
            resident_epoch=(self.device_state.epoch
                            if self.device_state is not None else 0),
        )
        with self._lock:
            self._inflight[name] = spec
            self._skip_reason.pop(name, None)
            self._dispatched += 1
        self._dispatch_counter.inc(1, {"pool": name})
        return True

    def _cancel_inflight(self, pool_name: str) -> None:
        with self._lock:
            stale = self._inflight.pop(pool_name, None)
        if stale is not None:
            self.guard.cancel(stale.token)

    # --------------------------------------------------------------- commit

    def try_commit(self, pool, queue, state, config,
                   *, launch_filter=None) -> CommitResult:
        """Commit-or-drop the pool's in-flight speculation at cycle N+1
        start.  `queue` is the REAL just-ranked queue; `state` the pool's
        (admission-clamped) match state.  On "hit" the caller finalizes
        `prepared` + `assignment` directly — tensor_build and the solve
        already happened during cycle N's drain."""
        from cook_tpu.scheduler.encode_cache import offers_fingerprint
        from cook_tpu.scheduler.matcher import select_considerable

        name = pool.name
        with self._lock:
            spec = self._inflight.pop(name, None)
            skip = self._skip_reason.pop(name, "")
        if spec is None:
            return CommitResult(status="none", reason=skip)
        if not self.enabled:
            self.guard.cancel(spec.token)
            return self._drop(name, DROP_DISABLED)
        committable, reason = self.guard.finish(spec.token)
        if not committable:
            return self._drop(name, reason)
        if self.encode_cache is not None \
                and self.encode_cache.epoch != spec.encode_epoch:
            return self._drop(name, DROP_EPOCH_STALE)
        if self.device_state is not None \
                and self.device_state.epoch != spec.resident_epoch:
            # the resident mirror was invalidated while this solve was
            # in flight: its tensors were built from dropped state
            return self._drop(name, DROP_EPOCH_STALE)
        # offer STRUCTURE must be unchanged (hosts come and go without
        # store events; spare amounts are covered by the guard — only
        # confirmed completions may have moved them)
        from cook_tpu.cluster.base import safe_pool_offers

        current_offers = []
        for cluster in self.clusters:
            if not cluster.accepts_work:
                continue
            offers = safe_pool_offers(cluster, name)
            for offer in offers or ():
                current_offers.append((cluster, offer))
        if offers_fingerprint(current_offers) != spec.offers_fp:
            return self._drop(name, DROP_OFFERS_CHANGED)
        # the fresh considerable window (real queue, live quota budgets,
        # current admission clamp) must be the speculative one exactly
        fresh = select_considerable(self.store, pool, queue,
                                    state.num_considerable,
                                    launch_filter=launch_filter)
        if [j.uuid for j in fresh] != spec.considerable_uuids:
            return self._drop(name, DROP_QUEUE_SHIFTED)
        try:
            from cook_tpu.obs import data_plane

            # a hit's ONLY transfer this cycle: the pre-solved
            # assignment's D2H fetch (the tensor build ran during the
            # previous cycle's drain) — labeled so hit cycles legibly
            # report near-zero H2D on their records
            with data_plane.family(data_plane.FAM_SOLVE):
                assignment = np.asarray(spec.pending.fetch())
        except Exception:  # noqa: BLE001 — a deferred device error
            # surfaces at the speculative fetch; the cycle solves fresh
            log.exception("speculative solve failed at fetch (pool %s)",
                          name)
            return self._drop(name, DROP_SOLVE_ERROR)
        with self._lock:
            self._hits += 1
        self._hit_counter.inc(1, {"pool": name})
        return CommitResult(status="hit", prepared=spec.prepared,
                            assignment=assignment)

    def _drop(self, pool_name: str, reason: str) -> CommitResult:
        with self._lock:
            self._dropped += 1
            self._drop_reasons[reason] += 1
        self._drop_counter.inc(1, {"pool": pool_name, "reason": reason})
        return CommitResult(status="dropped", reason=reason)

    # ---------------------------------------------------------------- stats

    def stats_json(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "horizon_ms": self.horizon_ms,
                "inflight": sorted(self._inflight),
                "dispatched": self._dispatched,
                "hits": self._hits,
                "dropped": self._dropped,
                "drop_reasons": dict(self._drop_reasons),
            }
