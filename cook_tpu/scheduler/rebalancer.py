"""The rebalancer: periodic DRU-driven preemption.

Reference: /root/reference/scheduler/src/cook/rebalancer.clj — per cycle,
walk the top pending jobs in fairness order; for each, find the preemption
decision (host + prefix of highest-DRU tasks) that frees enough room while
maximizing the minimum preempted DRU, guarded by `safe-dru-threshold` and
`min-dru-diff`; simulate the launch so later decisions see the updated
fairness picture; then transact the preemptions and kill the victims.

The victim search itself is the `ops.rebalance.find_preemption_decision`
kernel (one call scans all tasks x hosts).  This module keeps the
incremental state (`next-state`, rebalancer.clj:270-318) with a fixed-row
layout: every task owns a row in device-resident tensors for the whole
cycle; preemptions flip an eligibility bit, simulated launches fill
preallocated slack rows, and only changed users' DRU rows are rescored and
scattered back (dru.clj:128 `next-task->scored-task`) — so the ≤
max_preemption kernel calls per cycle ship O(changed) bytes, not O(tasks).
"""
from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

import jax.numpy as jnp

from cook_tpu.obs import data_plane
from cook_tpu.models.entities import DruMode, Instance, Job, Pool, Resources
from cook_tpu.models.store import JobStore
from cook_tpu.ops.common import BIG, bucket_size
from cook_tpu.ops.rebalance import (
    RebalanceState,
    decide_from_sorted,
    find_preemption_decision,
    sort_rebalance_state,
)


@dataclass
class RebalancerParams:
    """Runtime-mutable knobs (reference: Datomic-stored `:rebalancer/config`,
    rebalancer.clj:535-557, docs/rebalancer-config.adoc)."""

    safe_dru_threshold: float = 1.0
    min_dru_diff: float = 0.5
    max_preemption: int = 100
    # fast_cycle sorts the task tensors ONCE per cycle and reuses the
    # order for every decision (ops/rebalance.py decide_from_sorted):
    # ~max_preemption x fewer device sorts per cycle.  DRU values stay
    # LIVE (threshold/min-diff/score exact); the approximations are the
    # frozen within-host prefix ORDER and launches consuming spare
    # instead of joining the preemptable rows
    fast_cycle: bool = False
    # serve the cycle-start victim tensors from a device-resident
    # keyed-row mirror (scheduler/device_state.ResidentRows): tasks that
    # survived since the last cycle move zero encode bytes; only new /
    # changed rows scatter.  Config key: [scheduler] resident_rebalancer
    resident: bool = False
    # ---- gang admission (scheduler/gang.py) ----
    # topology-aware whole-gang admission from the rebalance cycle:
    # drain-vs-kill per block, reservations tagged gang:<group>
    gang_enabled: bool = True
    # gangs admitted (drain or preempt) per rebalance cycle
    gang_max_admissions: int = 4
    # preempt-less admission: wait for a block's natural drain only when
    # the predictor expects it free within this budget...
    gang_drain_max_wait_ms: float = 300_000.0
    # ...AND the wait is under factor x the wasted-work seconds the kill
    # alternative would destroy (1.0 = break even: a second of waiting
    # is worth a second of someone else's destroyed runtime)
    gang_drain_wasted_factor: float = 1.0


@dataclass
class Decision:
    job: Job                      # to make room for
    hostname: str
    task_ids: list[str]           # victims (empty = spare-only)
    min_preempted_dru: float
    # per-victim detail for the fairness ledger, captured at decision
    # time (the cycle state mutates as later decisions apply):
    # [{task_id, user, dru, mem, cpus, gpus}]
    victims: list[dict] = field(default_factory=list)


@dataclass
class _UserTasks:
    """One user's running tasks in feature-vector order."""

    keys: list[tuple] = field(default_factory=list)  # sort keys
    ids: list[str] = field(default_factory=list)     # task ids (sim-* = simulated)
    res: list[tuple] = field(default_factory=list)   # (mem, cpus, gpus, disk)
    rows: list[int] = field(default_factory=list)    # fixed tensor rows
    dru: list[float] = field(default_factory=list)


class RebalanceCycle:
    """State for one pool's rebalance cycle (fixed-row tensor layout)."""

    def __init__(
        self,
        store: JobStore,
        pool: Pool,
        host_spare: dict[str, Resources],
        params: RebalancerParams,
        host_info: Optional[dict[str, tuple[dict, str]]] = None,
        resident=None,
    ):
        self.store = store
        self.pool = pool
        self.params = params
        self.host_info = host_info or {}  # hostname -> (attrs, location)
        self.gpu_mode = pool.dru_mode == DruMode.GPU

        # hosts
        self.hostnames = sorted(
            set(host_spare)
            | {
                i.hostname
                for i in store.running_instances(pool.name)
                if i.hostname
            }
        )
        self.host_idx = {h: i for i, h in enumerate(self.hostnames)}
        h = len(self.hostnames)
        # bucket the host axis: an unbucketed H mints a fresh XLA program
        # whenever the host count changes (the compile observatory's
        # op=rebalance storm signature); padded rows are host_ok=False
        # with zero spare, so the kernel can never pick them
        h_pad = bucket_size(max(h, 1))
        spare = np.zeros((h_pad, 4), dtype=np.float32)
        for hostname, res in host_spare.items():
            i = self.host_idx[hostname]
            spare[i] = (res.mem, res.cpus, res.gpus, res.disk)

        # per-user ordered running tasks
        self.users: dict[str, _UserTasks] = {}
        self.task_info: dict[str, tuple[str, str]] = {}  # task id -> (user, host)
        for job in store.running_jobs(pool.name):
            for inst in store.job_instances(job.uuid):
                if inst.status.terminal:
                    continue
                ut = self.users.setdefault(job.user, _UserTasks())
                ut.keys.append(self._task_key(job, inst))
                ut.ids.append(inst.task_id)
                ut.res.append(
                    (job.resources.mem, job.resources.cpus,
                     job.resources.gpus, job.resources.disk)
                )
                self.task_info[inst.task_id] = (job.user, inst.hostname)

        # fixed-row flat layout: all tasks + slack rows for simulated
        # launches, bucketed so a churning running-task count reuses the
        # same compiled program (pad rows: host -1, ineligible — the
        # shape every task on an unknown host already takes)
        n_tasks = sum(len(ut.ids) for ut in self.users.values())
        total = bucket_size(max(n_tasks + params.max_preemption, 1))
        self.row_ids: list[str] = [""] * total
        host_np = np.full(total, -1, np.int32)
        res_np = np.zeros((total, 4), np.float32)
        self._dru_np = np.zeros(total, np.float32)
        self._elig_np = np.zeros(total, bool)
        row = 0
        for user in sorted(self.users):
            ut = self.users[user]
            order = sorted(range(len(ut.keys)), key=lambda i: ut.keys[i])
            ut.keys = [ut.keys[i] for i in order]
            ut.ids = [ut.ids[i] for i in order]
            ut.res = [ut.res[i] for i in order]
            ut.rows = list(range(row, row + len(ut.ids)))
            for k, tid in enumerate(ut.ids):
                self.row_ids[row] = tid
                host = self.task_info[tid][1]
                hidx = self.host_idx.get(host, -1)
                host_np[row] = hidx
                res_np[row] = ut.res[k]
                self._elig_np[row] = hidx >= 0
                row += 1
            self._rescore(user)
        self._next_slack = n_tasks

        # device-resident tensors; per-iteration updates are small scatters
        if resident is not None and params.resident:
            # keyed-row mirror: one row per RUNNING task keyed by task
            # id, gathered into this cycle's row order on device — a
            # task that survived since the last cycle ships zero encode
            # bytes.  Slack rows beyond n_tasks gather the all-zero pad
            # row, so host encodes value+1 (pad's 0 decodes to the -1
            # "unknown host" sentinel the slack rows need).
            keys = self.row_ids[:n_tasks]
            cols, _stats = resident.build(
                keys,
                {
                    "host1": (host_np[:n_tasks] + 1).astype(np.int32),
                    "res": res_np[:n_tasks],
                    "dru": self._dru_np[:n_tasks],
                    "elig": self._elig_np[:n_tasks],
                },
                out_len=total,
            )
            self._dev_host = cols["host1"] - 1
            self._dev_res = cols["res"]
            self._dev_dru = cols["dru"]
            self._dev_elig = cols["elig"]
            self._dev_spare = resident.whole_array("spare", spare)
            self._dev_host_ok = resident.whole_array(
                "host_ok", np.arange(len(spare)) < h)
        else:
            # classic full upload, ledger-accounted under the same
            # family so cold-vs-warm encode bytes compare honestly
            with data_plane.family(data_plane.FAM_REBALANCE):
                self._dev_host = data_plane.h2d(host_np)
                self._dev_res = data_plane.h2d(res_np)
                self._dev_dru = data_plane.h2d(self._dru_np)
                self._dev_elig = data_plane.h2d(self._elig_np)
                self._dev_spare = data_plane.h2d(spare)
                self._dev_host_ok = data_plane.h2d(
                    np.arange(len(spare)) < h)
        self._spare_np = spare.copy()
        self.preempted: set[str] = set()
        self._sorted = None
        self._perm_np = None
        if params.fast_cycle:
            # ONE sort for the whole cycle; decisions reuse the order
            self._sorted = sort_rebalance_state(
                self._dev_host, self._dev_dru, self._dev_res,
                self._dev_elig)
            self._perm_np = np.asarray(self._sorted.perm)

    # ------------------------------------------------------------ internals

    @staticmethod
    def _task_key(job: Job, inst: Optional[Instance]) -> tuple:
        start = inst.start_time_ms if inst is not None else 2**62
        tid = inst.task_id if inst is not None else "￿"
        return (-job.priority, start, tid)

    def _divisors(self, user: str) -> tuple[float, float, float]:
        share = self.store.get_share(user, self.pool.name)
        return (min(share.mem, BIG), min(share.cpus, BIG), min(share.gpus, BIG))

    def _rescore(self, user: str) -> list[int]:
        """Recompute the user's cumulative DRUs into the flat dru column
        (only-changed-users rescore); returns the touched rows."""
        ut = self.users.get(user)
        if ut is None:
            return []
        md, cd, gd = self._divisors(user)
        cum_m = cum_c = cum_g = 0.0
        ut.dru = []
        for k, (mem, cpus, gpus, *_rest) in enumerate(ut.res):
            cum_m += mem
            cum_c += cpus
            cum_g += gpus
            value = (cum_g / gd if self.gpu_mode
                     else max(cum_m / md, cum_c / cd))
            ut.dru.append(value)
            self._dru_np[ut.rows[k]] = value
        return list(ut.rows)

    def _device_state(self) -> RebalanceState:
        return RebalanceState(
            task_host=self._dev_host,
            task_dru=self._dev_dru,
            task_res=self._dev_res,
            task_eligible=self._dev_elig,
            spare=self._dev_spare,
            host_ok=self._dev_host_ok,
        )

    def pending_job_dru(self, job: Job) -> float:
        """compute-pending-default-job-dru / -gpu (rebalancer.clj:157-205):
        the user's nearest running task's dru + the job's own share."""
        md, cd, gd = self._divisors(job.user)
        ut = self.users.get(job.user)
        nearest = 0.0
        if ut is not None and ut.ids:
            key = self._task_key(job, None)
            pos = bisect.bisect_right(ut.keys, key)
            if pos > 0:
                nearest = ut.dru[pos - 1]
        r = job.resources
        if self.gpu_mode:
            return nearest + r.gpus / gd
        return max(nearest + r.mem / md, nearest + r.cpus / cd)

    def user_below_quota(self, job: Job) -> bool:
        """job-below-quota (rebalancer.clj:212-222): would launching exceed
        the user's quota?"""
        quota = self.store.get_quota(job.user, self.pool.name)
        ut = self.users.get(job.user)
        mem = cpus = gpus = 0.0
        count = 0
        if ut is not None:
            for k in range(len(ut.ids)):
                mem += ut.res[k][0]
                cpus += ut.res[k][1]
                gpus += ut.res[k][2]
                count += 1
        r = job.resources
        return (
            mem + r.mem <= quota.resources.mem
            and cpus + r.cpus <= quota.resources.cpus
            and gpus + r.gpus <= quota.resources.gpus
            and count + 1 <= quota.count
        )

    # ----------------------------------------------------------- main loop

    def _host_ok_for(self, job: Job) -> Optional[np.ndarray]:
        """Per-host constraint pass for the pending job (reference:
        make-rebalancer-job-constraints, constraints.clj:504): novel-host,
        user attribute EQUALS, checkpoint locality."""
        failed_hosts = {
            inst.hostname
            for inst in self.store.job_instances(job.uuid)
            if inst.status.terminal and inst.hostname
        }
        need_attrs = {c.attribute: c.pattern for c in job.constraints}
        need_location = (job.checkpoint.location
                         if job.checkpoint is not None else "")
        if not failed_hosts and not need_attrs and not need_location:
            return None
        # padded host rows stay False (matching _dev_host_ok)
        ok = np.zeros(len(self._spare_np), dtype=bool)
        ok[:len(self.hostnames)] = True
        for i, hostname in enumerate(self.hostnames):
            if hostname in failed_hosts:
                ok[i] = False
                continue
            attrs, location = self.host_info.get(hostname, ({}, ""))
            if need_location and location != need_location:
                ok[i] = False
                continue
            for attr, want in need_attrs.items():
                if attrs.get(attr) != want:
                    ok[i] = False
                    break
        return ok

    def compute_decision(self, job: Job) -> Optional[Decision]:
        if self.params.fast_cycle:
            return self._compute_decision_fast(job)
        state = self._device_state()
        host_ok = self._host_ok_for(job)
        if host_ok is not None:
            state = state._replace(host_ok=jnp.asarray(host_ok))
        pending_dru = self.pending_job_dru(job)
        if not self.user_below_quota(job):
            # over-quota users may only preempt their own tasks
            # (rebalancer.clj:339-346)
            ut = self.users.get(job.user)
            own_rows = np.asarray(ut.rows if ut else [], dtype=np.int32)
            allowed = (
                jnp.zeros(state.task_eligible.shape[0], bool)
                .at[jnp.asarray(own_rows)].set(True)
            )
            state = state._replace(
                task_eligible=state.task_eligible & allowed
            )
        r = job.resources
        decision = find_preemption_decision(
            state,
            jnp.asarray([r.mem, r.cpus, r.gpus, r.disk], dtype=jnp.float32),
            jnp.float32(pending_dru),
            jnp.float32(self.params.safe_dru_threshold),
            jnp.float32(self.params.min_dru_diff),
        )
        host = int(decision.host)
        if host < 0:
            return None
        mask = np.asarray(decision.preempt_mask)
        task_ids = [self.row_ids[i] for i in np.where(mask)[0]]
        victims = self._victim_details(task_ids)
        self._apply(job, host, task_ids, np.asarray(decision.freed))
        return Decision(
            job=job,
            hostname=self.hostnames[host],
            task_ids=task_ids,
            min_preempted_dru=float(decision.score),
            victims=victims,
        )

    def _victim_details(self, task_ids: list[str]) -> list[dict]:
        """Per-victim (user, DRU-at-decision, resources) for the fairness
        ledger.  Must run BEFORE _apply: applying the decision deletes
        the victims' entries from the per-user task lists."""
        out = []
        for tid in task_ids:
            user, _ = self.task_info[tid]
            ut = self.users[user]
            k = ut.ids.index(tid)
            mem, cpus, gpus, _disk = ut.res[k]
            out.append({
                "task_id": tid,
                "user": user,
                "dru": round(float(ut.dru[k]), 6),
                "mem": float(mem),
                "cpus": float(cpus),
                "gpus": float(gpus),
            })
        return out

    def _compute_decision_fast(self, job: Job) -> Optional[Decision]:
        """Decision against the cycle-start sort (RebalancerParams
        .fast_cycle): per-decision validity is a host-side [T] mask
        gathered into sorted space — no device sort per decision."""
        host_ok = self._host_ok_for(job)
        host_ok_dev = (jnp.asarray(host_ok) if host_ok is not None
                       else self._dev_host_ok)
        pending_dru = self.pending_job_dru(job)
        row_ok = self._elig_np
        if not self.user_below_quota(job):
            ut = self.users.get(job.user)
            own = np.zeros(len(self._elig_np), dtype=bool)
            if ut:
                own[np.asarray(ut.rows, dtype=np.int64)] = True
            row_ok = row_ok & own
        r = job.resources
        decision = decide_from_sorted(
            self._sorted,
            jnp.asarray(row_ok[self._perm_np]),
            jnp.asarray(self._dru_np[self._perm_np]),
            jnp.asarray(self._spare_np),
            host_ok_dev,
            jnp.asarray([r.mem, r.cpus, r.gpus, r.disk], dtype=jnp.float32),
            jnp.float32(pending_dru),
            jnp.float32(self.params.safe_dru_threshold),
            jnp.float32(self.params.min_dru_diff),
        )
        host = int(decision.host)
        if host < 0:
            return None
        mask_sorted = np.asarray(decision.preempt_mask)
        rows = self._perm_np[np.where(mask_sorted)[0]]
        task_ids = [self.row_ids[i] for i in rows]
        victims = self._victim_details(task_ids)
        self._apply(job, host, task_ids, np.asarray(decision.freed))
        return Decision(
            job=job,
            hostname=self.hostnames[host],
            task_ids=task_ids,
            min_preempted_dru=float(decision.score),
            victims=victims,
        )

    def _apply(self, job: Job, host: int, task_ids: list[str],
               freed: np.ndarray) -> None:
        """next-state (rebalancer.clj:270-318): remove victims, add the
        simulated launch, rescore changed users, update host spare —
        all as small scatters into the device-resident tensors."""
        changed = {job.user}
        dead_rows = []
        for tid in task_ids:
            self.preempted.add(tid)
            user, _ = self.task_info[tid]
            ut = self.users[user]
            k = ut.ids.index(tid)
            dead_rows.append(ut.rows[k])
            del ut.keys[k], ut.ids[k], ut.res[k], ut.rows[k]
            changed.add(user)
        # simulated launch of the pending job on the chosen host: it joins
        # the fairness state (and may itself be preempted by later
        # decisions), living in a preallocated slack row
        ut = self.users.setdefault(job.user, _UserTasks())
        key = self._task_key(job, None)
        pos = bisect.bisect_right(ut.keys, key)
        sim_id = f"sim-{job.uuid}"
        sim_row = self._next_slack
        self._next_slack += 1
        res = (job.resources.mem, job.resources.cpus,
               job.resources.gpus, job.resources.disk)
        ut.keys.insert(pos, key)
        ut.ids.insert(pos, sim_id)
        ut.res.insert(pos, res)
        ut.rows.insert(pos, sim_row)
        self.row_ids[sim_row] = sim_id
        self.task_info[sim_id] = (job.user, self.hostnames[host])

        touched = []
        for user in changed:
            touched.extend(self._rescore(user))
        for row in dead_rows:
            self._elig_np[row] = False
        # in fast_cycle the sim row is outside the cycle-start sort (its
        # sorted position sits in the sentinel segment, which the decide
        # kernel excludes); host-side bookkeeping above still counts it
        # for quota/pending-dru purposes
        self._elig_np[sim_row] = not self.params.fast_cycle

        r = job.resources
        new_spare = np.maximum(
            freed - np.array([r.mem, r.cpus, r.gpus, r.disk]), 0.0
        ).astype(np.float32)
        self._spare_np[host] = new_spare
        if self.params.fast_cycle:
            return
        # device scatters: O(changed rows)
        rows = np.asarray(sorted(set(touched + dead_rows + [sim_row])),
                          dtype=np.int32)
        dev_rows = jnp.asarray(rows)
        self._dev_dru = self._dev_dru.at[dev_rows].set(
            jnp.asarray(self._dru_np[rows]))
        self._dev_elig = self._dev_elig.at[dev_rows].set(
            jnp.asarray(self._elig_np[rows]))
        self._dev_host = self._dev_host.at[sim_row].set(host)
        self._dev_res = self._dev_res.at[sim_row].set(
            jnp.asarray(np.asarray(res, np.float32)))
        self._dev_spare = self._dev_spare.at[host].set(jnp.asarray(new_spare))


def rebalance_pool(
    store: JobStore,
    pool: Pool,
    pending_in_dru_order: Sequence[Job],
    host_spare: dict[str, Resources],
    params: RebalancerParams,
    host_info: Optional[dict] = None,
    telemetry=None,
    reclaimer=None,
    resident=None,
) -> list[Decision]:
    """One pool's rebalance cycle: returns the preemption decisions
    (rebalancer.clj:434-479 `rebalance`).  The caller transacts + kills.

    `reclaimer` is the elastic capacity plane's pre-preemption hook
    (cook_tpu/elastic/planner.py reclaim_for): when the pool has
    capacity on loan and its pending demand exceeds spare, loaned
    capacity is reclaimed — durably, non-disruptively — and the victim
    search below runs against the REFRESHED spare map, so returned
    capacity yields spare-only decisions (no victims) instead of
    kills.

    `resident` is an optional `device_state.ResidentRows` mirror owned
    by the caller (it must OUTLIVE the cycle — warm reuse is the whole
    point); it serves the cycle-start victim tensors when
    `params.resident` is set."""
    if reclaimer is not None:
        refreshed = reclaimer(pool.name, pending_in_dru_order, host_spare)
        if refreshed is not None:
            host_spare = refreshed
    cycle = RebalanceCycle(store, pool, host_spare, params,
                           host_info=host_info, resident=resident)
    solve_shape = (int(cycle._dev_host.shape[0]),
                   int(cycle._dev_spare.shape[0]))
    decisions = []
    for job in list(pending_in_dru_order)[: params.max_preemption]:
        if telemetry is not None:
            # one observation per compute_decision = per kernel dispatch
            # (an idle pool dispatches nothing and must report nothing);
            # the victim-search kernel compiles per (task rows, hosts)
            # bucket; fast_cycle swaps in the sort-once kernel pair (own
            # programs).  No pool= arg: the per-pool last-solve snapshot
            # tracks the MATCH solve (the /unscheduled_jobs correlation)
            telemetry.record_solve(
                "rebalance", solve_shape,
                "fast_cycle" if params.fast_cycle else "exact")
        decision = cycle.compute_decision(job)
        if decision is not None and decision.task_ids:
            decisions.append(decision)
    return decisions
