"""The rebalancer: periodic DRU-driven preemption.

Reference: /root/reference/scheduler/src/cook/rebalancer.clj — per cycle,
walk the top pending jobs in fairness order; for each, find the preemption
decision (host + prefix of highest-DRU tasks) that frees enough room while
maximizing the minimum preempted DRU, guarded by `safe-dru-threshold` and
`min-dru-diff`; simulate the launch so later decisions see the updated
fairness picture; then transact the preemptions and kill the victims.

The victim search itself is the `ops.rebalance.find_preemption_decision`
kernel (one call scans all tasks x hosts); this module keeps the host-side
incremental state (`next-state`, rebalancer.clj:270-318): preempted tasks
drop out, the simulated launch joins the user's task list, and only changed
users are re-scored (dru.clj:128 `next-task->scored-task`).
"""
from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

import jax.numpy as jnp

from cook_tpu.models.entities import DruMode, Instance, Job, Pool, Resources
from cook_tpu.models.store import JobStore
from cook_tpu.ops.common import BIG
from cook_tpu.ops.rebalance import RebalanceState, find_preemption_decision


@dataclass
class RebalancerParams:
    """Runtime-mutable knobs (reference: Datomic-stored `:rebalancer/config`,
    rebalancer.clj:535-557, docs/rebalancer-config.adoc)."""

    safe_dru_threshold: float = 1.0
    min_dru_diff: float = 0.5
    max_preemption: int = 100


@dataclass
class Decision:
    job: Job                      # to make room for
    hostname: str
    task_ids: list[str]           # victims (empty = spare-only)
    min_preempted_dru: float


@dataclass
class _UserTasks:
    """One user's running tasks in feature-vector order."""

    keys: list[tuple] = field(default_factory=list)      # sort keys
    ids: list[str] = field(default_factory=list)         # task ids ("" = simulated)
    res: list[tuple] = field(default_factory=list)       # (mem, cpus, gpus)
    dru: list[float] = field(default_factory=list)


class RebalanceCycle:
    """Host-side state for one pool's rebalance cycle."""

    def __init__(
        self,
        store: JobStore,
        pool: Pool,
        host_spare: dict[str, Resources],
        params: RebalancerParams,
    ):
        self.store = store
        self.pool = pool
        self.params = params
        self.gpu_mode = pool.dru_mode == DruMode.GPU

        # hosts
        self.hostnames = sorted(
            set(host_spare)
            | {
                i.hostname
                for i in store.running_instances(pool.name)
                if i.hostname
            }
        )
        self.host_idx = {h: i for i, h in enumerate(self.hostnames)}
        h = len(self.hostnames)
        self.spare = np.zeros((max(h, 1), 4), dtype=np.float64)
        for hostname, res in host_spare.items():
            i = self.host_idx[hostname]
            self.spare[i] = (res.mem, res.cpus, res.gpus, res.disk)

        # per-user ordered running tasks
        self.users: dict[str, _UserTasks] = {}
        self.task_info: dict[str, tuple[str, str]] = {}  # task id -> (user, host)
        for job in store.running_jobs(pool.name):
            for inst in store.job_instances(job.uuid):
                if inst.status.terminal:
                    continue
                ut = self.users.setdefault(job.user, _UserTasks())
                ut.keys.append(self._task_key(job, inst))
                ut.ids.append(inst.task_id)
                ut.res.append(
                    (job.resources.mem, job.resources.cpus,
                     job.resources.gpus, job.resources.disk)
                )
                self.task_info[inst.task_id] = (job.user, inst.hostname)
        for user, ut in self.users.items():
            order = sorted(range(len(ut.keys)), key=lambda i: ut.keys[i])
            ut.keys = [ut.keys[i] for i in order]
            ut.ids = [ut.ids[i] for i in order]
            ut.res = [ut.res[i] for i in order]
            self._rescore(user)
        self.preempted: set[str] = set()

    # ------------------------------------------------------------ internals

    @staticmethod
    def _task_key(job: Job, inst: Optional[Instance]) -> tuple:
        start = inst.start_time_ms if inst is not None else 2**62
        tid = inst.task_id if inst is not None else "￿"
        return (-job.priority, start, tid)

    def _divisors(self, user: str) -> tuple[float, float, float]:
        share = self.store.get_share(user, self.pool.name)
        return (min(share.mem, BIG), min(share.cpus, BIG), min(share.gpus, BIG))

    def _rescore(self, user: str) -> None:
        """Recompute the user's cumulative DRUs (only-changed-users rescore)."""
        ut = self.users.get(user)
        if ut is None:
            return
        md, cd, gd = self._divisors(user)
        cum_m = cum_c = cum_g = 0.0
        ut.dru = []
        for mem, cpus, gpus, *_ in ut.res:
            cum_m += mem
            cum_c += cpus
            cum_g += gpus
            if self.gpu_mode:
                ut.dru.append(cum_g / gd)
            else:
                ut.dru.append(max(cum_m / md, cum_c / cd))

    def _flat_state(self) -> tuple[RebalanceState, list[str]]:
        """Flatten per-user state into kernel tensors."""
        ids, hosts, drus, res, elig = [], [], [], [], []
        for user, ut in sorted(self.users.items()):
            for k, tid in enumerate(ut.ids):
                if tid in self.preempted:
                    continue
                host = self.task_info.get(tid, (user, ""))[1] if tid else ""
                ids.append(tid)
                hosts.append(self.host_idx.get(host, -1))
                drus.append(ut.dru[k])
                res.append(ut.res[k])
                elig.append(bool(tid) and host in self.host_idx)
        t = max(len(ids), 1)
        task_host = np.full(t, -1, dtype=np.int32)
        task_dru = np.zeros(t, dtype=np.float32)
        task_res = np.zeros((t, 4), dtype=np.float32)
        task_elig = np.zeros(t, dtype=bool)
        for i in range(len(ids)):
            task_host[i] = hosts[i]
            task_dru[i] = drus[i]
            task_res[i] = res[i]
            task_elig[i] = elig[i]
        state = RebalanceState(
            task_host=jnp.asarray(task_host),
            task_dru=jnp.asarray(task_dru),
            task_res=jnp.asarray(task_res),
            task_eligible=jnp.asarray(task_elig),
            spare=jnp.asarray(self.spare.astype(np.float32)),
            host_ok=jnp.ones(len(self.spare), dtype=bool),
        )
        return state, ids

    def pending_job_dru(self, job: Job) -> float:
        """compute-pending-default-job-dru / -gpu (rebalancer.clj:157-205):
        the user's nearest running task's dru + the job's own share."""
        md, cd, gd = self._divisors(job.user)
        ut = self.users.get(job.user)
        nearest = 0.0
        if ut is not None and ut.ids:
            key = self._task_key(job, None)
            pos = bisect.bisect_right(ut.keys, key)
            if pos > 0:
                nearest = ut.dru[pos - 1]
        r = job.resources
        if self.gpu_mode:
            return nearest + r.gpus / gd
        return max(nearest + r.mem / md, nearest + r.cpus / cd)

    def user_below_quota(self, job: Job) -> bool:
        """job-below-quota (rebalancer.clj:212-222): would launching exceed
        the user's quota?"""
        quota = self.store.get_quota(job.user, self.pool.name)
        ut = self.users.get(job.user)
        mem = cpus = gpus = 0.0
        count = 0
        if ut is not None:
            for k, tid in enumerate(ut.ids):
                if tid in self.preempted:
                    continue
                mem += ut.res[k][0]
                cpus += ut.res[k][1]
                gpus += ut.res[k][2]
                count += 1
        r = job.resources
        return (
            mem + r.mem <= quota.resources.mem
            and cpus + r.cpus <= quota.resources.cpus
            and gpus + r.gpus <= quota.resources.gpus
            and count + 1 <= quota.count
        )

    # ----------------------------------------------------------- main loop

    def compute_decision(self, job: Job) -> Optional[Decision]:
        state, ids = self._flat_state()
        pending_dru = self.pending_job_dru(job)
        below_quota = self.user_below_quota(job)
        if not below_quota:
            # over-quota users may only preempt their own tasks
            # (rebalancer.clj:339-346)
            own = set()
            ut = self.users.get(job.user)
            if ut is not None:
                own = {tid for tid in ut.ids if tid}
            elig = np.array([tid in own for tid in ids], dtype=bool)
            if len(elig) < state.task_eligible.shape[0]:
                elig = np.pad(elig, (0, state.task_eligible.shape[0] - len(elig)))
            state = state._replace(
                task_eligible=jnp.asarray(elig) & state.task_eligible
            )
        r = job.resources
        decision = find_preemption_decision(
            state,
            jnp.asarray([r.mem, r.cpus, r.gpus, r.disk], dtype=jnp.float32),
            jnp.float32(pending_dru),
            jnp.float32(self.params.safe_dru_threshold),
            jnp.float32(self.params.min_dru_diff),
        )
        host = int(decision.host)
        if host < 0:
            return None
        mask = np.asarray(decision.preempt_mask)
        task_ids = [ids[i] for i in np.where(mask[: len(ids)])[0]]
        self._apply(job, host, task_ids, np.asarray(decision.freed))
        return Decision(
            job=job,
            hostname=self.hostnames[host],
            task_ids=task_ids,
            min_preempted_dru=float(decision.score),
        )

    def _apply(self, job: Job, host: int, task_ids: list[str],
               freed: np.ndarray) -> None:
        """next-state (rebalancer.clj:270-318): remove victims, add the
        simulated launch, rescore changed users, update host spare."""
        changed = {job.user}
        for tid in task_ids:
            self.preempted.add(tid)
            user, _ = self.task_info[tid]
            ut = self.users[user]
            k = ut.ids.index(tid)
            del ut.keys[k], ut.ids[k], ut.res[k]
            changed.add(user)
        # simulated launch of the pending job on the chosen host
        ut = self.users.setdefault(job.user, _UserTasks())
        key = self._task_key(job, None)
        pos = bisect.bisect_right(ut.keys, key)
        sim_id = f"sim-{job.uuid}"
        ut.keys.insert(pos, key)
        ut.ids.insert(pos, sim_id)
        ut.res.insert(pos, (job.resources.mem, job.resources.cpus,
                            job.resources.gpus, job.resources.disk))
        self.task_info[sim_id] = (job.user, self.hostnames[host])
        for user in changed:
            self._rescore(user)
        r = job.resources
        self.spare[host] = np.maximum(
            freed - np.array([r.mem, r.cpus, r.gpus, r.disk]), 0.0
        )


def rebalance_pool(
    store: JobStore,
    pool: Pool,
    pending_in_dru_order: Sequence[Job],
    host_spare: dict[str, Resources],
    params: RebalancerParams,
) -> list[Decision]:
    """One pool's rebalance cycle: returns the preemption decisions
    (rebalancer.clj:434-479 `rebalance`).  The caller transacts + kills."""
    cycle = RebalanceCycle(store, pool, host_spare, params)
    decisions = []
    for job in list(pending_in_dru_order)[: params.max_preemption]:
        decision = cycle.compute_decision(job)
        if decision is not None and decision.task_ids:
            decisions.append(decision)
    return decisions
