"""Plugin hook points.

Reference: cook.plugins (/root/reference/scheduler/src/cook/plugins/
definitions.clj:18-70 + submission.clj/launch.clj caching wrappers).  The
same seven extension seams, as Python protocols resolved from dotted paths
(the analog of `lazy-load-var`), with the submission/launch results cached
for a TTL like the reference's caching wrappers.
"""
from __future__ import annotations

import importlib
import logging
from dataclasses import dataclass, field
from typing import Any, Protocol, runtime_checkable

from cook_tpu.models.entities import Job
from cook_tpu.utils.incremental import entity_fraction


@dataclass(frozen=True)
class PluginResult:
    accepted: bool
    message: str = ""
    # for launch filters: suppress retries until this time
    cache_expires_ms: int = 0


ACCEPT = PluginResult(accepted=True)


@runtime_checkable
class JobSubmissionValidator(Protocol):
    def check_job_submission(self, job_spec: dict, user: str, pool: str
                             ) -> PluginResult: ...


@runtime_checkable
class JobSubmissionModifier(Protocol):
    def modify_job(self, job_spec: dict, user: str, pool: str) -> dict: ...


@runtime_checkable
class JobLaunchFilter(Protocol):
    def check_job_launch(self, job: Job) -> PluginResult: ...


@runtime_checkable
class InstanceCompletionHandler(Protocol):
    def on_instance_completion(self, job: Job, instance) -> None: ...


@runtime_checkable
class PoolSelector(Protocol):
    def select_pool(self, job_spec: dict, default_pool: str) -> str: ...


@runtime_checkable
class JobAdjuster(Protocol):
    def adjust_job(self, job: Job) -> Job: ...


@runtime_checkable
class JobRouter(Protocol):
    def route_pool(self, job_spec: dict) -> str: ...


@runtime_checkable
class FileUrlGenerator(Protocol):
    """Generates the sandbox-file URL surfaced to clients for one
    instance (reference: FileUrlGenerator, plugins/definitions.clj:56 —
    deployments front sandbox access with their own file service)."""

    def file_url(self, instance) -> str: ...


class AttributePoolSelector:
    """Default pool selection: an explicit `pool` field, else the default
    (reference plugins/pool.clj attribute-pool-selector)."""

    def select_pool(self, job_spec: dict, default_pool: str) -> str:
        return job_spec.get("pool") or default_pool


class PoolMoverAdjuster:
    """Percentage-rollout migration of a user's jobs between pools at
    submission (reference plugins/pool_mover.clj): config maps a
    submission pool to `{"destination_pool": ..., "users": {user:
    {"portion": 0..1}}}`; a job moves when its uuid's stable hash bucket
    (mod 100) falls under portion*100 — the same jobs move on every
    resubmission, giving a deterministic gradual rollout."""

    def __init__(self, config: dict):
        self.config = dict(config or {})

    def adjust_job(self, job: Job) -> Job:
        rule = self.config.get(job.pool)
        if not rule:
            return job
        portion = (rule.get("users", {}).get(job.user) or {}).get("portion")
        destination = rule.get("destination_pool")
        if not isinstance(portion, (int, float)) or not destination:
            return job
        # stable uuid-hash rollout (pool_mover.clj: (mod (hash uuid) 100)),
        # via the same bucketing idiom as incremental config rollouts
        if entity_fraction(job.uuid) < portion:
            return job.with_(pool=destination)
        return job


def load_plugin(dotted_path: str) -> Any:
    """`lazy-load-var` analog: 'package.module:ClassName' or
    'package.module.factory_fn'."""
    if ":" in dotted_path:
        mod_name, attr = dotted_path.split(":", 1)
    else:
        mod_name, _, attr = dotted_path.rpartition(".")
    module = importlib.import_module(mod_name)
    obj = getattr(module, attr)
    return obj() if isinstance(obj, type) else obj


@dataclass
class PluginRegistry:
    submission_validators: list = field(default_factory=list)
    submission_modifiers: list = field(default_factory=list)
    launch_filters: list = field(default_factory=list)
    completion_handlers: list = field(default_factory=list)
    pool_selector: Any = field(default_factory=AttributePoolSelector)
    job_adjusters: list = field(default_factory=list)
    job_routers: list = field(default_factory=list)
    # None = the backend's own sandbox URL (retrieve_sandbox_url_path)
    file_url_generator: Any = None

    def sandbox_url(self, instance, default_fn) -> str:
        """Sandbox file URL for an instance: the FileUrlGenerator plugin
        when configured, else the backend default."""
        if self.file_url_generator is not None:
            return self.file_url_generator.file_url(instance)
        return default_fn()

    def validate_submission(self, job_spec: dict, user: str, pool: str
                            ) -> PluginResult:
        for validator in self.submission_validators:
            result = validator.check_job_submission(job_spec, user, pool)
            if not result.accepted:
                return result
        return ACCEPT

    def modify_submission(self, job_spec: dict, user: str, pool: str) -> dict:
        for modifier in self.submission_modifiers:
            job_spec = modifier.modify_job(job_spec, user, pool)
        return job_spec

    def check_launch(self, job: Job, now_ms: int,
                     cache: dict[str, tuple[int, PluginResult]]) -> bool:
        """Launch-filter with TTL cache (reference plugins/launch.clj)."""
        cached = cache.get(job.uuid)
        if cached is not None and cached[0] > now_ms:
            return cached[1].accepted
        for plugin in self.launch_filters:
            result = plugin.check_job_launch(job)
            if not result.accepted:
                expires = result.cache_expires_ms or (now_ms + 60_000)
                cache[job.uuid] = (expires, result)
                return False
        cache[job.uuid] = (now_ms + 60_000, ACCEPT)
        return True

    def on_completion(self, job: Job, instance) -> None:
        for handler in self.completion_handlers:
            handler.on_instance_completion(job, instance)

    def adjust(self, job: Job) -> Job:
        """Run JobAdjusters over a parsed job at submission.  A failing
        adjuster is skipped and the job passes through unchanged, like
        the reference's catch-and-keep (pool_mover.clj error path)."""
        for adjuster in self.job_adjusters:
            try:
                job = adjuster.adjust_job(job)
            except Exception:  # noqa: BLE001 — plugin faults never block
                logging.getLogger(__name__).exception(
                    "job adjuster %r failed; keeping job unchanged",
                    adjuster)
        return job


def registry_from_config(conf: dict) -> "PluginRegistry":
    """Build the registry from the `plugins` config section: dotted paths
    per seam (the reference's lazy-load-var wiring, components.clj) plus
    the built-in pool-mover rule table.

        {"submission_validators": ["pkg.mod:Cls", ...],
         "submission_modifiers": [...], "launch_filters": [...],
         "completion_handlers": [...], "job_adjusters": [...],
         "job_routers": [...], "pool_selector": "pkg.mod:Cls",
         "file_url_generator": "pkg.mod:Cls",
         "pool_mover": {submission_pool: {"destination_pool": ...,
                        "users": {user: {"portion": 0.25}}}}}
    """
    conf = conf or {}
    registry = PluginRegistry()
    for seam in ("submission_validators", "submission_modifiers",
                 "launch_filters", "completion_handlers",
                 "job_adjusters", "job_routers"):
        for path in conf.get(seam, []):
            getattr(registry, seam).append(load_plugin(path))
    if conf.get("pool_selector"):
        registry.pool_selector = load_plugin(conf["pool_selector"])
    if conf.get("file_url_generator"):
        registry.file_url_generator = load_plugin(conf["file_url_generator"])
    if conf.get("pool_mover"):
        registry.job_adjusters.append(PoolMoverAdjuster(conf["pool_mover"]))
    return registry
