"""Topology-aware gang admission: preempt-less drain vs. contiguous kill.

The matcher's gang chokepoint (matcher.py / ops/gang.py) makes gang
placement all-or-nothing, but it can only SAY no — when every topology
block is fragmented, a waiting gang sits at `gang-incomplete` forever
while scalar jobs keep back-filling the very hosts it needs.  This
planner closes the loop from the rebalancer side.  Per cycle it walks
the waiting gangs in queue order and, for each, evaluates every topology
block (the same contiguous host ranges the hierarchical matcher solves):

  * **free** hosts — spare already fits one member;
  * **draining** hosts — busy, but PR 10's runtime predictor
    (`QuantileRuntimePredictor.predict_runtime_ms`) expects every task on
    them to complete within `gang_drain_max_wait_ms`;
  * **kill** hosts — busy, freed only by preempting, costing the victims'
    elapsed runtime as wasted work.

If a block's natural drain beats killing — predicted wait under the knob
AND under `gang_drain_wasted_factor` x the wasted-work the kill option
would destroy — the planner chooses PREEMPT-LESS admission: it reserves
the free+draining hosts for the gang (`host_reservations` with a
`gang:<group>` tag every member can claim) and kills nobody; the block
drains into the reservation and the next match places the gang whole.
Otherwise it picks the victim set with the least wasted work INSIDE ONE
BLOCK (contiguous freed capacity, not scattered singles) and the caller
transacts the kills.  Either way the freed/freeing hosts are reserved so
scalar jobs cannot re-fragment the block before the gang lands.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence

from cook_tpu.models.entities import Job, Pool, Resources
from cook_tpu.models.store import JobStore

# host_reservations value prefix: a reservation any member of the gang's
# group may claim (matcher feasibility + core release logic understand it)
GANG_RESERVATION_PREFIX = "gang:"


def gang_reservation_tag(group_uuid: str) -> str:
    return GANG_RESERVATION_PREFIX + group_uuid


@dataclass
class GangAdmission:
    """One gang's admission decision for this rebalance cycle."""

    group_uuid: str
    gang_size: int
    leader_uuid: str                  # first member (queue order)
    mode: str                         # "drain" | "preempt"
    block: int                        # block index in the sorted host list
    hosts: list = field(default_factory=list)    # hosts to reserve
    victims: list = field(default_factory=list)  # task ids (preempt mode)
    predicted_wait_ms: float = 0.0    # drain: predicted block-free time
    victim_wasted_s: float = 0.0      # preempt: runtime the kills destroy
    detail: str = ""

    def to_json(self) -> dict:
        return {
            "group": self.group_uuid,
            "gang_size": self.gang_size,
            "mode": self.mode,
            "block": self.block,
            "hosts": list(self.hosts),
            "victims": list(self.victims),
            "predicted_wait_ms": round(self.predicted_wait_ms, 1),
            "victim_wasted_s": round(self.victim_wasted_s, 3),
            "detail": self.detail,
        }


def waiting_gangs(jobs: Sequence[Job]) -> list[tuple[str, list[Job]]]:
    """Whole gangs in the waiting queue, queue order: (group, members)
    for groups whose full complement of `gang_size` members is present.
    A partial complement is not admissible (members-missing) and is left
    to the submit path / next cycles, not to preemption."""
    members: dict[str, list[Job]] = {}
    order: list[str] = []
    for job in jobs:
        if job.gang_size >= 2 and job.group_uuid:
            if job.group_uuid not in members:
                order.append(job.group_uuid)
            members.setdefault(job.group_uuid, []).append(job)
    out = []
    for group in order:
        jobs_g = members[group]
        need = max(j.gang_size for j in jobs_g)
        if len(jobs_g) >= need:
            out.append((group, jobs_g))
    return out


@dataclass
class _HostState:
    hostname: str
    free: bool
    # drain ETA for busy hosts: max predicted-remaining ms across its
    # tasks (inf when any task has no prediction)
    drain_eta_ms: float = 0.0
    # wasted work a kill would destroy: sum of tasks' elapsed seconds
    wasted_s: float = 0.0
    task_ids: list = field(default_factory=list)


def _fits(spare: Optional[Resources], demand: Resources) -> bool:
    if spare is None:
        return False
    return (spare.mem >= demand.mem and spare.cpus >= demand.cpus
            and spare.gpus >= demand.gpus and spare.disk >= demand.disk)


def _member_demand(jobs_g: Sequence[Job]) -> Resources:
    return Resources(
        mem=max(j.resources.mem for j in jobs_g),
        cpus=max(j.resources.cpus for j in jobs_g),
        gpus=max(j.resources.gpus for j in jobs_g),
        disk=max(j.resources.disk for j in jobs_g),
    )


def _host_states(store: JobStore, pool: Pool,
                 host_spare: dict, demand: Resources,
                 predictor, now_ms: float) -> dict[str, _HostState]:
    """Classify every pool host as free / draining-in-eta / kill-cost."""
    by_host: dict[str, _HostState] = {}
    tasks_by_host: dict[str, list] = {}
    for inst in store.running_instances(pool.name):
        if inst.hostname:
            tasks_by_host.setdefault(inst.hostname, []).append(inst)
    for hostname in set(host_spare) | set(tasks_by_host):
        tasks = tasks_by_host.get(hostname, [])
        free = not tasks and _fits(host_spare.get(hostname), demand)
        hs = _HostState(hostname=hostname, free=free)
        if not free and _fits(host_spare.get(hostname), demand):
            # busy but the member already fits beside the running tasks:
            # as good as free for this gang's purposes
            hs.free = True
        if not hs.free:
            eta = 0.0
            for inst in tasks:
                job = store.jobs.get(inst.job_uuid)
                elapsed_ms = max(0.0, now_ms - inst.start_time_ms)
                hs.wasted_s += elapsed_ms / 1000.0
                hs.task_ids.append(inst.task_id)
                pred = None
                if predictor is not None and job is not None:
                    pred = predictor.predict_runtime_ms(job.user,
                                                        job.command)
                if pred is None:
                    eta = math.inf
                else:
                    eta = max(eta, max(0.0, pred - elapsed_ms))
            if not tasks:
                # no running work yet the member does not fit (e.g. the
                # spare map lags a launch): nothing to drain or kill
                eta = math.inf
            hs.drain_eta_ms = eta
        by_host[hostname] = hs
    return by_host


def plan_gang_admissions(
    store: JobStore,
    pool: Pool,
    queue_jobs: Sequence[Job],
    host_spare: dict,
    *,
    nodes_per_block: int,
    predictor,
    params,
    now_ms: float,
    reserved: Optional[set] = None,
) -> list[GangAdmission]:
    """Admission decisions for this cycle's waiting gangs (queue order,
    at most `params.gang_max_admissions`).  `params` is RebalancerParams
    (gang_* knobs).  Pure planning: the caller transacts kills and writes
    the reservations."""
    admissions: list[GangAdmission] = []
    gangs = waiting_gangs(queue_jobs)
    if not gangs:
        return admissions
    reserved = reserved or set()
    taken: set[str] = set(reserved)  # hosts claimed by earlier decisions
    for group, jobs_g in gangs:
        if len(admissions) >= params.gang_max_admissions:
            break
        k = max(j.gang_size for j in jobs_g)
        demand = _member_demand(jobs_g)
        states = _host_states(store, pool, host_spare, demand, predictor,
                              now_ms)
        hostnames = sorted(states)
        npb = nodes_per_block if nodes_per_block > 0 else max(
            1, len(hostnames))
        # evaluate each block: how would the gang get k distinct hosts?
        best = None  # (deficit, cost, block, plan)
        n_blocks = (len(hostnames) + npb - 1) // npb
        for b in range(n_blocks):
            block_hosts = hostnames[b * npb:(b + 1) * npb]
            if len(block_hosts) < k:
                continue
            free = [h for h in block_hosts
                    if states[h].free and h not in taken]
            busy = [h for h in block_hosts
                    if not states[h].free and h not in taken]
            if len(free) >= k:
                continue  # the matcher can already place here; no action
            deficit = k - len(free)
            if len(busy) < deficit:
                continue
            drain_pick = sorted(
                busy, key=lambda h: (states[h].drain_eta_ms,
                                     states[h].wasted_s, h))[:deficit]
            drain_wait = max(states[h].drain_eta_ms for h in drain_pick)
            kill_pick = sorted(
                busy, key=lambda h: (states[h].wasted_s, h))[:deficit]
            kill_wasted = sum(states[h].wasted_s for h in kill_pick)
            cost = min(drain_wait,
                       kill_wasted * 1000.0 if kill_wasted else 0.0)
            cand = (deficit, cost, b, free, drain_pick, drain_wait,
                    kill_pick, kill_wasted)
            if best is None or cand[:3] < best[:3]:
                best = cand
        if best is None:
            continue
        (deficit, _cost, b, free, drain_pick, drain_wait, kill_pick,
         kill_wasted) = best
        drain_ok = (drain_wait <= params.gang_drain_max_wait_ms
                    and drain_wait <= (params.gang_drain_wasted_factor
                                       * kill_wasted * 1000.0))
        leader = jobs_g[0]
        if drain_ok:
            hosts = sorted(free[:k - deficit] + drain_pick)
            adm = GangAdmission(
                group_uuid=group, gang_size=k, leader_uuid=leader.uuid,
                mode="drain", block=b, hosts=hosts,
                predicted_wait_ms=drain_wait,
                detail=(f"block {b} drains in ~{drain_wait / 1000.0:.1f}s"
                        f" (< killing {kill_wasted:.1f}s of work)"))
        else:
            victims = []
            for h in kill_pick:
                victims.extend(states[h].task_ids)
            hosts = sorted(free[:k - deficit] + kill_pick)
            adm = GangAdmission(
                group_uuid=group, gang_size=k, leader_uuid=leader.uuid,
                mode="preempt", block=b, hosts=hosts, victims=victims,
                victim_wasted_s=kill_wasted,
                detail=(f"freeing {deficit} host(s) in block {b} "
                        f"(drain predicted {drain_wait / 1000.0:.1f}s, "
                        f"over budget)"))
        taken.update(adm.hosts)
        admissions.append(adm)
    return admissions
