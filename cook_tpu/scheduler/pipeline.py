"""Pipelined match cycle: overlap host encode/launch with the device solve.

The serial cycle (matcher.match_pool) runs tensor_build -> blocking fetch
-> launch strictly in sequence: the device idles while the host builds
tensors and fans out launches, and the host idles while the device
solves.  Prediction-assisted online schedulers (arXiv:2501.05563) and
elastic DL schedulers like Aryl (arXiv:2202.07896) pipeline scheduler
phases so accelerator and host work overlap and decision latency stays
inside the cluster's offer cadence; this module is that structure for the
multi-pool match pass:

    pool k:    prepare ----> dispatch . . . . [device solves] . . fetch -> finalize
    pool k+1:               prepare -> dispatch . . [device] . . . . fetch -> ...
                 ^ host                  ^ overlaps pool k's solve

  * `dispatch_pool_solve` starts pool k's kernel asynchronously (JAX's
    async dispatch — no inline `fetch_result`), then the host runs pool
    k+1's `prepare_pool_problem` and pool k-1's `finalize_pool_match`
    while the device executes;
  * a double-buffered stage queue bounds in-flight solves (depth 2 by
    default: one solving, one just dispatched), so device memory holds at
    most `depth` pools' problems;
  * the ORDERING RULE: store transactions commit in pool order — stages
    drain FIFO, so pool k's `finalize_pool_match` (where create_instance
    transacts) always completes before pool k+1's begins;
  * the per-cluster `launch_tasks` fan-out runs on each cluster's bounded
    launch executor (ComputeCluster.launch_tasks_async) with the
    kill-lock read side held by the worker, so backend RPC latency leaves
    the cycle's critical path while kills still exclude mid-launch;
    launch failures flow back into the store's state machine
    (task -> failed, `launch-failed` reason) — never swallowed by the
    async boundary;
  * a solve raising for pool k surfaces at ITS fetch: the pool's jobs are
    skipped with `solve-failed` and pools k±1 proceed untouched.

Overlap accounting: each participating CycleRecord keeps per-phase times
with the serial path's semantics (solve = dispatch -> fetch-complete
interval), plus the shared pass wall and the device/host overlap
fraction (summed phase time beyond the wall), visible at
`GET /debug/cycles` — see docs/observability.md.
"""
from __future__ import annotations

import collections
import logging
import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

import numpy as np

from cook_tpu.cluster.base import ComputeCluster
from cook_tpu.models.entities import Job, Pool
from cook_tpu.models.store import JobStore
from cook_tpu.obs import data_plane
from cook_tpu.scheduler import flight_recorder as flight_codes
from cook_tpu.scheduler.flight_recorder import NULL_CYCLE
from cook_tpu.scheduler.matcher import (
    CpuFallbackPending,
    MatchConfig,
    MatchOutcome,
    PoolMatchState,
    check_device_fallback,
    cpu_fallback_solve,
    dispatch_pool_solve,
    enter_device_fallback,
    exit_device_fallback,
    fail_launched_specs,
    finalize_pool_match,
    prepare_pool_problem,
    record_fallback_outcome,
    record_solve_outcome,
)
from cook_tpu.scheduler.ranking import RankedQueue

log = logging.getLogger(__name__)

# the phases whose summed time the overlap accounting compares against
# the pass wall (rank/preemption_search run outside the pipelined pass).
# The four walls are DISJOINT per pool: the solve interval starts where
# the dispatch phase ends, so nothing is double-counted and a pass that
# degenerated to serial genuinely reports overlap 0.
# speculation_commit is the (tiny) validation wall of a pool served from
# a committed speculative solve — such pools have no tensor_build /
# dispatch / solve phases this cycle (that work ran during the PREVIOUS
# cycle's drain; scheduler/prediction.py)
PIPELINE_PHASES = ("tensor_build", "dispatch", "solve", "launch",
                   "speculation_commit")


@dataclass
class PipelineParams:
    """Knobs of the pipelined pass."""

    # max in-flight solves (double-buffered by default: one pool solving
    # while the next is being prepared/dispatched)
    depth: int = 2
    # fan launches out via each cluster's launch executor instead of
    # blocking the cycle on backend RPCs
    async_launch: bool = True
    # wait for every async launch batch before the pass returns — the
    # per-pool overlap is already banked; draining at the END keeps the
    # cycle's externally visible semantics identical to the serial path
    # (callers observe launched tasks in the store).  False = launches
    # may still be in flight when the pass returns.
    drain_launches: bool = True
    drain_timeout_s: float = 30.0


@dataclass
class _Stage:
    pool: Pool
    prepared: object
    state: PoolMatchState
    flight: object
    pending: object = None          # PendingResult or None
    t_dispatch: float = 0.0
    fallback_reason: str = ""       # non-empty = CPU-fallback cycle
    # committed speculative assignment (scheduler/prediction.py): the
    # solve already ran during the previous cycle's drain — this stage
    # skips dispatch/fetch entirely and finalizes at the queue head
    # without holding a device-buffer slot
    speculative_assignment: object = None


def match_pools_pipelined(
    store: JobStore,
    pools: Sequence[Pool],
    queues: dict[str, RankedQueue],
    clusters: Sequence[ComputeCluster],
    config: MatchConfig,
    states: dict[str, PoolMatchState],
    *,
    make_task_id: Callable[[Job], str],
    launch_filter: Optional[Callable[[Job], bool]] = None,
    record_placement_failure: Optional[Callable[[Job, str], None]] = None,
    host_reservations: Optional[dict[str, str]] = None,
    host_attrs: Optional[dict[str, dict]] = None,
    flights: Optional[dict] = None,
    telemetry=None,
    encode_cache=None,
    recorder=None,
    params: Optional[PipelineParams] = None,
    predictor=None,
    speculative: Optional[dict] = None,
    device_state=None,
) -> dict[str, MatchOutcome]:
    """Run every pool's match cycle through the pipelined engine.

    Same decision semantics as looping `matcher.match_pool` over the
    pools (the parity test pins this); only the schedule differs.

    `speculative` maps pool name -> a COMMITTED prediction.CommitResult
    (validated by the caller against the speculation commit rule): such
    pools skip prepare + dispatch entirely — their solve already ran
    while the previous cycle drained — and finalize straight away.
    """
    params = params or PipelineParams()
    flights = flights or {}
    speculative = speculative or {}
    outcomes: dict[str, MatchOutcome] = {}

    def pool_flight(pool_name: str):
        return flights.get(pool_name, NULL_CYCLE)

    for f in flights.values():
        if f.record is not None:
            f.record.pipelined = True

    def launch_failure_cb_for(flight):
        # the callback runs on a cluster launch-worker thread and can
        # land before OR after the cycle record commits — record + index
        # writes go through the recorder lock, never the builder
        record = flight.record

        def cb(specs, exc):
            def note(job_uuid, detail):
                if recorder is not None:
                    recorder.note_async_launch_failure(
                        record, job_uuid, flight_codes.LAUNCH_FAILED,
                        detail)
            fail_launched_specs(store, specs, exc, note_reason=note)
        return cb

    def finish(stage: _Stage) -> None:
        """Fetch + finalize one pool.  Called strictly in pool order."""
        flight = stage.flight
        assignment = np.empty(0, dtype=np.int32)
        if stage.speculative_assignment is not None:
            # cycle served from a committed speculation: the solve's
            # telemetry/fallback protocol already ran when the
            # speculation was validated — straight to the launch phase
            assignment = stage.speculative_assignment
        elif stage.pending is not None:
            solve_failed = False
            t_fetch = time.perf_counter()
            try:
                # re-activate THIS pool's data-plane scope for the
                # fetch: under overlap the driving thread interleaves
                # pool k's fetch with pool k±1's prepare/finalize, and
                # each stage must credit its own cycle's byte counts
                # (the disjointness the ledger tests pin)
                with data_plane.activate(flight.dp), \
                        data_plane.family(data_plane.FAM_SOLVE):
                    assignment = stage.pending.fetch()
            except Exception:  # noqa: BLE001 — pool k's kernel raising
                # (deferred device error surfaces at fetch) must not
                # wedge pools k±1
                log.exception("pipelined solve failed (pool %s)",
                              stage.pool.name)
                if stage.fallback_reason \
                        or config.device_fallback_cycles <= 0:
                    # the raise came from the CPU fallback itself (or
                    # fallback is disabled): there is no further tier to
                    # degrade to — jobs wait a cycle (historic
                    # solve-failed semantics), pools k±1 untouched
                    solve_failed = True
                else:
                    # reaction (c): re-solve THIS cycle host-side and
                    # degrade the pool (same semantics as the serial
                    # path) — no cycle lost, pools k±1 untouched
                    enter_device_fallback(stage.state, config,
                                          stage.pool.name, "solve-error")
                    stage.fallback_reason = "solve-error"
                    try:
                        assignment = cpu_fallback_solve(stage.prepared,
                                                        config)
                    except Exception:  # noqa: BLE001 — fallback solver
                        # failing too must still not escape finish()
                        log.exception("cpu fallback solve failed "
                                      "(pool %s)", stage.pool.name)
                        solve_failed = True
            t_end = time.perf_counter()
            # solve phase wall = dispatch-end -> fetch-complete; under
            # overlap it also spans the host work interleaved between
            # dispatch and fetch, which is exactly what the overlap
            # fraction quantifies.  Only the blocking fetch WAIT is
            # device-attributed: the overlapped span is not accelerator
            # time, and crediting it would inflate cycle.device_seconds
            # the moment the pipeline turns on (the un-overlapped device
            # execution is covered by the wait; fully hidden device time
            # is the pipeline working as designed)
            wait_s = t_end - t_fetch
            solve_s = t_end - stage.t_dispatch
            # a CPU-fallback solve is pure host work: nothing about its
            # wall is device-attributable
            flight.add_phase("solve", wait_s,
                             device=not stage.fallback_reason)
            if solve_s > wait_s:
                flight.add_phase("solve", solve_s - wait_s, device=False)
            if solve_failed:
                outcome = stage.prepared.outcome
                outcome.unmatched = list(stage.prepared.considerable)
                outcome.head_matched = False
                for job in stage.prepared.considerable:
                    flight.note_skip(job.uuid, flight_codes.SOLVE_FAILED)
                    if record_placement_failure is not None:
                        record_placement_failure(
                            job, flight_codes.REASON_TEXT[
                                flight_codes.SOLVE_FAILED])
                from cook_tpu.scheduler.matcher import _apply_backoff

                _apply_backoff(config, stage.state, False)
                outcomes[stage.pool.name] = outcome
                return
            if stage.fallback_reason:
                record_fallback_outcome(stage.prepared, stage.pool.name,
                                        stage.state, flight, telemetry,
                                        stage.fallback_reason)
            else:
                record_solve_outcome(stage.prepared, assignment, config,
                                     stage.state, stage.pool.name, solve_s,
                                     flight, telemetry, overlapped=True)
                exit_device_fallback(stage.state, telemetry,
                                     stage.pool.name)
        with data_plane.activate(flight.dp), flight.phase("launch"):
            outcomes[stage.pool.name] = finalize_pool_match(
                store, stage.prepared, assignment, config, stage.state,
                clusters,
                make_task_id=make_task_id,
                record_placement_failure=record_placement_failure,
                flight=flight,
                async_launch=params.async_launch,
                launch_failure_cb=(launch_failure_cb_for(flight)
                                   if params.async_launch else None),
            )

    t_pass = time.perf_counter()
    inflight: collections.deque[_Stage] = collections.deque()
    depth = max(1, params.depth)
    for pool in pools:
        flight = pool_flight(pool.name)
        state = states[pool.name]
        hit = speculative.get(pool.name)
        if hit is not None:
            # pre-solved pool: no prepare, no dispatch, no buffer slot —
            # pending stays None, so the drain condition below finalizes
            # it as soon as it reaches the queue head (pool-order commits
            # still hold; finish() routes via speculative_assignment)
            inflight.append(_Stage(
                pool=pool, prepared=hit.prepared, state=state,
                flight=flight, speculative_assignment=hit.assignment))
            while inflight and (
                    inflight[0].pending is None
                    or sum(1 for s in inflight if s.pending is not None)
                    >= depth):
                finish(inflight.popleft())
            continue
        with data_plane.activate(flight.dp), flight.phase("tensor_build"):
            prepared = prepare_pool_problem(
                store, pool, queues[pool.name], clusters, config, state,
                launch_filter=launch_filter,
                host_reservations=host_reservations,
                host_attrs=host_attrs, flight=flight,
                encode_cache=encode_cache, predictor=predictor,
                device_state=device_state,
            )
        stage = _Stage(pool=pool, prepared=prepared, state=state,
                       flight=flight)
        if prepared.solvable:
            use_cpu, fb_reason = check_device_fallback(
                config, state, telemetry, pool.name)
            if use_cpu:
                # pool in device-fallback mode: the "pending solve" is a
                # host-side reference solve run at fetch time (no device
                # buffer behind it)
                stage.pending = CpuFallbackPending(prepared, config)
                stage.fallback_reason = fb_reason
            else:
                with data_plane.activate(flight.dp), \
                        flight.phase("dispatch"):
                    try:
                        stage.pending = dispatch_pool_solve(
                            prepared, config, telemetry=telemetry)
                    except Exception:  # noqa: BLE001 — a dispatch-time
                        # raise (tracing/compile error) is this pool's
                        # solve failing eagerly; mark it failed at
                        # finish() like a deferred device error
                        log.exception("pipelined dispatch failed "
                                      "(pool %s)", pool.name)
                        stage.pending = _FailedDispatch()
            # the solve interval starts where the dispatch phase ends —
            # disjoint walls, so phase sums never double-count
            stage.t_dispatch = time.perf_counter()
        inflight.append(stage)
        # the double-buffered stage queue: once `depth` solves are in
        # flight, the oldest pool's fetch+finalize runs NOW — its device
        # wait overlaps the pool just prepared/dispatched, and the FIFO
        # drain keeps transactions committing in pool order.  Unsolvable
        # pools (nothing dispatched) finalize as soon as they reach the
        # head; they never hold a buffer slot
        while inflight and (
                inflight[0].pending is None
                or sum(1 for s in inflight if s.pending is not None)
                >= depth):
            finish(inflight.popleft())
    while inflight:
        finish(inflight.popleft())

    if params.async_launch and params.drain_launches:
        from cook_tpu.cluster.base import wait_all_launches

        for cluster in wait_all_launches(clusters,
                                         timeout=params.drain_timeout_s):
            log.warning("pipelined pass: cluster %s still has launches "
                        "in flight after %.0fs drain timeout",
                        cluster.name, params.drain_timeout_s)

    # ------------------------------------------------ overlap accounting
    wall_s = time.perf_counter() - t_pass
    summed = 0.0
    for pool in pools:
        record = pool_flight(pool.name).record
        if record is None:
            continue
        summed += sum(record.phases.get(name, 0.0)
                      for name in PIPELINE_PHASES)
    overlap_s = max(0.0, summed - wall_s)
    overlap_fraction = overlap_s / summed if summed > 0 else 0.0
    for pool in pools:
        record = pool_flight(pool.name).record
        if record is None:
            continue
        record.pipeline_wall_s = wall_s
        record.overlap_s = overlap_s
        record.overlap_fraction = overlap_fraction
    return outcomes


class _FailedDispatch:
    """Stand-in pending result for a solve that raised at dispatch time:
    fetch() re-raises so finish() takes the one solve-failed path."""

    def fetch(self):
        raise RuntimeError("solve dispatch failed (see log)")
