"""Token-bucket rate limiters.

Reference: cook.rate-limit (/root/reference/scheduler/src/cook/
rate_limit/{generic,token_bucket_filter}.clj): a lazily-refilled token
bucket per key, used for (a) global job-submission rate, (b) per-user
per-pool launch rate (quota.clj:118), (c) per-compute-cluster launch rate.
`spend!` is always allowed to go negative ("spend-through"): enforcement
happens at `allowed?` time, which keeps the hot path lock-free-ish and
matches the reference's semantics of charging work that was already done.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Hashable


@dataclass
class _Bucket:
    tokens: float
    last_ms: int


class TokenBucketRateLimiter:
    def __init__(
        self,
        *,
        tokens_replenished_per_minute: float,
        bucket_size: float,
        clock: Callable[[], int],
        enforce: bool = True,
    ):
        self.rate_per_ms = tokens_replenished_per_minute / 60_000.0
        self.bucket_size = bucket_size
        self.clock = clock
        self.enforce = enforce
        self._buckets: dict[Hashable, _Bucket] = {}
        self._lock = threading.Lock()

    def _refill(self, key: Hashable) -> _Bucket:
        now = self.clock()
        bucket = self._buckets.get(key)
        if bucket is None:
            bucket = _Bucket(tokens=self.bucket_size, last_ms=now)
            self._buckets[key] = bucket
        else:
            elapsed = max(0, now - bucket.last_ms)
            bucket.tokens = min(
                self.bucket_size, bucket.tokens + elapsed * self.rate_per_ms
            )
            bucket.last_ms = now
        return bucket

    def allowed(self, key: Hashable) -> bool:
        if not self.enforce:
            return True
        with self._lock:
            return self._refill(key).tokens >= 1.0

    def spend(self, key: Hashable, amount: float = 1.0) -> None:
        with self._lock:
            self._refill(key).tokens -= amount

    def tokens_available(self, key: Hashable) -> float:
        """Current balance (refilled): lets a caller budget a batch of
        work up front (the matcher's per-cluster launch cap)."""
        if not self.enforce:
            return float("inf")
        with self._lock:
            return self._refill(key).tokens

    def try_spend(self, key: Hashable, amount: float = 1.0) -> bool:
        """allowed? + spend! in one step (submission path)."""
        if not self.enforce:
            return True
        with self._lock:
            bucket = self._refill(key)
            if bucket.tokens < 1.0:
                return False
            bucket.tokens -= amount
            return True


class UnlimitedRateLimiter:
    def allowed(self, key: Hashable) -> bool:
        return True

    def spend(self, key: Hashable, amount: float = 1.0) -> None:
        pass

    def try_spend(self, key: Hashable, amount: float = 1.0) -> bool:
        return True
