"""Constraint encoding: job/group placement rules -> tensor masks.

The reference evaluates a zoo of Fenzo constraint objects per (job, node)
pair (/root/reference/scheduler/src/cook/scheduler/constraints.clj).  Here
constraints are split the way SURVEY §7 prescribes:

  * vectorizable constraints (novel-host, gpu-host, attribute EQUALS,
    max-tasks-per-host, group member-exclusion) are encoded host-side into
    one [J, N] boolean feasibility mask fed to the match kernel — numpy
    vectorized, O(J*N) bitwork, no Python loops over pairs;

  * order-dependent group constraints (unique-host / balanced /
    attribute-equals *within the current cycle*) are enforced by a
    post-kernel validation pass that unassigns violators (they simply wait
    a cycle, like any unplaced job).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from cook_tpu.cluster.base import Offer
from cook_tpu.ops.common import binpack_fitness
from cook_tpu.models.entities import (
    Group,
    GroupPlacementType,
    Job,
)

# Balanced-host treats a host with the attribute absent as carrying a nil
# VALUE that participates in the frequency map (the reference maps cohost
# attr maps with `get`, so nils are counted — constraints.clj:600), not as
# an infeasible host.
MISSING_ATTR = "\x00missing"


def _closed_value_mask(
    counts: dict[str, int],
    minimum: int,
    codes: np.ndarray,
    vocab: dict[str, int],
) -> np.ndarray:
    """[N] bool: nodes whose attribute value is closed to a balanced group
    under `counts` — value at the max member count while counts are skewed
    (until `minimum` distinct values are in play the floor is pinned to 0,
    forcing spread onto unseen values).  The single encoding of the rule
    shared by the pre-mask closure and the post-solve top-up."""
    closed = np.zeros(codes.shape[0], dtype=bool)
    if not counts:
        return closed
    minim = 0 if minimum > len(counts) else min(counts.values())
    maxim = max(counts.values())
    if minim == maxim:
        return closed
    for value, c in counts.items():
        if c < maxim:
            continue
        if value == MISSING_ATTR:
            closed |= codes == -1
        else:
            closed |= codes == vocab.get(value, -2)
    return closed


@dataclass
class EncodedNodes:
    """Host-side encoding of one pool's offers."""

    offers: list[Offer]
    hostname_to_idx: dict[str, int]
    has_gpus: np.ndarray          # [N] bool
    attr_codes: dict[str, np.ndarray]  # attr name -> [N] int codes (-1 missing)
    attr_vocab: dict[str, dict[str, int]]

    @property
    def n(self) -> int:
        return len(self.offers)


def encode_nodes(offers: Sequence[Offer]) -> EncodedNodes:
    hostname_to_idx = {o.hostname: i for i, o in enumerate(offers)}
    has_gpus = np.array([o.gpus > 0 for o in offers], dtype=bool)
    attr_names = set()
    for o in offers:
        attr_names.update(dict(o.attributes).keys())
    attr_codes: dict[str, np.ndarray] = {}
    attr_vocab: dict[str, dict[str, int]] = {}
    for name in attr_names:
        vocab: dict[str, int] = {}
        codes = np.full(len(offers), -1, dtype=np.int32)
        for i, o in enumerate(offers):
            val = dict(o.attributes).get(name)
            if val is None:
                continue
            if val not in vocab:
                vocab[val] = len(vocab)
            codes[i] = vocab[val]
        attr_codes[name] = codes
        attr_vocab[name] = vocab
    return EncodedNodes(
        offers=list(offers),
        hostname_to_idx=hostname_to_idx,
        has_gpus=has_gpus,
        attr_codes=attr_codes,
        attr_vocab=attr_vocab,
    )


def feasibility_mask(
    jobs: Sequence[Job],
    nodes: EncodedNodes,
    *,
    previous_hosts: Optional[dict[str, set[str]]] = None,
    group_used_hosts: Optional[dict[str, set[str]]] = None,
    group_attr_value: Optional[dict[str, tuple[str, str]]] = None,
    group_balance_counts: Optional[dict[str, dict[str, int]]] = None,
    groups: Optional[dict[str, Group]] = None,
    tasks_on_host: Optional[dict[str, int]] = None,
    max_tasks_per_host: int = 0,
    offer_locations: Optional[Sequence[str]] = None,
    job_est_end_ms: Optional[np.ndarray] = None,
    host_lifetime_mins: float = 0.0,
    balanced_pre_rows: Optional[dict[int, np.ndarray]] = None,
) -> np.ndarray:
    """Build the [J, N] mask.

    previous_hosts: job uuid -> hostnames of prior failed instances
      (novel-host constraint, constraints.clj:68).
    group_used_hosts: group uuid -> hostnames already used by RUNNING group
      members (unique-host member exclusion, constraints.clj:586).
    group_attr_value: group uuid -> (attr, value) pinned by running members
      (attribute-equals, constraints.clj:628).
    tasks_on_host + max_tasks_per_host: constraints.clj:433.
    """
    j, n = len(jobs), nodes.n
    mask = np.ones((j, n), dtype=bool)
    if n == 0:
        return mask

    # gpu-host constraint (constraints.clj:122): gpu jobs only on gpu nodes,
    # non-gpu jobs never on gpu nodes.
    job_gpu = np.array([job.resources.gpus > 0 for job in jobs], dtype=bool)
    mask &= job_gpu[:, None] == nodes.has_gpus[None, :]

    # disk type (disk-host-constraint, constraints.clj:164): a typed disk
    # request only matches hosts advertising that "disk-type" attribute
    # (space binpacking is the kernel's 4th resource column)
    job_disk_type = [job.resources.disk_type for job in jobs]
    if any(job_disk_type):
        host_disk_type = np.array(
            [dict(o.attributes).get("disk-type", "") for o in nodes.offers])
        for ji, want in enumerate(job_disk_type):
            if want:
                mask[ji, :] &= host_disk_type == want

    # port count: a job requesting N ports only fits offers carrying >= N
    # free ports (mesos/task.clj port resources); concrete assignment
    # happens post-solve in the matcher
    job_ports = np.array([job.resources.ports for job in jobs])
    if job_ports.any():
        avail_ports = np.array([o.port_count() for o in nodes.offers])
        mask &= job_ports[:, None] <= avail_ports[None, :]

    # estimated completion vs host lifetime (constraints.clj:385): skip
    # hosts expected to die before the job's estimated end; hosts without
    # a "host-start-time" attribute (epoch seconds) always pass
    if job_est_end_ms is not None and host_lifetime_mins > 0:
        start_s = np.array(
            [float(dict(o.attributes).get("host-start-time", -1))
             for o in nodes.offers])
        death_ms = start_s * 1000.0 + host_lifetime_mins * 60_000.0
        no_estimate = job_est_end_ms < 0
        mask &= (no_estimate[:, None] | (start_s < 0)[None, :]
                 | (job_est_end_ms[:, None] < death_ms[None, :]))

    # max tasks per host
    if max_tasks_per_host and tasks_on_host:
        full = np.array(
            [tasks_on_host.get(o.hostname, 0) >= max_tasks_per_host
             for o in nodes.offers],
            dtype=bool,
        )
        mask &= ~full[None, :]

    loc_arr = (np.array(offer_locations) if offer_locations is not None
               else None)
    for ji, job in enumerate(jobs):
        # checkpoint locality (constraints.clj:218): a job restarting from a
        # checkpoint only runs where its checkpoint is reachable
        if (job.checkpoint is not None and job.checkpoint.location
                and loc_arr is not None):
            mask[ji, :] &= loc_arr == job.checkpoint.location
        # novel-host: never revisit a host this job failed on
        if previous_hosts:
            for hostname in previous_hosts.get(job.uuid, ()):
                idx = nodes.hostname_to_idx.get(hostname)
                if idx is not None:
                    mask[ji, idx] = False
        # user-specified attribute constraints (EQUALS)
        for c in job.constraints:
            codes = nodes.attr_codes.get(c.attribute)
            if codes is None:
                mask[ji, :] = False
                continue
            want = nodes.attr_vocab[c.attribute].get(c.pattern, -2)
            mask[ji, :] &= codes == want
        # group placement derived from already-running members
        if job.group_uuid and groups:
            group = groups.get(job.group_uuid)
            if group is not None:
                ptype = group.host_placement.type
                if ptype == GroupPlacementType.UNIQUE and group_used_hosts:
                    for hostname in group_used_hosts.get(job.group_uuid, ()):
                        idx = nodes.hostname_to_idx.get(hostname)
                        if idx is not None:
                            mask[ji, idx] = False
                elif (ptype == GroupPlacementType.ATTRIBUTE_EQUALS
                      and group_attr_value):
                    pinned = group_attr_value.get(job.group_uuid)
                    if pinned is not None:
                        attr, value = pinned
                        codes = nodes.attr_codes.get(attr)
                        if codes is None:
                            mask[ji, :] = False
                        else:
                            want = nodes.attr_vocab[attr].get(value, -2)
                            mask[ji, :] &= codes == want
                elif (ptype == GroupPlacementType.BALANCED
                      and group_balance_counts):
                    # the running-member part of balanced-host
                    # (constraints.clj:600) is order-independent, so it is
                    # enforced up front: attribute values already at the
                    # max member count are closed to the group (otherwise
                    # the kernel would keep picking the fittest closed host
                    # and the post-pass would reject it every cycle)
                    counts = group_balance_counts.get(job.group_uuid)
                    if counts:
                        attr = group.host_placement.attribute
                        minimum = group.host_placement.minimum
                        codes = nodes.attr_codes.get(attr)
                        if codes is None:
                            # attr absent from every offer: all hosts carry
                            # the nil value (code -1), same as the post-pass
                            codes = np.full(nodes.n, -1, dtype=np.int32)
                        closed = _closed_value_mask(
                            counts, minimum, codes,
                            nodes.attr_vocab.get(attr, {}))
                        if closed.any():
                            # intra-cycle leveling can re-open a closed
                            # value; keep the pre-closure row so the
                            # post-solve top-up (balanced_group_topup) can
                            # retry against live counts
                            if balanced_pre_rows is not None:
                                balanced_pre_rows[ji] = mask[ji].copy()
                            mask[ji, :] &= ~closed
    return mask


def validate_group_assignments(
    jobs: Sequence[Job],
    assignment: np.ndarray,
    nodes: EncodedNodes,
    groups: dict[str, Group],
    group_used_hosts: dict[str, set[str]],
    group_attr_value: dict[str, tuple[str, str]],
    group_balance_counts: Optional[dict[str, dict[str, int]]] = None,
    out_balance_counts: Optional[dict[str, dict[str, int]]] = None,
) -> np.ndarray:
    """Post-kernel pass enforcing intra-cycle group semantics: walk matches
    in schedule order; a match that violates its group's unique-host /
    attribute-equals placement against *earlier* matches this cycle is
    unassigned (set to -1).  Returns the corrected assignment.

    `group_balance_counts` seeds the balanced-host skew counts with RUNNING
    members — including those on hosts outside this cycle's offer set — so
    the constraint matches the reference's all-running-members semantics
    (constraints.clj:600), not just intra-cycle placements."""
    assignment = assignment.copy()
    used: dict[str, set[str]] = {g: set(h) for g, h in group_used_hosts.items()}
    pinned: dict[str, tuple[str, str]] = dict(group_attr_value)
    # balanced: per-group count of members per attribute value, seeded with
    # running members
    balance_counts: dict[str, dict[str, int]] = {
        g: dict(c) for g, c in (group_balance_counts or {}).items()
    }
    for ji, job in enumerate(jobs):
        node_idx = int(assignment[ji])
        if node_idx < 0 or not job.group_uuid:
            continue
        group = groups.get(job.group_uuid)
        if group is None:
            continue
        hostname = nodes.offers[node_idx].hostname
        ptype = group.host_placement.type
        if ptype == GroupPlacementType.UNIQUE:
            seen = used.setdefault(job.group_uuid, set())
            if hostname in seen:
                assignment[ji] = -1
                continue
            seen.add(hostname)
        elif ptype == GroupPlacementType.ATTRIBUTE_EQUALS:
            attr = group.host_placement.attribute
            value = dict(nodes.offers[node_idx].attributes).get(attr)
            if value is None:
                assignment[ji] = -1
                continue
            prev = pinned.get(job.group_uuid)
            if prev is None:
                pinned[job.group_uuid] = (attr, value)
            elif prev != (attr, value):
                assignment[ji] = -1
        elif ptype == GroupPlacementType.BALANCED:
            # balanced-host (constraints.clj:600): a member may land on an
            # already-seen attribute value only if that value's member count
            # is below the current max (or all seen values are level); until
            # `minimum` distinct values are in play the floor is pinned to 0,
            # which forces spreading onto unseen values.  Unseen values
            # always pass.
            attr = group.host_placement.attribute
            minimum = group.host_placement.minimum
            value = dict(nodes.offers[node_idx].attributes).get(
                attr, MISSING_ATTR)
            counts = balance_counts.setdefault(job.group_uuid, {})
            freq = counts.get(value)
            if counts and freq is not None:
                minim = 0 if minimum > len(counts) else min(counts.values())
                maxim = max(counts.values())
                if minim != maxim and freq >= maxim:
                    assignment[ji] = -1
                    continue
            counts[value] = counts.get(value, 0) + 1
    if out_balance_counts is not None:
        out_balance_counts.update(balance_counts)
    return assignment


def balanced_group_topup(
    jobs: Sequence[Job],
    assignment: np.ndarray,
    nodes: EncodedNodes,
    groups: dict[str, Group],
    balance_counts: dict[str, dict[str, int]],
    balanced_pre_rows: dict[int, np.ndarray],
    remaining_avail: np.ndarray,
    demands: np.ndarray,
    totals: np.ndarray,
) -> np.ndarray:
    """Second chance for balanced-group jobs the pre-mask closed out.

    The pre-mask closes attribute values already at the max member count
    using counts seeded BEFORE the solve; placements made during the cycle
    can level those counts and legitimately re-open a closed value — which
    the kernel, solving against the stale mask, could never propose.  This
    host-side pass walks still-unplaced jobs whose rows the closure
    restricted (in schedule order), re-evaluating admissibility against the
    LIVE post-cycle counts (the same rule as validate_group_assignments)
    and placing on the best-fitting node with enough remaining resources.

    `remaining_avail`/`demands` are [N, R]/[J, R] in the kernel's resource
    layout; both are mutated-by-copy (the returned assignment reflects the
    extra placements, `remaining_avail` is updated in place so callers see
    consumed capacity).
    """
    for ji in sorted(balanced_pre_rows):
        if assignment[ji] >= 0:
            continue
        job = jobs[ji]
        group = groups.get(job.group_uuid) if job.group_uuid else None
        if group is None or (group.host_placement.type
                             != GroupPlacementType.BALANCED):
            continue
        attr = group.host_placement.attribute
        minimum = group.host_placement.minimum
        counts = balance_counts.setdefault(job.group_uuid, {})
        codes = nodes.attr_codes.get(attr)
        if codes is None:
            codes = np.full(nodes.n, -1, dtype=np.int32)
        vocab = nodes.attr_vocab.get(attr, {})
        # admissible values under LIVE counts (same rule as the pre-mask
        # closure and the post-pass, via the shared helper)
        closed = _closed_value_mask(counts, minimum, codes, vocab)
        ok = (balanced_pre_rows[ji]
              & ~closed
              & np.all(remaining_avail >= demands[ji][None, :], axis=-1))
        if not ok.any():
            continue
        # best-fit: the kernel's own fitness (shared definition), so the
        # top-up doesn't undo the solve's packing quality
        denom = np.maximum(totals, 1e-30)
        used = totals - remaining_avail[:, :2]
        fit_val = binpack_fitness(used[:, 0], used[:, 1], demands[ji][0],
                                  demands[ji][1], denom[:, 0], denom[:, 1])
        fit = np.where(ok, fit_val, -np.inf)
        node_idx = int(np.argmax(fit))
        assignment[ji] = node_idx
        remaining_avail[node_idx] -= demands[ji]
        value = dict(nodes.offers[node_idx].attributes).get(
            attr, MISSING_ATTR)
        counts[value] = counts.get(value, 0) + 1
    return assignment
