"""Progress aggregation: batched, sampled task progress updates.

Reference: cook.progress (/root/reference/scheduler/src/cook/progress.clj):
`progress-aggregator` keeps only the newest update per task under a
pending-size cap (sequence numbers drop out-of-order messages), and a
periodic `progress-update-transactor` publishes the batch to the store in
one go — raw executor messages never hit storage directly.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from cook_tpu.models.store import JobStore
from cook_tpu.utils.metrics import global_registry


@dataclass(frozen=True)
class ProgressUpdate:
    task_id: str
    sequence: int
    percent: int
    message: str = ""


class ProgressAggregator:
    def __init__(self, store: JobStore, *, max_pending: int = 4096):
        self.store = store
        self.max_pending = max_pending
        self._pending: dict[str, ProgressUpdate] = {}
        self._lock = threading.Lock()
        self.dropped = 0

    def handle(self, update: ProgressUpdate) -> bool:
        """Accept one raw update (progress-aggregator, progress.clj:34):
        newest sequence per task wins; cap the pending map size."""
        with self._lock:
            existing = self._pending.get(update.task_id)
            if existing is not None and existing.sequence >= update.sequence:
                return False
            if existing is None and len(self._pending) >= self.max_pending:
                self.dropped += 1
                global_registry.counter(
                    "progress.dropped",
                    "progress updates dropped at the pending cap").inc()
                return False
            self._pending[update.task_id] = update
            return True

    def publish(self) -> int:
        """Flush the batch to the store (progress-update-transactor,
        progress.clj:153)."""
        with self._lock:
            batch = list(self._pending.values())
            self._pending.clear()
        written = 0
        for update in batch:
            if self.store.update_instance_progress(
                update.task_id, update.percent, update.message
            ):
                written += 1
        global_registry.counter(
            "progress.published",
            "progress updates flushed to the store").inc(written)
        return written
