"""Pending-queue length limits, checked at submission time.

Reference: cook.queue-limit (/root/reference/scheduler/src/cook/
queue_limit.clj): per-pool global and per-pool-per-user pending-job caps;
submissions that would exceed them are rejected with 400.  The reference
refreshes counts by polling so non-leader nodes can enforce too
(components.clj:110-112); here the store is local so we read it directly,
keeping the same update-on-submit bookkeeping interface.
"""
from __future__ import annotations

from dataclasses import dataclass

from cook_tpu.models.store import JobStore


@dataclass
class QueueLimits:
    per_pool: int = 1_000_000
    per_user_per_pool: int = 100_000


class QueueLimitChecker:
    def __init__(self, store: JobStore, limits: QueueLimits | None = None):
        self.store = store
        self.limits = limits or QueueLimits()

    def check_submission(self, user: str, pool: str, n_new: int) -> str | None:
        """Returns an error string if the submission would exceed limits."""
        pool_len = self.store.pending_count(pool)
        if pool_len + n_new > self.limits.per_pool:
            return (
                f"pool {pool} queue length {pool_len} plus {n_new} new jobs "
                f"would exceed the limit {self.limits.per_pool}"
            )
        user_len = self.store.pending_count(pool, user=user)
        if user_len + n_new > self.limits.per_user_per_pool:
            return (
                f"user {user} queue length {user_len} in pool {pool} plus "
                f"{n_new} new jobs would exceed the limit "
                f"{self.limits.per_user_per_pool}"
            )
        return None
