"""Vectorized rank cycle over the columnar job index.

Same semantics as `ranking.rank_pool` (per-user (-priority, start, id)
order, take-while quota capping, DRU kernel, global fairness order) with
all host-side encoding as numpy column operations — O(total jobs)
vectorized instead of O(jobs) Python, which is what keeps 100k-job rank
cycles in tens of milliseconds of host time.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from cook_tpu.models.columnar import ColumnarJobIndex
from cook_tpu.models.entities import DruMode, Pool
from cook_tpu.models.store import JobStore
from cook_tpu.obs import data_plane
from cook_tpu.ops.common import BIG, bucket_size, pad_to
from cook_tpu.ops.dru import DruTasks, dru_rank
from cook_tpu.scheduler.ranking import RankedQueue


def _seg_cumsum(values: np.ndarray, seg: np.ndarray) -> np.ndarray:
    """Cumulative sum restarting at each new value of sorted `seg`."""
    total = np.cumsum(values)
    starts = np.empty(len(seg), bool)
    if len(seg):
        starts[0] = True
        starts[1:] = seg[1:] != seg[:-1]
    idx = np.arange(len(seg))
    seg_first = np.maximum.accumulate(np.where(starts, idx, 0))
    base = np.where(seg_first > 0, total[np.maximum(seg_first - 1, 0)], 0.0)
    return total - base


def rank_pool_columnar(
    store: JobStore,
    index: ColumnarJobIndex,
    pool: Pool,
    *,
    capacity_limits=None,  # (max_mem, max_cpus, max_gpus) offensive filter
    device_state=None,     # DRU-column residency (device_state.py)
) -> RankedQueue:
    pending, inst_sel = index.pool_view(pool.name)
    n_idx = index._n

    quarantined: list[str] = []
    if capacity_limits is not None and len(pending):
        max_mem, max_cpus, max_gpus = capacity_limits
        ok = (
            (index.mem[pending] <= max_mem)
            & (index.cpus[pending] <= max_cpus)
            & (index.gpus[pending] <= max_gpus)
        )
        quarantined = [index.uuids[r] for r in pending[~ok]]
        pending = pending[ok]

    if len(pending) == 0:
        return RankedQueue(jobs=[], dru={}, capped=[],
                           quarantined=quarantined)

    # per-user priority order: (user, -priority, submit, row)
    u = index.user_code[pending]
    order = np.lexsort((pending, index.submit_ms[pending],
                        -index.priority[pending], u))
    p_sorted = pending[order]
    us = index.user_code[p_sorted]

    # running usage per user (live instances of this pool)
    inst_jobs = index.inst_job_row[inst_sel]
    iu = index.user_code[inst_jobs]
    n_users = len(index.users.names)
    usage_mem = np.bincount(iu, weights=index.mem[inst_jobs],
                            minlength=n_users)
    usage_cpu = np.bincount(iu, weights=index.cpus[inst_jobs],
                            minlength=n_users)
    usage_gpu = np.bincount(iu, weights=index.gpus[inst_jobs],
                            minlength=n_users)
    usage_cnt = np.bincount(iu, minlength=n_users).astype(np.float64)

    # quota columns for the users present
    qmem = np.full(n_users, np.inf)
    qcpu = np.full(n_users, np.inf)
    qgpu = np.full(n_users, np.inf)
    qcnt = np.full(n_users, np.inf)
    for code in np.unique(us):
        quota = store.get_quota(index.users.names[code], pool.name)
        qmem[code] = quota.resources.mem
        qcpu[code] = quota.resources.cpus
        qgpu[code] = quota.resources.gpus
        qcnt[code] = quota.count

    # take-while quota cap via segmented cumsums
    cmem = _seg_cumsum(index.mem[p_sorted].astype(np.float64), us) + usage_mem[us]
    ccpu = _seg_cumsum(index.cpus[p_sorted].astype(np.float64), us) + usage_cpu[us]
    cgpu = _seg_cumsum(index.gpus[p_sorted].astype(np.float64), us) + usage_gpu[us]
    ccnt = _seg_cumsum(np.ones(len(p_sorted)), us) + usage_cnt[us]
    fits = ((cmem <= qmem[us]) & (ccpu <= qcpu[us])
            & (cgpu <= qgpu[us]) & (ccnt <= qcnt[us]))
    # prefix-AND within each user segment (first failure closes the user)
    over = _seg_cumsum((~fits).astype(np.float64), us)
    keep = over == 0
    capped = [index.uuids[r] for r in p_sorted[~keep]]
    kept = p_sorted[keep]
    if len(kept) == 0:
        return RankedQueue(jobs=[], dru={}, capped=capped,
                           quarantined=quarantined)

    # DRU kernel input: running instances first, then kept pending
    n_run = len(inst_jobs)
    n = n_run + len(kept)
    user = np.concatenate([index.user_code[inst_jobs],
                           index.user_code[kept]]).astype(np.int32)
    mem = np.concatenate([index.mem[inst_jobs], index.mem[kept]])
    cpus = np.concatenate([index.cpus[inst_jobs], index.cpus[kept]])
    gpus = np.concatenate([index.gpus[inst_jobs], index.gpus[kept]])
    neg_prio = np.concatenate([
        -index.priority[inst_jobs], -index.priority[kept]
    ]).astype(np.int64)
    start = np.concatenate([
        index.inst_start[inst_sel],
        np.full(len(kept), 2**62, np.int64),  # pending after running
    ])
    perm = np.lexsort((np.arange(n), start, neg_prio, user))
    order_key = np.empty(n, np.float32)
    order_key[perm] = np.arange(n, dtype=np.float32)

    present = np.unique(user)
    mem_div = np.ones(n_users, np.float32)
    cpu_div = np.ones(n_users, np.float32)
    gpu_div = np.ones(n_users, np.float32)
    for code in present:
        share = store.get_share(index.users.names[code], pool.name)
        mem_div[code] = min(share.mem, BIG)
        cpu_div[code] = min(share.cpus, BIG)
        gpu_div[code] = min(share.gpus, BIG)

    pad_t = bucket_size(n)
    # same data-plane accounting as the full encoder (ranking.rank_pool):
    # DRU columns are their own transfer family; with device residency
    # each column reuses its resident device copy when content is
    # unchanged (device_state.resident_array — zero re-upload)
    fam = data_plane.FAM_DRU
    if device_state is not None:
        def put(name, arr):
            return device_state.resident_array(pool.name, "dru." + name,
                                               arr, family=fam)
    else:
        def put(name, arr):
            return data_plane.h2d(arr, family=fam)
    data_plane.note_padding("dru", (pad_t,), valid_cells=n,
                            padded_cells=pad_t)
    tasks = DruTasks(
        user=put("user", pad_to(user, pad_t)),
        mem=put("mem", pad_to(mem.astype(np.float32), pad_t)),
        cpus=put("cpus", pad_to(cpus.astype(np.float32), pad_t)),
        gpus=put("gpus", pad_to(gpus.astype(np.float32), pad_t)),
        order_key=put("order_key", pad_to(order_key, pad_t, fill=BIG)),
        valid=put("valid", pad_to(np.ones(n, bool), pad_t, fill=False)),
    )
    result = dru_rank(
        tasks,
        put("mem_div", mem_div), put("cpu_div", cpu_div),
        put("gpu_div", gpu_div),
        gpu_mode=(pool.dru_mode == DruMode.GPU),
    )
    kernel_order = np.asarray(result.order)
    dru = np.asarray(result.dru)
    data_plane.note_d2h(kernel_order.nbytes + dru.nbytes, family=fam)

    # pending positions in kernel order -> job objects
    pend_positions = kernel_order[(kernel_order >= n_run)
                                  & (kernel_order < n)]
    rows_in_order = kept[pend_positions - n_run]
    ranked_jobs = [store.jobs[index.uuids[r]] for r in rows_in_order]
    dru_map = {
        job.uuid: float(dru[pos])
        for job, pos in zip(ranked_jobs, pend_positions)
    }
    return RankedQueue(jobs=ranked_jobs, dru=dru_map, capped=capped,
                       quarantined=quarantined, solve_shape=(pad_t,))
