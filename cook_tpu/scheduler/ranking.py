"""The rank cycle: store state -> DRU kernel -> ordered pending queue.

Reference: `rank-jobs` + `sort-jobs-by-dru-pool`
(/root/reference/scheduler/src/cook/scheduler/scheduler.clj:2057-2296) —
every few seconds, per pool: per-user task lists (running tasks first, then
pending jobs, ordered by (-priority, start-time, id)), quota-capped, DRU
scored, merged into one global fairness order, filtered to pending.

Here the scoring+merge is the `dru_rank` kernel; this module does the
host-side gather/encode and the over-quota capping.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

import jax.numpy as jnp

from cook_tpu.models.entities import DruMode, Job, Pool, Resources
from cook_tpu.models.store import JobStore
from cook_tpu.obs import data_plane
from cook_tpu.ops.common import BIG, bucket_size, pad_to
from cook_tpu.ops.dru import DruTasks, dru_rank


@dataclass
class RankedQueue:
    """Output of one pool's rank cycle."""

    jobs: list[Job]          # pending jobs in fair-share order
    dru: dict[str, float]    # job uuid -> queue dru
    capped: list[str]        # job uuids dropped by quota capping
    quarantined: list[str] = None  # dropped by the offensive-job filter
    # padded task-bucket shape of the DRU kernel call that ranked this
    # queue (None when no kernel ran) — the compile observatory's rank key
    solve_shape: tuple = None

    def __post_init__(self):
        if self.quarantined is None:
            self.quarantined = []


class QuotaWalk:
    """Incremental per-user quota admission over a priority-ordered job
    stream (reference `filter-based-on-user-quota` + `filter-sequential`,
    tools.clj:903/:654).

    Snapshot of running usage is taken at construction; each admit() call
    accumulates the job's demand onto the user's cumulative usage and
    answers whether the user stays within quota on every dimension.
    Take-while semantics per user: since usage only grows along the walk,
    the first over-quota job closes the user's queue (a later smaller job
    must not jump it) — which is exactly the reference's state-threading
    through rejected jobs, monotonicity collapsed into a closed set.

    Used at RANK time to cap the queue and again at MATCH time with a
    fresh snapshot (`pending-jobs->considerable-jobs`, scheduler.clj:729)
    so launches or quota changes between rank ticks cannot push a user
    over quota."""

    def __init__(self, store: JobStore, pool: str):
        self.store = store
        self.pool = pool
        self.usage = store.user_usage(pool)
        self.running_counts: dict[str, int] = {}
        for job in store.running_jobs(pool):
            self.running_counts[job.user] = (
                self.running_counts.get(job.user, 0) + 1)
        # per-user cumulative (mem, cpus, gpus, count) tuples + a quota
        # cache — admit() is called once per pending job per cycle
        self.quotas: dict[str, tuple[float, float, float, int]] = {}
        self.cum: dict[str, tuple[float, float, float, int]] = {}
        self.closed: set[str] = set()

    def admit(self, job: Job) -> bool:
        user = job.user
        if user in self.closed:
            return False
        q = self.quotas.get(user)
        if q is None:
            quota = self.store.get_quota(user, self.pool)
            q = (quota.resources.mem, quota.resources.cpus,
                 quota.resources.gpus, quota.count)
            self.quotas[user] = q
        state = self.cum.get(user)
        if state is None:
            u = self.usage.get(user)
            state = ((u.mem, u.cpus, u.gpus) if u is not None
                     else (0.0, 0.0, 0.0)) + (
                self.running_counts.get(user, 0),)
        r = job.resources
        new_state = (state[0] + r.mem, state[1] + r.cpus,
                     state[2] + r.gpus, state[3] + 1)
        if (new_state[3] <= q[3] and new_state[0] <= q[0]
                and new_state[1] <= q[1] and new_state[2] <= q[2]):
            self.cum[user] = new_state
            return True
        self.closed.add(user)
        return False


def _quota_cap(
    store: JobStore,
    pool: str,
    pending: list[Job],
) -> tuple[list[Job], list[str]]:
    """Drop pending jobs that would exceed their user's quota given running
    usage + earlier pending jobs (reference `limit-over-quota-jobs` +
    `filter-based-on-quota`, scheduler.clj:2057-2157).  `pending` must be in
    per-user priority order."""
    walk = QuotaWalk(store, pool)
    kept, capped = [], []
    for job in pending:
        if walk.admit(job):
            kept.append(job)
        else:
            capped.append(job.uuid)
    return kept, capped


def offensive_job_filter(
    max_mem: float, max_cpus: float, max_gpus: float
):
    """Filter for jobs that can never be matched — demands beyond any host
    in the pool (reference: the offensive-job filter at
    scheduler.clj:2198-2257, which quarantines such jobs out of the queue
    instead of letting them clog the head)."""

    def accept(job: Job) -> bool:
        r = job.resources
        return r.mem <= max_mem and r.cpus <= max_cpus and r.gpus <= max_gpus

    return accept


def rank_pool(
    store: JobStore,
    pool: Pool,
    *,
    offensive_job_filter=None,
    predictor=None,
    backfill_weight: float = 0.0,
    backfill_norm_ms: float = 600_000.0,
    device_state=None,
) -> RankedQueue:
    """Rank one pool's pending jobs by cumulative DRU.

    With `predictor` (scheduler/prediction.py) and a positive
    `backfill_weight`, each pending task carries a predicted-duration
    column into the DRU kernel: fraction = min(predicted_runtime /
    backfill_norm_ms, 1), no-estimate jobs pinned at 1 (never boosted).
    The kernel adds `weight x fraction` to the DRU before the global
    order sort — short-job backfill as a bounded scoring term
    (arXiv:1106.4985), not a separate pass.  Weight 0 (the default)
    reproduces the unadjusted order exactly."""
    pool_name = pool.name
    pending = store.pending_jobs(pool_name)
    quarantined: list[str] = []
    if offensive_job_filter is not None:
        kept = []
        for j in pending:
            if offensive_job_filter(j):
                kept.append(j)
            else:
                quarantined.append(j.uuid)
        pending = kept

    # order pending per user by (-priority, submit-time, insertion order) —
    # the pending-job part of task->feature-vector (tools.clj:614-641; the
    # reference's final tie-break is the :db/id entity id, i.e. insertion)
    seq = store.job_seq
    pending.sort(key=lambda j: (-j.priority, j.submit_time_ms,
                                seq.get(j.uuid, 0)))
    pending, capped = _quota_cap(store, pool_name, pending)

    running = []
    for job in store.running_jobs(pool_name):
        for inst in store.job_instances(job.uuid):
            if not inst.status.terminal:
                running.append((job, inst))

    t_total = len(running) + len(pending)
    if t_total == 0 or not pending:
        return RankedQueue(jobs=[], dru={}, capped=capped, quarantined=quarantined)

    users = sorted(
        {j.user for j in pending} | {j.user for j, _ in running}
    )
    user_idx = {u: i for i, u in enumerate(users)}

    # Build the flat task tensor: running tasks sort before pending ones for
    # the same user/priority (start-time < infinity), matching the
    # reference's feature vector.
    n = t_total
    user = np.empty(n, dtype=np.int32)
    mem = np.empty(n, dtype=np.float32)
    cpus = np.empty(n, dtype=np.float32)
    gpus = np.empty(n, dtype=np.float32)
    neg_prio = np.empty(n, dtype=np.int64)
    start = np.empty(n, dtype=np.int64)
    is_pending = np.zeros(n, dtype=bool)
    job_refs: list[Job] = []
    for i, (job, inst) in enumerate(running):
        user[i] = user_idx[job.user]
        mem[i], cpus[i], gpus[i] = (job.resources.mem, job.resources.cpus,
                                    job.resources.gpus)
        neg_prio[i] = -job.priority
        start[i] = inst.start_time_ms
        job_refs.append(job)
    for k, job in enumerate(pending):
        i = len(running) + k
        user[i] = user_idx[job.user]
        mem[i], cpus[i], gpus[i] = (job.resources.mem, job.resources.cpus,
                                    job.resources.gpus)
        neg_prio[i] = -job.priority
        start[i] = 2**62  # pending sorts after running at equal priority
        is_pending[i] = True
        job_refs.append(job)

    # per-user order key: global lexicographic position (host-side lexsort;
    # preserves (-priority, start, submit-order) within each user)
    perm = np.lexsort((np.arange(n), start, neg_prio, user))
    order_key = np.empty(n, dtype=np.float32)
    order_key[perm] = np.arange(n, dtype=np.float32)

    mem_div = np.empty(len(users), dtype=np.float32)
    cpu_div = np.empty(len(users), dtype=np.float32)
    gpu_div = np.empty(len(users), dtype=np.float32)
    for u, i in user_idx.items():
        share = store.get_share(u, pool_name)
        mem_div[i] = min(share.mem, BIG)
        cpu_div[i] = min(share.cpus, BIG)
        gpu_div[i] = min(share.gpus, BIG)

    # predicted-duration backfill column: pending tasks with an estimate
    # get fraction = min(est / norm, 1); everything else (running tasks,
    # cold keys) pins at 1.0 — neutral-worst, so an unestimated job is
    # never boosted past an estimated one
    backfill = None
    if predictor is not None and backfill_weight > 0:
        backfill = np.ones(n, dtype=np.float32)
        norm = max(float(backfill_norm_ms), 1.0)
        for k, job in enumerate(pending):
            est = predictor.predict_runtime_ms(job.user, job.command)
            if est is not None:
                backfill[len(running) + k] = min(est / norm, 1.0)

    pad_t = bucket_size(n)
    # DRU columns are their own data-plane family: the rank cycle's
    # transfers are the second-largest per-cycle flow after the match
    # tensors.  With device residency (scheduler/device_state.py) each
    # column stays resident and re-uploads only when its content
    # changed — an unchanged queue's rank cycle moves zero DRU bytes
    fam = data_plane.FAM_DRU
    if device_state is not None:
        def put(name, arr):
            return device_state.resident_array(pool_name, "dru." + name,
                                               arr, family=fam)
    else:
        def put(name, arr):
            return data_plane.h2d(arr, family=fam)
    data_plane.note_padding("dru", (pad_t,), valid_cells=n,
                            padded_cells=pad_t)
    tasks = DruTasks(
        user=put("user", pad_to(user, pad_t)),
        mem=put("mem", pad_to(mem, pad_t)),
        cpus=put("cpus", pad_to(cpus, pad_t)),
        gpus=put("gpus", pad_to(gpus, pad_t)),
        order_key=put("order_key", pad_to(order_key, pad_t, fill=BIG)),
        valid=put("valid", pad_to(np.ones(n, dtype=bool), pad_t,
                                  fill=False)),
    )
    result = dru_rank(
        tasks,
        put("mem_div", mem_div),
        put("cpu_div", cpu_div),
        put("gpu_div", gpu_div),
        gpu_mode=(pool.dru_mode == DruMode.GPU),
        backfill=(put("backfill", pad_to(backfill, pad_t, fill=1.0))
                  if backfill is not None else None),
        backfill_weight=(jnp.float32(backfill_weight)
                         if backfill is not None else None),
    )
    order = np.asarray(result.order[:])
    dru = np.asarray(result.dru[:])
    data_plane.note_d2h(order.nbytes + dru.nbytes, family=fam)

    ranked_jobs: list[Job] = []
    dru_map: dict[str, float] = {}
    for pos in order:
        if pos >= n or not is_pending[pos]:
            continue
        job = job_refs[pos]
        ranked_jobs.append(job)
        dru_map[job.uuid] = float(dru[pos])
    return RankedQueue(jobs=ranked_jobs, dru=dru_map, capped=capped,
                       quarantined=quarantined, solve_shape=(pad_t,))
