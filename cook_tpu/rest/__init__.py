"""REST API layer (aiohttp)."""
from cook_tpu.rest.api import ApiConfig, CookApi, run_server  # noqa: F401
