"""REST API: the full client-facing surface.

Reference: cook.rest.api (/root/reference/scheduler/src/cook/rest/api.clj,
routes at :3649-4016).  Same resources and JSON shapes, served with aiohttp:

  /rawscheduler (deprecated alias), /jobs[/:uuid], /instances[/:uuid],
  /group, /share, /quota, /usage, /retry, /queue, /running, /list,
  /unscheduled_jobs, /stats/instances, /pools, /settings, /info,
  /failure_reasons, /progress/:uuid, /metrics, /compute-clusters,
  /incremental-config, /shutdown-leader.

Auth mirrors the reference's pluggable schemes in spirit: the requesting
user comes from HTTP basic auth or the X-Cook-Requesting-User dev header
(the reference's :one-user / :http-basic dev modes), with X-Cook-Impersonate
honored for configured admins (rest/impersonation.clj).
"""
from __future__ import annotations

import dataclasses
import json
import re
import statistics
from dataclasses import dataclass
from typing import Optional

from aiohttp import web

from cook_tpu.cluster.base import ClusterState
from cook_tpu.models.entities import (
    Application,
    Checkpoint,
    Container,
    Group,
    GroupPlacementType,
    HostPlacement,
    Instance,
    Job,
    JobConstraint,
    ConstraintOperator,
    Quota,
    Resources,
    Share,
    StragglerHandling,
    job_display,
    new_uuid,
)
from cook_tpu.models.reasons import _REASONS, REASONS_BY_CODE
from cook_tpu.models.store import JobStore, TransactionVetoed
from cook_tpu.shard.router import MisroutedKey
from cook_tpu.obs.contention import (
    ContentionObservatory,
    ContentionParams,
    EndpointTelemetry,
)
from cook_tpu.scheduler.core import Scheduler
from cook_tpu.scheduler.plugins import PluginRegistry
from cook_tpu.scheduler.queue_limit import QueueLimitChecker
from cook_tpu.scheduler.ratelimit import TokenBucketRateLimiter, UnlimitedRateLimiter
from cook_tpu.txn import TransactionLog, TxnOutcome
from cook_tpu.utils.metrics import global_registry


@dataclass
class ApiConfig:
    default_pool: str = "default"
    max_job_mem: float = 512_000.0       # MB
    max_job_cpus: float = 512.0
    max_job_gpus: float = 64.0
    max_retries_limit: int = 200
    # largest accepted gang (gang_size=k all-or-nothing placement,
    # scheduler/gang.py) — bounded by what one topology block can hold
    max_gang_size: int = 64
    admins: tuple = ("admin",)
    version: str = "cook-tpu-0.1.0"
    submission_rate_per_minute: float = 0.0  # 0 = unlimited
    # origins allowed to make credentialed cross-origin requests
    # (reference: rest/cors.clj).  Entries are exact origins, or regexes
    # when prefixed with "re:" ("re:https://.*\\.corp\\.example") — exact
    # entries are never regex-interpreted, so an unescaped "." cannot let
    # lookalike origins through.  Empty = CORS disabled; reflecting the
    # request Origin with Allow-Credentials would let any website issue
    # credentialed requests.
    cors_origins: tuple = ()
    # injectable request authenticator (rest/auth.py); None = the
    # permissive dev stack (basic auth, then dev header, then anonymous)
    authenticator: object = None
    # shared secret executors present (X-Cook-Executor-Token) on their
    # heartbeat/progress posts; when set, those endpoints are only
    # auth-exempt for callers carrying it — without it any network peer
    # could spoof liveness/progress under a strict authenticator
    executor_token: str = ""
    # sync-ack replication (the reference's durable-on-ack semantics,
    # datomic.clj:79 transact-with-retries: a write survives leader death
    # the moment the REST call returns).  When enabled, EVERY mutating
    # endpoint (submit, kill, retry, share/quota, group ops, pool moves,
    # config updates — all committed through cook_tpu.txn) blocks until
    # >= replication_min_acks standbys have ACKed a sequence number
    # covering the commit, or the timeout lapses — a timeout still
    # commits (the write is applied and journaled locally) but the
    # response carries "replicated": false (JSON bodies) or
    # X-Cook-Replicated: false (204s) so callers know the durability
    # bound was not met.
    replication_sync_ack: bool = False
    replication_min_acks: int = 1
    replication_ack_timeout_s: float = 5.0
    # acks older than this stop counting toward min_acks (and are
    # pruned): a decommissioned standby's last ack must not satisfy the
    # durability bound forever.  <= 0 disables liveness qualification.
    replication_ack_liveness_s: float = 30.0
    # thresholds for the control-plane contention health checks
    # (store-lock-saturation, fsync-stall, replication-lag,
    # commit-ack-slo-burn, job-starvation); None = defaults
    contention: Optional[ContentionParams] = None
    # overload load shedding (cook_tpu/faults/reactions.py): while
    # commit-ack SLO burn or store-lock saturation is active, heavy read
    # endpoints answer 429 + Retry-After instead of piling onto the
    # saturated store lock; mutations are never shed
    load_shedding: bool = True
    shed_retry_after_s: float = 5.0
    # POST /debug/faults (arm/disarm the process fault schedule) — OFF
    # by default and admin-only when on; never enable in production
    # outside a chaos drill (docs/resilience.md)
    fault_injection: bool = False
    # replica-served reads (cook_tpu/shard/replica.py): a non-leader
    # with a journal follower serves heavy read endpoints from its
    # replayed state, advertising bounded staleness
    # (X-Cook-Staleness-Ms + staleness_ms in JSON-object bodies).
    # Above the freshness ceiling the read falls back to the leader
    # (307); a replica that stopped applying for replica_refuse_after_s
    # refuses reads (503) instead of serving stale forever.
    replica_reads: bool = True
    replica_staleness_ceiling_ms: float = 5000.0
    replica_refuse_after_s: float = 30.0


class CookApi:
    def __init__(
        self,
        store: JobStore,
        scheduler: Optional[Scheduler] = None,
        config: Optional[ApiConfig] = None,
        plugins: Optional[PluginRegistry] = None,
        txn: Optional[TransactionLog] = None,
        history=None,
    ):
        self.store = store
        self.scheduler = scheduler
        self.config = config or ApiConfig()
        self.plugins = plugins or PluginRegistry()
        # the durable commit pipeline every mutating handler goes through
        # (components.py wires the journal in; a bare CookApi commits
        # in-memory only)
        self.txn = txn or TransactionLog(store)
        self.queue_limits = QueueLimitChecker(store)
        if self.config.submission_rate_per_minute > 0:
            self.submission_limiter = TokenBucketRateLimiter(
                tokens_replenished_per_minute=self.config.submission_rate_per_minute,
                bucket_size=self.config.submission_rate_per_minute,
                clock=store.clock,
            )
        else:
            self.submission_limiter = UnlimitedRateLimiter()
        if self.config.authenticator is not None:
            self.authenticator = self.config.authenticator
        else:
            from cook_tpu.rest.auth import dev_default_authenticator

            self.authenticator = dev_default_authenticator()
        self.leader = True
        self.leader_url = ""  # set on standbys for leader proxying
        # replication feed identity: event sequence numbers are only
        # comparable within one leader history, so every process stamps
        # its feed with a fresh incarnation; followers force a snapshot
        # bootstrap when it changes (a deposed leader may hold committed
        # events the new leader never saw — silent divergence otherwise)
        import uuid as _uuid

        self.incarnation = _uuid.uuid4().hex[:12]
        # follower -> highest event seq it has confirmed applied AND
        # journaled locally (POST /replication/ack with durable=true);
        # read by sync-ack commits.  Acks from followers without local
        # durability (no journal/data_dir) are tracked in
        # replication_ack_meta only — they must not satisfy min_acks,
        # or "replicated: true" would not mean what it says.
        self.replication_acks: dict[str, int] = {}
        # sharded control plane: shard -> follower -> highest durable
        # acked seq ON THAT SHARD (sequence numbers are per shard).
        # Unsharded acks land under shard 0, so the same await path
        # serves both layouts.
        self.replication_shard_acks: dict[int, dict[str, int]] = {}
        # (follower, shard) -> {seq, durable, time(monotonic)} for every
        # ack seen; liveness pruning keys off `time`
        self.replication_ack_meta: dict[str, dict] = {}
        # replica-served reads (cook_tpu/shard/replica.py): a standby's
        # wiring (components.py) points this at its journal follower's
        # staleness_view; None = no replica-read surface on this node
        self.staleness_fn = None
        # long-poll/sync-ack wakeups: per-waiter events, set from the
        # store's watcher thread via call_soon_threadsafe
        self._repl_waiters: set = set()
        self._repl_loop = None
        # merged-trace process identity (obs/distributed.py): the mp
        # worker stamps "worker-gN" here so this node's REST-side spans
        # route to its pid track; None on single-process servers
        self.process_label = None
        # control-plane contention observatory (cook_tpu/obs/contention):
        # per-route REST telemetry (fed by the outermost middleware),
        # store-lock / journal / replication / commit-ack attribution —
        # served at GET /debug/contention and folded into /debug/health
        self.endpoints = EndpointTelemetry()
        self.contention = ContentionObservatory(
            store,
            params=self.config.contention,
            endpoints=self.endpoints,
            journal_fn=lambda: getattr(
                getattr(self.txn, "journal", None), "telemetry", None),
            replication_meta_fn=lambda: self.replication_ack_meta,
            starvation_fn=self._starvation_view,
        )
        if hasattr(self.txn, "shard_view"):
            # sharded pipeline (cook_tpu/shard/ShardedTransactionLog):
            # per-shard lock/journal/commit attribution rides the same
            # /debug/contention surface
            self.contention.shards_fn = (
                lambda: self.txn.shard_view(self.contention.params))
        self._replica_refusals = global_registry.counter(
            "shard.replica_reads_refused",
            "replica reads refused because the replica stopped applying")
        self._replica_fallbacks = global_registry.counter(
            "shard.replica_reads_fallback",
            "replica reads redirected to the leader over the staleness "
            "ceiling")
        # overload reaction: heavy reads shed while the SLO burns
        # (cook_tpu/faults/reactions.py; also the scheduler's admission-
        # scaleback signal — components.py wires overload_fn to this)
        from cook_tpu.faults.reactions import LoadShedder

        self.shedder = LoadShedder(
            self.contention,
            retry_after_s=self.config.shed_retry_after_s)
        # incident observatory (cook_tpu/obs/incident.py): adopt the
        # scheduler's recorder (it already collects cycles/trace/faults)
        # or stand up a control-plane-only one (proxy/standby nodes still
        # capture contention-shaped incidents); either way this layer
        # contributes the /debug/contention snapshot as bundle evidence
        from cook_tpu.obs.incident import (IncidentRecorder,
                                           add_default_collectors)

        self.incidents = getattr(scheduler, "incidents", None)
        self.profiler = getattr(scheduler, "profiler", None)
        if self.incidents is None:
            self.incidents = add_default_collectors(IncidentRecorder())
        self.incidents.add_collector("contention", self.contention.snapshot)
        # durable multi-resolution metrics history (cook_tpu/obs/tsdb.py):
        # components.py passes the data_dir-backed, sampler-started
        # instance; a bare CookApi gets a memory-only one so
        # GET /debug/history always serves (tests/smoke force sample
        # ticks through it).  Every bundle embeds the pre-incident slice
        # of the key series — "what changed before it broke" without a
        # live node.
        if history is None:
            from cook_tpu.obs.tsdb import MetricsHistory

            history = MetricsHistory()
        self.history = history
        self.incidents.add_collector("history", self.history.incident_slice)
        # fleet observatory (cook_tpu/obs/fleet.py): the leader's wiring
        # (components.py) attaches a started FleetObservatory; None =
        # this node does not federate (GET /debug/fleet says so)
        self.fleet = None
        # fairness observatory (cook_tpu/obs/fairness.py): adopt the
        # scheduler's (rank/rebalance cycles feed it) or stand up a
        # local one on scheduler-less nodes (mp shard-group workers) so
        # /debug/fairness scatter-merges fleet-wide and the incident
        # bundle carries fairness evidence either way
        self.fairness = getattr(scheduler, "fairness", None)
        if self.fairness is None:
            from cook_tpu.obs.fairness import FairnessObservatory

            self.fairness = FairnessObservatory(clock=store.clock)
            self.fairness.recover(store)
            self.incidents.add_collector("fairness", self.fairness.collector)

    def _starvation_view(self) -> dict:
        from cook_tpu.scheduler.monitor import starvation_stats

        return {pool: starvation_stats(self.store, pool)
                for pool in sorted(self.store.pools)}

    # ------------------------------------------------------------ app wiring

    def build_app(self) -> web.Application:
        # endpoint telemetry sits OUTSIDE auth so rejected requests are
        # measured too (an auth-storm is control-plane load like any
        # other); aiohttp applies middlewares in list order
        app = web.Application(middlewares=[self._endpoint_middleware,
                                           self._auth_middleware,
                                           self._replica_middleware])
        r = app.router
        for path in ("/rawscheduler", "/jobs"):
            r.add_get(path, self.get_jobs)
            r.add_post(path, self.post_jobs)
            r.add_delete(path, self.delete_jobs)
        r.add_get("/jobs/{uuid}", self.get_job)
        r.add_get("/instances/{uuid}", self.get_instance)
        r.add_get("/instances", self.get_instances)
        r.add_delete("/instances", self.delete_instances)
        r.add_get("/group", self.get_groups)
        r.add_delete("/group", self.delete_groups)
        r.add_get("/share", self.get_share)
        r.add_post("/share", self.post_share)
        r.add_delete("/share", self.delete_share)
        r.add_get("/quota", self.get_quota)
        r.add_post("/quota", self.post_quota)
        r.add_delete("/quota", self.delete_quota)
        r.add_get("/usage", self.get_usage)
        r.add_post("/pool-move", self.post_pool_move)
        r.add_get("/retry", self.get_retry)
        r.add_post("/retry", self.post_retry)
        r.add_put("/retry", self.post_retry)
        r.add_get("/queue", self.get_queue)
        r.add_get("/running", self.get_running)
        r.add_get("/list", self.get_list)
        r.add_get("/unscheduled_jobs", self.get_unscheduled)
        r.add_get("/stats/instances", self.get_instance_stats)
        r.add_get("/pools", self.get_pools)
        r.add_get("/settings", self.get_settings)
        r.add_get("/info", self.get_info)
        r.add_get("/failure_reasons", self.get_failure_reasons)
        r.add_get("/progress/{uuid}", self.get_progress)
        r.add_post("/progress/{uuid}", self.post_progress)
        r.add_post("/heartbeat/{uuid}", self.post_heartbeat)
        r.add_get("/metrics", self.get_metrics)
        r.add_get("/compute-clusters", self.get_compute_clusters)
        r.add_post("/compute-clusters", self.post_compute_cluster)
        r.add_delete("/compute-clusters/{name}", self.delete_compute_cluster)
        r.add_get("/incremental-config", self.get_incremental_config)
        r.add_post("/incremental-config", self.post_incremental_config)
        r.add_post("/shutdown-leader", self.post_shutdown_leader)
        r.add_get("/replication/journal", self.get_replication_journal)
        r.add_get("/replication/snapshot", self.get_replication_snapshot)
        r.add_post("/replication/ack", self.post_replication_ack)
        r.add_get("/debug", self.get_debug)
        r.add_get("/debug/replica", self.get_debug_replica)
        r.add_get("/debug/health", self.get_debug_health)
        r.add_get("/debug/contention", self.get_debug_contention)
        r.add_get("/debug/faults", self.get_debug_faults)
        r.add_post("/debug/faults", self.post_debug_faults)
        r.add_get("/debug/elastic", self.get_debug_elastic)
        r.add_get("/debug/device", self.get_debug_device)
        r.add_get("/debug/predictions", self.get_debug_predictions)
        r.add_get("/debug/cycles", self.get_debug_cycles)
        r.add_get("/debug/cycles/{cycle_id}", self.get_debug_cycle)
        r.add_get("/debug/spans", self.get_debug_spans)
        r.add_get("/debug/trace", self.get_debug_trace)
        r.add_get("/debug/incidents", self.get_debug_incidents)
        r.add_get("/debug/incidents/{incident_id}", self.get_debug_incident)
        r.add_get("/debug/history", self.get_debug_history)
        r.add_get("/debug/fleet", self.get_debug_fleet)
        r.add_get("/debug/fairness", self.get_debug_fairness)
        r.add_get("/debug/profile", self.get_debug_profile)
        r.add_post("/debug/profile", self.post_debug_profile)
        r.add_get("/jobs/{uuid}/timeline", self.get_job_timeline)
        r.add_get("/swagger-docs", self.get_swagger_docs)
        r.add_get("/swagger-ui", self.get_swagger_ui)
        self._openapi = _build_openapi(app)
        return app

    async def get_swagger_docs(self, request: web.Request) -> web.Response:
        """Machine-readable API description (reference serves swagger at
        the same paths, rest/api.clj:3650)."""
        return web.json_response(self._openapi)

    async def get_swagger_ui(self, request: web.Request) -> web.Response:
        rows = []
        for path, methods in sorted(self._openapi["paths"].items()):
            for method, info in sorted(methods.items()):
                rows.append(
                    f"<tr><td><code>{method.upper()}</code></td>"
                    f"<td><code>{path}</code></td>"
                    f"<td>{info.get('summary', '')}</td></tr>"
                )
        html = (
            "<html><head><title>cook-tpu API</title></head><body>"
            "<h1>cook-tpu REST API</h1>"
            "<p>Machine-readable spec: <a href='/swagger-docs'>"
            "/swagger-docs</a></p>"
            "<table border=1 cellpadding=4><tr><th>Method</th><th>Path</th>"
            "<th>Handler</th></tr>" + "".join(rows) + "</table></body></html>"
        )
        return web.Response(text=html, content_type="text/html")

    async def get_debug(self, request: web.Request) -> web.Response:
        """Health endpoint (reference components.clj:141): 200 when the
        process serves; includes leadership so load balancers can route
        writes to the leader."""
        return web.json_response({
            "healthy": True,
            "leader": bool(self.scheduler) and self.leader,
        })

    def _recorder(self):
        return getattr(self.scheduler, "recorder", None) \
            if self.scheduler is not None else None

    def _telemetry(self):
        return getattr(self.scheduler, "telemetry", None) \
            if self.scheduler is not None else None

    async def get_debug_health(self, request: web.Request) -> web.Response:
        """Health verdict: the device-telemetry degradations (recompile-
        storm, quality-drift, solve-latency-regression, device-oom-risk)
        merged with the control-plane contention degradations (store-
        lock-saturation, fsync-stall, replication-lag,
        commit-ack-slo-burn, job-starvation), each with per-check
        evidence.  Always 200; `healthy`/`status` carry the verdict so
        probes distinguish "degraded" from "down".  With device telemetry
        disabled (device_telemetry=False, or no scheduler attached — a
        proxy-only node) the device side reports "unobserved" while the
        contention checks still run — the control plane is observable on
        every node."""
        return web.json_response(self.health_verdict())

    def health_verdict(self) -> dict:
        """Compute the MERGED health verdict (device telemetry +
        contention) and report it to the incident observatory — shared by
        the REST handler and the health-watch trigger loop
        (components.py), so incident capture doesn't depend on an
        external prober hitting /debug/health at the right moment."""
        telemetry = self._telemetry()
        if telemetry is None:
            verdict = {
                "healthy": True,
                "status": "unobserved",
                "degradations": [],
                "reasons": [],
                "checks": {},
            }
        else:
            # observe=False: the incident observatory must see ONE
            # verdict per evaluation — the merged one below — or a
            # contention-only degradation would read as an ok->degraded
            # flap on every probe
            verdict = telemetry.health(observe=False)
        degradations, checks = self.contention.evaluate()
        # fairness drift (obs/fairness.py): a sustained Jain-index drop
        # joins the merged verdict the same way the contention half does
        fair_degradations = self.fairness.health_degradations()
        degradations = degradations + fair_degradations
        verdict["degradations"] = verdict["degradations"] + degradations
        verdict["checks"]["contention"] = checks
        verdict["checks"]["fairness"] = self.fairness.health_checks()
        verdict["reasons"] = sorted(
            set(verdict["reasons"]) | {d["reason"] for d in degradations})
        if degradations:
            verdict["healthy"] = False
            verdict["status"] = "degraded"
        # the rollup gauge must reflect the MERGED verdict (the device-
        # side HealthMonitor already set it from its own half)
        global_registry.gauge(
            "obs.health.degraded",
            "1 while /debug/health reports any degradation reason").set(
            0.0 if verdict["healthy"] else 1.0)
        self.incidents.observe(verdict)
        return verdict

    def _shed(self, route: str) -> Optional[web.Response]:
        """Load-shedding gate for heavy read endpoints: 429 + Retry-After
        while a shed-relevant degradation (commit-ack-slo-burn,
        store-lock-saturation) is active.  Mutations and cheap probes
        are never routed through here."""
        if not self.config.load_shedding:
            return None
        verdict = self.shedder.should_shed(route)
        if verdict is None:
            return None
        response = _err(429, verdict["detail"])
        response.headers["Retry-After"] = str(
            max(1, int(verdict["retry_after_s"])))
        return response

    async def get_debug_faults(self, request: web.Request) -> web.Response:
        """The armed fault schedule (rule state + firing counts).
        Readable whenever fault injection is enabled."""
        from cook_tpu import faults

        if not self.config.fault_injection:
            return _err(403, "fault injection is disabled "
                             "(ApiConfig.fault_injection)")
        active = faults.ACTIVE
        return web.json_response({
            "enabled": True,
            "armed": active is not None,
            "schedule": active.to_dict() if active is not None else None,
        })

    async def post_debug_faults(self, request: web.Request) -> web.Response:
        """Arm ({"seed": .., "rules": [...]}) or disarm ({"disarm":
        true}) the process-global fault schedule.  Admin-only, and gated
        behind ApiConfig.fault_injection — this endpoint exists for
        chaos drills (docs/resilience.md), not production traffic."""
        from cook_tpu import faults

        if not self.config.fault_injection:
            return _err(403, "fault injection is disabled "
                             "(ApiConfig.fault_injection)")
        if request["user"] not in self.config.admins:
            return _err(403, f"user {request['user']} is not an admin")
        body = await request.json()
        if body.get("disarm"):
            faults.disarm()
            return web.json_response({"armed": False})
        try:
            schedule = faults.FaultSchedule.from_dict(body)
        except (KeyError, TypeError, ValueError) as e:
            return _err(400, f"bad fault schedule: {e}")
        faults.arm(schedule)
        return web.json_response({"armed": True,
                                  "schedule": schedule.to_dict()})

    async def get_debug_contention(self, request: web.Request
                                   ) -> web.Response:
        """Control-plane contention snapshot (cook_tpu/obs/contention):
        where the write path's time goes — store-lock wait/hold per call
        site (current holder, longest waiter, contention ratio), journal
        append/fsync pipeline, per-follower replication lag, per-route
        REST latency/RPS/in-flight, and the commit-ack SLO burn rate.
        The before/after instrument for the control-plane sharding work
        (ROADMAP item 2)."""
        return web.json_response(self.contention.snapshot())

    async def get_debug_elastic(self, request: web.Request) -> web.Response:
        """Elastic capacity plane state (cook_tpu/elastic/): the durable
        loan ledger, the ledger-derived net adjustment per pool, and the
        planner's recent decisions (interval plans + on-demand reclaims,
        `?limit=` bounds, `?kind=` filters).  The ledger renders even
        when the planner is disabled — a standby's replicated ledger is
        inspectable before promotion."""
        try:
            limit = max(1, int(request.query.get("limit", "50")))
        except ValueError:
            return _err(400, "limit must be an integer")
        elastic = getattr(self.scheduler, "elastic", None) \
            if self.scheduler is not None else None
        body = {
            "enabled": elastic is not None,
            "ledger": self.store.encoded_capacity_ledger(),
            "net": {pool: self.store.net_capacity_adjustment(pool)
                    for pool in sorted(self.store.pools)},
            "plans": (elastic.recorder.records_json(
                limit=limit, kind=request.query.get("kind"))
                if elastic is not None else []),
        }
        return web.json_response(body)

    async def get_debug_device(self, request: web.Request) -> web.Response:
        """Device data-plane observatory (cook_tpu/obs/data_plane.py):
        host<->device transfer totals per tensor family (the matcher's
        CPU-fallback/audit puts bucketed separately under `fallback`),
        the per-pool residency ledger (`rebuild_fraction` — the fraction
        of encode-row bytes freshly recomputed; 1 - this is the traffic
        a device-resident encode cache would remove), padding waste per
        (op, padded bucket), recent per-cycle byte summaries, and the
        roofline rows (FLOPs + bytes accessed per compiled program from
        cost_analysis(), joined with observed warm solve walls).  The
        before/after instrument for ROADMAP item 2(a)."""
        from cook_tpu.obs import data_plane
        from cook_tpu.scheduler import device_state as _device_state

        body = data_plane.LEDGER.snapshot()
        telemetry = self._telemetry()
        body["roofline"] = (telemetry.observatory.cost_stats()
                            if telemetry is not None else [])
        body["device_telemetry"] = telemetry is not None
        # device-resident match state (scheduler/device_state.py):
        # per-pool resident bytes, delta-vs-rebuild counts, update-kernel
        # walls, quantization demotions — the item-2(a) after picture
        # next to the ledger's before picture
        body["device_state"] = _device_state.snapshot_all()
        return web.json_response(body)

    async def get_debug_predictions(self,
                                    request: web.Request) -> web.Response:
        """Prediction-assisted speculation surface (scheduler/
        prediction.py): the runtime predictor's key/observation counts
        and the speculator's dispatch/hit/drop tallies (drop reasons
        included) — the operator view of how much of the match load is
        being served ahead of the cycle clock."""
        scheduler = self.scheduler
        predictor = getattr(scheduler, "predictor", None) \
            if scheduler is not None else None
        speculator = getattr(scheduler, "speculator", None) \
            if scheduler is not None else None
        return web.json_response({
            "enabled": speculator is not None,
            "predictor": (predictor.stats_json()
                          if predictor is not None else None),
            "speculation": (speculator.stats_json()
                            if speculator is not None else None),
        })

    async def get_debug_cycles(self, request: web.Request) -> web.Response:
        """Flight-recorder ring: per-cycle structured decision records
        (per-phase durations, per-job reason codes, preemption victims).
        `?limit=` bounds the reply, `?pool=` filters, `?since=` keeps
        only records with cycle id > since (incremental polling)."""
        recorder = self._recorder()
        if recorder is None:
            return _err(503, "no scheduler/flight recorder attached")
        try:
            limit = int(request.query.get("limit", "50"))
            since = int(request.query.get("since", "0"))
        except ValueError:
            return _err(400, "limit/since must be integers")
        pool = request.query.get("pool")
        return web.json_response({
            "cycles": recorder.records_json(limit=max(1, limit), pool=pool,
                                            since=since),
            "capacity": recorder.capacity,
        })

    async def get_debug_cycle(self, request: web.Request) -> web.Response:
        """One full cycle record by id."""
        recorder = self._recorder()
        if recorder is None:
            return _err(503, "no scheduler/flight recorder attached")
        try:
            cycle_id = int(request.match_info["cycle_id"])
        except ValueError:
            return _err(400, "cycle id must be an integer")
        record = recorder.get_json(cycle_id)
        if record is None:
            return _err(404, f"cycle {cycle_id} not in the recorder ring")
        return web.json_response(record)

    async def get_debug_spans(self, request: web.Request) -> web.Response:
        """Recent span-ring entries; `?txn_id=` filters to one correlation
        id (the client's X-Cook-Txn-Id) so a mutation's spans — REST
        commit, txn apply, store ops — read as one linked trace."""
        from cook_tpu.utils import tracing

        try:
            limit = max(1, int(request.query.get("limit", "100")))
        except ValueError:
            return _err(400, "limit must be an integer")
        txn_id = request.query.get("txn_id")
        spans = tracing.recent_spans(
            limit=tracing.ring_capacity() if txn_id else limit)
        if txn_id:
            spans = [s for s in spans
                     if s.get("tags", {}).get("txn_id") == txn_id][-limit:]
        return web.json_response({"spans": spans})

    async def get_debug_trace(self, request: web.Request) -> web.Response:
        """Span-ring export.  `?format=chrome` (default) renders the ring
        as a Chrome-trace/Perfetto-loadable event file — host threads and
        pools become tracks, every ring tag (txn_id included) rides in
        the event args; `?format=raw` returns the ring entries verbatim.
        `?limit=` bounds how many (newest) spans export; `?txn_id=`
        slices the ring by correlation id first — the per-process half
        of the mp front end's federated trace merge
        (docs/observability.md, cross-process tracing)."""
        from cook_tpu.utils import tracing

        try:
            limit = max(1, int(request.query.get(
                "limit", str(tracing.ring_capacity()))))
        except ValueError:
            return _err(400, "limit must be an integer")
        txn_id = request.query.get("txn_id")
        if txn_id:
            spans = tracing.spans_for_txn(txn_id, limit=limit)
        else:
            spans = tracing.recent_spans(limit=limit)
        fmt = request.query.get("format", "chrome")
        if fmt == "chrome":
            return web.json_response(tracing.chrome_trace(spans=spans))
        if fmt == "raw":
            return web.json_response(
                {"spans": spans, "process": self.process_label,
                 "txn_id": txn_id})
        return _err(400, f"unknown format {fmt!r} (chrome | raw)")

    async def get_debug_incidents(self, request: web.Request
                                  ) -> web.Response:
        """Incident-bundle index: one summary per captured bundle
        (id, wall time, trigger, reasons, recovery stamp), newest last.
        Full bundles at /debug/incidents/{id}."""
        return web.json_response({
            "incidents": self.incidents.bundles(),
            "capacity": self.incidents.capacity,
            "cooldown_s": self.incidents.cooldown_s,
            "dir": self.incidents.dir,
        })

    async def get_debug_incident(self, request: web.Request
                                 ) -> web.Response:
        """One full incident bundle: the degraded verdict plus every
        evidence collector's snapshot (contention, cycle records,
        chrome-trace export, armed faults, profile capture outcome)."""
        incident_id = request.match_info["incident_id"]
        bundle = self.incidents.get(incident_id)
        if bundle is None:
            return _err(404, f"incident {incident_id} not retained")
        return web.json_response(bundle, dumps=lambda d: json.dumps(
            d, default=str))

    async def get_debug_history(self, request: web.Request) -> web.Response:
        """Durable multi-resolution metrics history (cook_tpu/obs/tsdb.py):
        `?metric=` selects series (exact series key, base name, or a
        trailing-`*` prefix), `?since=` bounds the window (epoch seconds;
        negative = relative, -600 = last ten minutes), `?step=` picks the
        resolution (`raw` | `1m` | `10m` — rollup buckets carry
        min/max/mean/last/count).  Without `metric`, serves the series
        index (every tracked series with its point count) — the
        discovery surface `cs history` tab-completes from."""
        metric = request.query.get("metric", "")
        step = request.query.get("step", "raw")
        try:
            since = float(request.query.get("since", "0") or 0)
        except ValueError:
            return _err(400, "since must be a number (epoch seconds, or "
                             "negative for relative)")
        from cook_tpu.obs.tsdb import STEPS

        body = {
            "enabled": True,
            "sample_s": self.history.config.sample_s,
            "steps": list(STEPS),
        }
        if not metric:
            body["series"] = self.history.series_index()
            return web.json_response(body)
        try:
            body.update(self.history.query(metric, since=since, step=step))
        except ValueError as e:
            return _err(400, str(e))
        return web.json_response(body)

    async def get_debug_fleet(self, request: web.Request) -> web.Response:
        """Merged fleet verdict (cook_tpu/obs/fleet.py): one row per
        node (self + every polled peer) with poll-age staleness,
        federation-level reasons (`peer-unreachable` / `peer-degraded`
        with the peer's own reasons attached), and the worst replication
        shard across the fleet.  `enabled: false` on nodes without a
        fleet observatory (non-leaders, or no peers configured)."""
        if self.fleet is None:
            return web.json_response({
                "enabled": False,
                "nodes": [],
                "reasons": [],
                "detail": "no fleet observatory on this node (leader-only "
                          "duty; Settings.peers / fleet_poll_s)",
            })
        return web.json_response(self.fleet.verdict())

    async def get_debug_fairness(self, request: web.Request) -> web.Response:
        """Fairness observatory (cook_tpu/obs/fairness.py): per-(pool,
        user) DRU trajectories (share, quota, usage, DRU score, queued
        depth), the preemption ledger (preemptor/victim users, DRU at
        decision, wasted-work seconds), per-pool rollups + Jain
        fairness index + fragmentation stat.  `?pool=` narrows to one
        pool; `?ledger=` bounds the ledger tail (default 50).  Body is
        pool-keyed so the mp front end scatter-merges shard groups."""
        pool = request.query.get("pool")
        try:
            ledger_limit = int(request.query.get("ledger", "50"))
        except ValueError:
            return _err(400, "ledger must be an integer")
        return web.json_response(
            self.fairness.snapshot(pool=pool, ledger_limit=ledger_limit))

    async def get_debug_profile(self, request: web.Request) -> web.Response:
        """Profile-capture status: the in-flight capture (if any), recent
        captures with their log dirs, and the auto-capture cooldown."""
        if self.profiler is None:
            return web.json_response({"enabled": False,
                                      "reason": "no scheduler attached"})
        return web.json_response({"enabled": True,
                                  **self.profiler.status()})

    async def post_debug_profile(self, request: web.Request) -> web.Response:
        """Start one duration-bounded device profile capture
        ({"duration_s": N}, clamped to the capturer's max).  Admin-only,
        single-flight: a capture already in flight answers 409 with its
        identity instead of corrupting it."""
        if self.profiler is None:
            return _err(503, "no scheduler/profiler attached")
        if request["user"] not in self.config.admins:
            return _err(403, f"user {request['user']} is not an admin")
        body = await request.json() if request.can_read_body else {}
        try:
            duration = float(body.get("duration_s", 0) or 0) or None
        except (TypeError, ValueError):
            return _err(400, "duration_s must be a number")
        result = self.profiler.capture(duration, trigger="rest")
        if result["started"]:
            status = 202
        elif result["reason"] == "capture-in-flight":
            # the documented retry-later case; clients poll GET status
            status = 409
        elif result["reason"].startswith("profiler-error"):
            status = 503
        else:  # bad input (e.g. non-positive duration)
            status = 400
        return web.json_response(result, status=status)

    async def get_job_timeline(self, request: web.Request) -> web.Response:
        """One job's causally-ordered lifecycle: submit, per-cycle
        rank/skip decisions (consecutive same-reason cycles compressed,
        e.g. "12 cycles skipped: insufficient-resources"), launches,
        preemptions, re-queues — with waiting-time attribution and phase
        latencies (cook_tpu/obs/incident.job_timeline)."""
        from cook_tpu.obs.incident import job_timeline

        job = self.store.jobs.get(request.match_info["uuid"])
        if job is None:
            return _err(404, "unknown job")
        return web.json_response(job_timeline(self.store, self._recorder(),
                                              job, fairness=self.fairness))

    @web.middleware
    async def _endpoint_middleware(self, request: web.Request, handler):
        """Per-endpoint REST telemetry: latency / RPS / in-flight /
        error-rate per matched route template (bounded label set — the
        route table, not the workload).  HTTPExceptions ARE responses
        here, counted under their status."""
        import time as _time

        resource = request.match_info.route.resource \
            if request.match_info.route is not None else None
        route = resource.canonical if resource is not None else "__unmatched__"
        method = request.method
        self.endpoints.begin(route, method)
        t0 = _time.perf_counter()
        status = 500
        try:
            response = await handler(request)
            status = response.status
            # server-side phase walls for the mp front end's per-hop
            # attribution (obs/distributed.py): "server" is this
            # response's total service wall (transport = the front
            # end's round-trip minus it); commits add apply / fsync /
            # replication_ack via request["phase_walls"] (_commit)
            from cook_tpu.obs import distributed

            walls = dict(request.get("phase_walls") or {})
            walls["server"] = _time.perf_counter() - t0
            try:
                response.headers[distributed.HOP_WALLS_HEADER] = \
                    distributed.encode_hop_walls(walls)
            except RuntimeError:
                pass  # prepared/streamed response: headers are sealed
            return response
        except web.HTTPException as e:
            status = e.status
            raise
        finally:
            self.endpoints.done(route, method, status,
                                _time.perf_counter() - t0)

    @web.middleware
    async def _auth_middleware(self, request: web.Request, handler):
        # pluggable authenticator stack (components.clj:267-284: spnego /
        # basic / dev one-user, with impersonation wrapping the winner)
        authenticator = self.authenticator
        user = authenticator.authenticate(request)
        if user is None:
            if self._auth_exempt(request):
                # machine endpoints that carry no user credentials: LB
                # health probes and the executor's heartbeat/progress
                # posts (the reference receives these over the backend
                # channel, outside the authed REST stack)
                user = "anonymous"
            else:
                response = _err(401, "authentication required")
                for key, value in authenticator.challenge().items():
                    response.headers[key] = value
                self._apply_cors(request, response)
                return response
        impersonate = request.headers.get("X-Cook-Impersonate")
        if impersonate:
            if user not in self.config.admins:
                return _err(403, f"user {user} may not impersonate")
            user = impersonate
        request["user"] = user
        try:
            response = await handler(request)
        except web.HTTPException as e:
            # HTTPExceptions ARE responses: CORS applies to errors too, or
            # browser JS can't read them and caches can cross-serve them
            self._apply_cors(request, e)
            raise
        except TransactionVetoed as e:
            response = _err(400, str(e))
        except MisroutedKey as e:
            # multi-process runtime (cook_tpu/mp/): this worker does not
            # own the key's shard — a stale front-end route map or a
            # client reading an old shard map.  421 (not 4xx-the-key):
            # the entity may well exist, just not HERE; the caller
            # refreshes its map (GET /debug/shards) and retries.
            response = _err(421, str(e))
            response.headers["X-Cook-Owner-Shard"] = str(e.owner_shard)
        except json.JSONDecodeError as e:
            response = _err(400, f"malformed JSON body: {e}")
        self._apply_cors(request, response)
        return response

    # ------------------------------------------------------ replica reads
    # Heavy read endpoints a non-leader replica serves from its replayed
    # journal, each response advertising bounded staleness
    # (cook_tpu/shard/replica.py has the full contract).
    REPLICA_READ_ROUTES = frozenset((
        "/jobs", "/jobs/{uuid}", "/rawscheduler", "/list", "/running",
        "/unscheduled_jobs", "/stats/instances", "/instances",
        "/instances/{uuid}", "/group", "/usage",
    ))

    def _replica_evaluation(self) -> Optional[dict]:
        """The per-shard staleness decision, or None when this node is
        the leader / has no follower wired."""
        if self.leader or not self.config.replica_reads \
                or self.staleness_fn is None:
            return None
        from cook_tpu.shard.replica import evaluate_staleness

        return evaluate_staleness(
            self.staleness_fn(),
            ceiling_ms=self.config.replica_staleness_ceiling_ms,
            refuse_after_s=self.config.replica_refuse_after_s)

    @web.middleware
    async def _replica_middleware(self, request: web.Request, handler):
        """Replica-read gate + staleness stamping.  Leader (or
        follower-less) nodes pass straight through.  On a replica:
        refusal (stopped applying) and leader fallback (over the
        freshness ceiling) short-circuit heavy reads; served reads —
        including /debug/* — carry X-Cook-Staleness-Ms (worst shard) and
        X-Cook-Shard-Staleness (per-shard split), and JSON-object bodies
        gain a staleness_ms field."""
        verdict = self._replica_evaluation()
        if verdict is None or request.method != "GET":
            return await handler(request)
        resource = request.match_info.route.resource \
            if request.match_info.route is not None else None
        route = resource.canonical if resource is not None else ""
        gated = route in self.REPLICA_READ_ROUTES
        if gated and verdict["action"] == "refuse":
            self._replica_refusals.inc()
            return _err(503, "replica stopped applying the leader's "
                             "journal; refusing stale reads "
                             "(X-Cook-Staleness-Ms unbounded)")
        if gated and verdict["action"] == "fallback":
            if self.leader_url:
                self._replica_fallbacks.inc()
                raise web.HTTPTemporaryRedirect(
                    f"{self.leader_url}{request.path_qs}")
            if verdict["staleness_ms"] == float("inf"):
                # never-synced AND no leader to redirect to: nothing
                # safe to serve
                self._replica_refusals.inc()
                return _err(503, "replica has not caught up with any "
                                 "leader yet and no leader is known")
        response = await handler(request)
        if gated or route.startswith("/debug"):
            self._stamp_staleness(response, verdict)
        return response

    @staticmethod
    def _stamp_staleness(response, verdict: dict) -> None:
        worst = verdict["staleness_ms"]
        worst_txt = "inf" if worst == float("inf") else str(int(worst))
        response.headers["X-Cook-Staleness-Ms"] = worst_txt
        response.headers["X-Cook-Shard-Staleness"] = json.dumps({
            str(shard): ("inf" if ms == float("inf") else int(ms))
            for shard, ms in verdict["shards"].items()})
        if response.content_type == "application/json" and response.body:
            try:
                payload = json.loads(response.body)
            except ValueError:
                return
            if isinstance(payload, dict):
                payload["staleness_ms"] = (
                    None if worst == float("inf") else worst)
                response.body = json.dumps(payload).encode()

    async def get_debug_replica(self, request: web.Request) -> web.Response:
        """Replica-read surface: whether this node serves replica reads,
        the per-shard staleness/stall view, and the decision the gate
        would take right now (serve / fallback / refuse)."""
        verdict = self._replica_evaluation()
        view = self.staleness_fn() if self.staleness_fn is not None else {}
        def clean(row):
            return {k: (None if v == float("inf") else v)
                    for k, v in row.items()}
        return web.json_response({
            "leader": self.leader,
            "replica_reads": self.config.replica_reads,
            "ceiling_ms": self.config.replica_staleness_ceiling_ms,
            "refuse_after_s": self.config.replica_refuse_after_s,
            "shards": {str(s): clean(row)
                       for s, row in sorted(view.items())},
            "decision": (None if verdict is None else {
                "action": verdict["action"],
                "staleness_ms": (None if verdict["staleness_ms"]
                                 == float("inf")
                                 else verdict["staleness_ms"]),
            }),
        })

    def _auth_exempt(self, request: web.Request) -> bool:
        path = request.path
        if path in ("/debug", "/debug/health"):
            # probe endpoints: LB liveness and the telemetry verdict both
            # get scraped by unauthenticated monitors
            return True
        if request.method == "GET" and path == "/metrics":
            return True
        if request.method == "POST" and (path.startswith("/heartbeat/")
                                         or path.startswith("/progress/")):
            token = self.config.executor_token
            if not token:
                return True
            # constant-time: this is the one credential that bypasses
            # strict auth; == would leak a byte-at-a-time timing oracle
            import hmac

            presented = request.headers.get("X-Cook-Executor-Token", "")
            return hmac.compare_digest(presented, token)
        return False

    def _apply_cors(self, request: web.Request, response) -> None:
        """CORS for browser dashboards, allowlist-gated (rest/cors.clj).
        Vary: Origin on EVERY response (success or error): the CORS
        headers differ per Origin, so shared caches must not serve one
        origin's copy (or a no-Origin copy with no CORS headers) to
        another."""
        response.headers.setdefault("Vary", "Origin")
        origin = request.headers.get("Origin")
        if origin and self._origin_allowed(origin):
            response.headers["Access-Control-Allow-Origin"] = origin
            response.headers["Access-Control-Allow-Credentials"] = "true"

    def _origin_allowed(self, origin: str) -> bool:
        for allowed in self.config.cors_origins:
            if allowed.startswith("re:"):
                try:
                    if re.fullmatch(allowed[3:], origin):
                        return True
                except re.error:
                    continue  # invalid pattern never matches (nor 500s)
            elif origin == allowed:
                return True
        return False

    # ------------------------------------------------------- txn commit seam

    async def _run_commit(self, op: str, payload: dict,
                          txn_id: Optional[str]) -> TxnOutcome:
        """Run the (synchronous) commit pipeline in the default executor:
        it ends in an fsync (+ possible retry backoff sleeps), which must
        not stall the event loop — and off-loop commits let the journal's
        group-commit sync() actually merge concurrent commits into one
        disk barrier."""
        import asyncio

        return await asyncio.get_running_loop().run_in_executor(
            None, lambda: self.txn.commit(op, payload, txn_id=txn_id))

    async def _commit(self, request: web.Request, op: str, payload: dict,
                      *, txn_suffix: str = "") -> TxnOutcome:
        """Commit one mutation through the transaction pipeline and, in
        sync-ack mode, await the replication durability bound (the
        datomic.clj:79 durable-on-ack semantics, now for EVERY mutation
        type).  Clients may pass X-Cook-Txn-Id: a retried request with
        the same id is answered from the transaction table, not
        re-applied — on this leader or a promoted standby."""
        import time as _time

        from cook_tpu.obs import distributed
        from cook_tpu.utils import tracing

        txn_id = request.headers.get("X-Cook-Txn-Id") or None
        if txn_id and txn_suffix:
            txn_id = f"{txn_id}:{txn_suffix}"
        t0 = _time.perf_counter()
        outcome = await self._run_commit(op, payload, txn_id)
        outcome.replicated = True
        walls = dict(outcome.phase_walls or {})
        if self.config.replication_sync_ack and not outcome.duplicate:
            t_repl = _time.perf_counter()
            outcome.replicated = await self._await_replication_outcome(
                outcome)
            walls["replication_ack"] = _time.perf_counter() - t_repl
            if not outcome.replicated:
                global_registry.counter(
                    "replication_ack_timeouts",
                    "sync-ack replication bounds missed").inc()
        if walls:
            # picked up by _endpoint_middleware into X-Cook-Hop-Walls
            outcome.phase_walls = walls
            request["phase_walls"] = walls
        # the server-side commit span: parented under the front end's
        # forward span when the request carried X-Cook-Parent-Span
        # (async-safe completed-span recorder — handlers interleave)
        span_tags = {"op": op}
        if self.process_label:
            span_tags["process"] = self.process_label
        tracing.record_span(
            "rest.commit", _time.perf_counter() - t0,
            parent=request.headers.get(distributed.PARENT_SPAN_HEADER),
            txn_id=outcome.txn_id, **span_tags)
        return outcome

    @staticmethod
    def _no_content(outcome: TxnOutcome) -> web.Response:
        """204 for a committed mutation; an unmet replication bound is
        flagged in a header (a 204 carries no body to say it in)."""
        response = web.Response(status=204)
        if outcome.replicated is False:
            response.headers["X-Cook-Replicated"] = "false"
        return response

    # ------------------------------------------------------------------ jobs

    async def post_jobs(self, request: web.Request) -> web.Response:
        user = request["user"]
        # a RETRIED submission (same X-Cook-Txn-Id) must be answered
        # from the transaction table before parsing: the first commit's
        # jobs exist now, so re-parsing would 400 "already exists" on
        # exactly the requests idempotency is for
        txn_id = request.headers.get("X-Cook-Txn-Id")
        if txn_id:
            cached = self.store.txn_results.get(txn_id)
            if cached is not None and cached.get("op") == "jobs/submit":
                return web.json_response(dict(cached.get("result") or {}),
                                         status=201)
        body = await request.json()
        specs = body.get("jobs", [])
        group_specs = body.get("groups", [])
        if not specs:
            return _err(400, "no jobs to schedule")
        if not self.submission_limiter.try_spend(user, len(specs)):
            return _err(429, "job submission rate limit exceeded")

        jobs, groups, parse_err = self.parse_submission(specs, group_specs,
                                                        user)
        if parse_err:
            return _err(400, parse_err)
        import time as _time

        t_commit = _time.perf_counter()
        try:
            outcome = await self._commit(
                request, "jobs/submit",
                {"jobs": jobs, "groups": list(groups.values())})
        except TransactionVetoed as e:
            return _err(400, str(e))
        if not outcome.duplicate:
            # submit -> commit-ack SLO: apply + journal fsync + (sync-ack
            # mode) replication wait, as the submitting client experiences
            # it.  Idempotent replays answer from the txn table in ~0s and
            # would flood the histogram with samples no durable commit saw.
            from cook_tpu.scheduler.monitor import observe_commit_ack

            commit_ack_s = _time.perf_counter() - t_commit
            observe_commit_ack(commit_ack_s)
            # the same sample, windowed: the contention observatory's
            # SLO burn-rate evaluation (commit-ack-slo-burn)
            self.contention.commit_ack.observe(commit_ack_s)
            global_registry.counter(
                "jobs_submitted", "jobs accepted via POST /jobs").inc(
                len(jobs))
        body = dict(outcome.result or {"jobs": [j.uuid for j in jobs]})
        if outcome.replicated is False:
            # durable-on-ack (datomic.clj:79): the commit stands, but the
            # standby durability bound was not met — say so
            body["replicated"] = False
        return web.json_response(body, status=201)

    def parse_submission(
            self, specs: list, group_specs: list, user: str,
    ) -> tuple[list, dict, Optional[str]]:
        """Parse + validate one submit batch into entity objects:
        group parsing, pool selection/acceptance, submission plugins,
        job parsing/adjustment, and the per-pool queue-limit check.
        Returns (jobs, groups, error) with error None on success — the
        shared seam under POST /jobs and the mp runtime's 2PC prepare
        phase (cook_tpu/mp/worker.py), which must veto with EXACTLY the
        conditions a single-process submit would 400 on.  Rate limiting
        and idempotency stay with the caller (they are per-entry-point,
        not per-validation)."""
        groups: dict[str, Group] = {}
        for gs in group_specs:
            group, err = self._parse_group(gs)
            if err:
                return [], {}, err
            groups[group.uuid] = group
        jobs = []
        pools_counted: dict[str, int] = {}
        for spec in specs:
            pool = self.plugins.pool_selector.select_pool(
                spec, self.config.default_pool
            )
            pool_ent = self.store.pools.get(pool)
            if pool_ent is None or not pool_ent.accepts_submissions:
                return [], {}, f"pool {pool} does not accept submissions"
            result = self.plugins.validate_submission(spec, user, pool)
            if not result.accepted:
                return [], {}, result.message or "rejected by plugin"
            spec = self.plugins.modify_submission(spec, user, pool)
            try:
                job, err = self._parse_job(spec, user, pool, groups)
            except (ValueError, TypeError) as e:
                # non-numeric mem/cpus/disk/ports etc.: a client error,
                # not a server fault
                job, err = None, f"malformed job field: {e}"
            if err:
                return [], {}, err
            # JobAdjusters (plugins/definitions.clj JobAdjuster, e.g. the
            # pool mover) may rewrite the parsed job; an adjusted pool
            # must still exist and accept work, else revert ONLY the pool
            # (other adjusters' changes survive)
            adjusted = self.plugins.adjust(job)
            if adjusted.pool != job.pool:
                dest = self.store.pools.get(adjusted.pool)
                if dest is None or not dest.accepts_submissions:
                    adjusted = adjusted.with_(pool=job.pool)
            job = adjusted
            jobs.append(job)
            pools_counted[job.pool] = pools_counted.get(job.pool, 0) + 1
        # gang batches must be complete (store._validate_gangs re-checks
        # under the txn lock; this mirrors it so the mp 2PC prepare phase
        # vetoes with the same message a single-process 400 carries)
        gangs: dict[str, list[Job]] = {}
        for job in jobs:
            if job.gang_size > 0 and job.group_uuid:
                gangs.setdefault(job.group_uuid, []).append(job)
        for guuid, members in gangs.items():
            k = members[0].gang_size
            if any(j.gang_size != k for j in members):
                return [], {}, f"group {guuid}: members disagree on gang_size"
            if any(j.pool != members[0].pool for j in members):
                return [], {}, f"group {guuid}: gang members span pools"
            if len(members) != k:
                return [], {}, (
                    f"group {guuid}: gang_size {k} but {len(members)} "
                    "member(s) in the batch (gangs submit atomically)")
        for pool, count in pools_counted.items():
            limit_err = self.queue_limits.check_submission(user, pool, count)
            if limit_err:
                return [], {}, limit_err
        return jobs, groups, None

    def _parse_job(self, spec: dict, user: str, pool: str,
                   groups: dict[str, Group]) -> tuple[Optional[Job], Optional[str]]:
        uuid = spec.get("uuid") or new_uuid()
        if uuid in self.store.jobs:
            return None, f"job {uuid} already exists"
        command = spec.get("command", "")
        if not command:
            return None, "command is required"
        mem = float(spec.get("mem", 128.0))
        cpus = float(spec.get("cpus", 1.0))
        gpus = float(spec.get("gpus", 0.0))
        if mem <= 0 or mem > self.config.max_job_mem:
            return None, f"mem {mem} out of range (0, {self.config.max_job_mem}]"
        if cpus <= 0 or cpus > self.config.max_job_cpus:
            return None, f"cpus {cpus} out of range (0, {self.config.max_job_cpus}]"
        if gpus < 0 or gpus > self.config.max_job_gpus:
            return None, f"gpus {gpus} out of range [0, {self.config.max_job_gpus}]"
        # disk: a bare number, or {"request": MiB, "type": "pd-ssd"}
        # (disk-host-constraint, constraints.clj:164)
        disk_spec = spec.get("disk", 0.0)
        if isinstance(disk_spec, dict):
            disk = float(disk_spec.get("request", 0.0))
            disk_type = str(disk_spec.get("type", ""))
        else:
            disk = float(disk_spec)
            disk_type = ""
        ports = int(spec.get("ports", 0))
        if not 0 <= ports <= 1000:
            return None, f"ports {ports} out of range [0, 1000]"
        max_retries = int(spec.get("max_retries", 1))
        if not 0 < max_retries <= self.config.max_retries_limit:
            return None, f"max_retries {max_retries} out of range"
        priority = int(spec.get("priority", 50))
        if not 0 <= priority <= 100:
            return None, f"priority {priority} out of range [0, 100]"
        constraints = []
        for c in spec.get("constraints", []):
            # ["attribute", "EQUALS", "pattern"]
            if len(c) != 3 or str(c[1]).upper() != "EQUALS":
                return None, f"unsupported constraint {c}"
            constraints.append(
                JobConstraint(attribute=c[0],
                              operator=ConstraintOperator.EQUALS,
                              pattern=c[2])
            )
        gang_size = int(spec.get("gang_size", 0))
        if gang_size < 0 or gang_size == 1 \
                or gang_size > self.config.max_gang_size:
            return None, (f"gang_size {gang_size} out of range "
                          f"(0 or [2, {self.config.max_gang_size}])")
        group_uuid = spec.get("group")
        if gang_size and not group_uuid:
            return None, "gang_size requires a group"
        if group_uuid and group_uuid not in groups \
                and group_uuid not in self.store.groups:
            # implicit group creation (reference: make-default-host-placement)
            groups[group_uuid] = Group(uuid=group_uuid)
        if gang_size and group_uuid in groups:
            # gang members need k DISTINCT hosts: an implicit (or
            # placement-less) gang group is promoted to unique-host so
            # validate_group_assignments enforces distinctness
            g = groups[group_uuid]
            if g.host_placement.type == GroupPlacementType.ALL:
                groups[group_uuid] = dataclasses.replace(
                    g, host_placement=HostPlacement(
                        type=GroupPlacementType.UNIQUE))
        container = None
        cspec = spec.get("container")
        if cspec:
            docker = cspec.get("docker", cspec)
            container = Container(
                image=docker.get("image", ""),
                kind=cspec.get("type", "docker").lower(),
                env=tuple(sorted(docker.get("env", {}).items())),
            )
        application = None
        aspec = spec.get("application")
        if aspec:
            application = Application(
                name=aspec.get("name", ""),
                version=aspec.get("version", ""),
                workload_class=aspec.get("workload-class", ""),
                workload_id=aspec.get("workload-id", ""),
            )
        checkpoint = None
        ckpt = spec.get("checkpoint")
        if ckpt:
            checkpoint = Checkpoint(
                mode=ckpt.get("mode", "auto"),
                periodic_sec=int(ckpt.get("periodic-sec", 0)),
                preserve_paths=tuple(ckpt.get("preserve-paths", ())),
                location=ckpt.get("location", ""),
            )
        job = Job(
            uuid=uuid,
            user=user,
            command=command,
            name=spec.get("name", "cookjob"),
            priority=priority,
            max_retries=max_retries,
            max_runtime_ms=int(spec.get("max_runtime", 2**62)),
            expected_runtime_ms=int(spec.get("expected_runtime", 0)),
            resources=Resources(mem=mem, cpus=cpus, gpus=gpus,
                                disk=disk, disk_type=disk_type,
                                ports=ports),
            pool=pool,
            user_provided_env=tuple(sorted(spec.get("env", {}).items())),
            labels=tuple(sorted(spec.get("labels", {}).items())),
            constraints=tuple(constraints),
            group_uuid=group_uuid,
            gang_size=gang_size,
            container=container,
            application=application,
            checkpoint=checkpoint,
            disable_mea_culpa_retries=bool(
                spec.get("disable_mea_culpa_retries", False)),
        )
        return job, None

    def _parse_group(self, spec: dict) -> tuple[Optional[Group], Optional[str]]:
        uuid = spec.get("uuid") or new_uuid()
        hp = spec.get("host_placement", {"type": "all"})
        try:
            ptype = GroupPlacementType(hp.get("type", "all"))
        except ValueError:
            return None, f"unknown host placement type {hp.get('type')}"
        sh = spec.get("straggler_handling", {"type": "none"})
        params = sh.get("parameters", {})
        return (
            Group(
                uuid=uuid,
                name=spec.get("name", "defaultgroup"),
                host_placement=HostPlacement(
                    type=ptype,
                    attribute=hp.get("parameters", {}).get("attribute", ""),
                    minimum=int(hp.get("parameters", {}).get("minimum", 0)),
                ),
                straggler_handling=StragglerHandling(
                    type=sh.get("type", "none"),
                    quantile=float(params.get("quantile", 0.5)),
                    multiplier=float(params.get("multiplier", 2.0)),
                ),
            ),
            None,
        )

    async def get_jobs(self, request: web.Request) -> web.Response:
        shed = self._shed("/jobs")
        if shed is not None:
            return shed
        uuids = request.query.getall("job", []) + request.query.getall("uuid", [])
        # resolve instance uuids to their jobs (reference: rawscheduler
        # accepts instance ids too)
        for inst_uuid in request.query.getall("instance", []):
            inst = self.store.instances.get(inst_uuid)
            if inst is None:
                return _err(404, f"unknown instance {inst_uuid}")
            uuids.append(inst.job_uuid)
        user = request.query.get("user")
        states = set(
            s for q in request.query.getall("state", []) for s in q.split("+")
        )
        out = []
        if uuids:
            for uuid in uuids:
                job = self.store.jobs.get(uuid)
                if job is None:
                    return _err(404, f"unknown job {uuid}")
                out.append(self._job_json(job))
        elif user:
            start = int(request.query.get("start-ms", 0))
            end = int(request.query.get("end-ms", 2**62))
            for job in self.store.user_jobs(user):
                if states and job.state.value not in states:
                    continue
                if not (start <= job.submit_time_ms <= end):
                    continue
                out.append(self._job_json(job))
        else:
            return _err(400, "specify job uuids or a user")
        return web.json_response(out)

    async def get_job(self, request: web.Request) -> web.Response:
        job = self.store.jobs.get(request.match_info["uuid"])
        if job is None:
            return _err(404, "unknown job")
        return web.json_response(self._job_json(job))

    def _job_json(self, job: Job) -> dict:
        d = job_display(job)
        d["instances"] = [
            self._instance_json(i) for i in self.store.job_instances(job.uuid)
        ]
        d["retries_remaining"] = max(
            0,
            job.max_retries
            - __import__("cook_tpu.models.state", fromlist=["attempts_consumed"])
            .attempts_consumed(job, self.store.job_instances(job.uuid)),
        )
        if job.group_uuid:
            d["groups"] = [job.group_uuid]
        return d

    def _instance_json(self, inst: Instance) -> dict:
        d = {
            "task_id": inst.task_id,
            "slave_id": inst.node_id,
            "hostname": inst.hostname,
            "status": inst.status.value,
            "preempted": inst.preempted,
            "backfilled": inst.backfilled,
            "compute-cluster": inst.compute_cluster,
            "start_time": inst.start_time_ms,
            "progress": inst.progress,
        }
        if inst.end_time_ms:
            d["end_time"] = inst.end_time_ms
        if inst.reason_code is not None:
            reason = REASONS_BY_CODE.get(inst.reason_code)
            d["reason_code"] = inst.reason_code
            if reason:
                d["reason_string"] = reason.description
                d["reason_mea_culpa"] = reason.mea_culpa
        if inst.exit_code is not None:
            d["exit_code"] = inst.exit_code
        if inst.sandbox_directory:
            d["sandbox_directory"] = inst.sandbox_directory
        if inst.progress_message:
            d["progress_message"] = inst.progress_message
        if self.scheduler is not None:
            cluster = self.scheduler.cluster_by_name(inst.compute_cluster)
            if cluster is not None:
                # FileUrlGenerator seam (plugins/definitions.clj:56):
                # deployments may front sandbox access with their own
                # file service instead of the backend's sidecar URL
                url = self.plugins.sandbox_url(
                    inst,
                    lambda: cluster.retrieve_sandbox_url_path(inst.task_id),
                )
                if url:
                    d["output_url"] = url
        return d

    async def delete_jobs(self, request: web.Request) -> web.Response:
        uuids = request.query.getall("job", []) + request.query.getall("uuid", [])
        if not uuids:
            return _err(400, "no jobs specified")
        user = request["user"]
        for uuid in uuids:
            job = self.store.jobs.get(uuid)
            if job is None:
                return _err(404, f"unknown job {uuid}")
            if job.user != user and user not in self.config.admins:
                return _err(403, f"not authorized to kill {uuid}")
        outcome = await self._commit(request, "jobs/kill", {"uuids": uuids})
        if not outcome.duplicate:
            global_registry.counter(
                "jobs_killed", "jobs killed via DELETE /jobs").inc(
                len(uuids))
        return self._no_content(outcome)

    # ------------------------------------------------------------- instances

    async def get_instance(self, request: web.Request) -> web.Response:
        inst = self.store.instances.get(request.match_info["uuid"])
        if inst is None:
            return _err(404, "unknown instance")
        d = self._instance_json(inst)
        d["job"] = self._job_json(self.store.jobs[inst.job_uuid])
        return web.json_response(d)

    async def get_instances(self, request: web.Request) -> web.Response:
        uuids = request.query.getall("instance", [])
        out = []
        for uuid in uuids:
            inst = self.store.instances.get(uuid)
            if inst is None:
                return _err(404, f"unknown instance {uuid}")
            out.append(self._instance_json(inst))
        return web.json_response(out)

    async def delete_instances(self, request: web.Request) -> web.Response:
        """Cancel specific instances (the job may retry elsewhere); the
        cancelled-task-killer reaps them (scheduler.clj:2000)."""
        uuids = request.query.getall("instance", [])
        user = request["user"]
        for uuid in uuids:
            inst = self.store.instances.get(uuid)
            if inst is None:
                return _err(404, f"unknown instance {uuid}")
            job = self.store.jobs[inst.job_uuid]
            if job.user != user and user not in self.config.admins:
                return _err(403, f"not authorized to cancel {uuid}")
        outcome = await self._commit(request, "instance/cancel",
                                     {"task_ids": uuids})
        if self.scheduler is not None:
            self.scheduler.kill_cancelled_tasks()
        return self._no_content(outcome)

    # ---------------------------------------------------------------- groups

    async def get_groups(self, request: web.Request) -> web.Response:
        uuids = request.query.getall("uuid", [])
        detailed = request.query.get("detailed") in ("true", "1")
        out = []
        for uuid in uuids:
            group = self.store.groups.get(uuid)
            if group is None:
                return _err(404, f"unknown group {uuid}")
            d = {
                "uuid": group.uuid,
                "name": group.name,
                "host_placement": {
                    "type": group.host_placement.type.value,
                    "parameters": (
                        {"attribute": group.host_placement.attribute}
                        if group.host_placement.attribute else {}
                    ),
                },
                "jobs": list(group.job_uuids),
            }
            if detailed:
                by_state: dict[str, int] = {}
                for ju in group.job_uuids:
                    job = self.store.jobs.get(ju)
                    if job:
                        by_state[job.state.value] = by_state.get(
                            job.state.value, 0) + 1
                d["composition"] = by_state
            out.append(d)
        return web.json_response(out)

    async def delete_groups(self, request: web.Request) -> web.Response:
        uuids = request.query.getall("uuid", [])
        for uuid in uuids:
            if uuid not in self.store.groups:
                return _err(404, f"unknown group {uuid}")
        outcome = await self._commit(request, "group/kill", {"groups": uuids})
        return self._no_content(outcome)

    # ------------------------------------------------------------ share/quota

    async def get_share(self, request: web.Request) -> web.Response:
        user = request.query.get("user")
        pool = request.query.get("pool", self.config.default_pool)
        if not user:
            return _err(400, "user required")
        share = self.store.get_share(user, pool)
        return web.json_response(_res_json(share))

    async def post_share(self, request: web.Request) -> web.Response:
        if request["user"] not in self.config.admins:
            return _err(403, "only admins may modify shares")
        body = await request.json()
        user = body.get("user")
        pool = body.get("pool", self.config.default_pool)
        res = body.get("share", {})
        if not user:
            return _err(400, "user required")
        outcome = await self._commit(request, "share/set", {"share": Share(
            user=user, pool=pool,
            resources=Resources(
                mem=float(res.get("mem", 0)),
                cpus=float(res.get("cpus", 0)),
                gpus=float(res.get("gpus", 0)),
            ),
            reason=body.get("reason", ""),
        )})
        body_out = _res_json(self.store.get_share(user, pool))
        if outcome.replicated is False:
            body_out["replicated"] = False
        return web.json_response(body_out, status=201)

    async def delete_share(self, request: web.Request) -> web.Response:
        if request["user"] not in self.config.admins:
            return _err(403, "only admins may modify shares")
        user = request.query.get("user")
        pool = request.query.get("pool", self.config.default_pool)
        outcome = await self._commit(request, "share/retract",
                                     {"user": user, "pool": pool})
        return self._no_content(outcome)

    async def get_quota(self, request: web.Request) -> web.Response:
        user = request.query.get("user")
        pool = request.query.get("pool", self.config.default_pool)
        if not user:
            return _err(400, "user required")
        quota = self.store.get_quota(user, pool)
        d = _res_json(quota.resources)
        d["count"] = quota.count
        return web.json_response(d)

    async def post_quota(self, request: web.Request) -> web.Response:
        if request["user"] not in self.config.admins:
            return _err(403, "only admins may modify quotas")
        body = await request.json()
        user = body.get("user")
        pool = body.get("pool", self.config.default_pool)
        res = body.get("quota", {})
        if not user:
            return _err(400, "user required")
        inf = float("inf")
        outcome = await self._commit(request, "quota/set", {"quota": Quota(
            user=user, pool=pool,
            resources=Resources(
                mem=float(res.get("mem", inf)),
                cpus=float(res.get("cpus", inf)),
                gpus=float(res.get("gpus", inf)),
            ),
            count=int(res.get("count", 2**31)),
            reason=body.get("reason", ""),
        )})
        body_out = {"user": user, "pool": pool}
        if outcome.replicated is False:
            body_out["replicated"] = False
        return web.json_response(body_out, status=201)

    async def delete_quota(self, request: web.Request) -> web.Response:
        if request["user"] not in self.config.admins:
            return _err(403, "only admins may modify quotas")
        user = request.query.get("user")
        pool = request.query.get("pool", self.config.default_pool)
        outcome = await self._commit(request, "quota/retract",
                                     {"user": user, "pool": pool})
        return self._no_content(outcome)

    async def get_usage(self, request: web.Request) -> web.Response:
        user = request.query.get("user")
        if not user:
            return _err(400, "user required")
        out = {"total_usage": {"mem": 0.0, "cpus": 0.0, "gpus": 0.0, "jobs": 0}}
        pools = {}
        for pool_name in self.store.pools:
            usage = self.store.user_usage(pool_name).get(user)
            running = [
                j for j in self.store.running_jobs(pool_name) if j.user == user
            ]
            if usage is None and not running:
                continue
            usage = usage or Resources()
            pools[pool_name] = {
                "usage": {"mem": usage.mem, "cpus": usage.cpus,
                          "gpus": usage.gpus, "jobs": len(running)},
            }
            out["total_usage"]["mem"] += usage.mem
            out["total_usage"]["cpus"] += usage.cpus
            out["total_usage"]["gpus"] += usage.gpus
            out["total_usage"]["jobs"] += len(running)
        out["pools"] = pools
        return web.json_response(out)

    # ----------------------------------------------------------------- retry

    async def get_retry(self, request: web.Request) -> web.Response:
        uuid = request.query.get("job")
        job = self.store.jobs.get(uuid or "")
        if job is None:
            return _err(404, "unknown job")
        return web.json_response(job.max_retries)

    async def post_retry(self, request: web.Request) -> web.Response:
        body = await request.json()
        uuids = body.get("jobs") or ([body["job"]] if "job" in body else [])
        if not uuids:
            return _err(400, "no jobs specified")
        retries = body.get("retries")
        increment = body.get("increment")
        if retries is None and increment is None:
            return _err(400, "retries or increment required")
        txn_id = request.headers.get("X-Cook-Txn-Id") or None
        last_seqs: dict[int, int] = {}
        duplicates = 0
        for uuid in uuids:
            if uuid not in self.store.jobs:
                return _err(404, f"unknown job {uuid}")
            try:
                # one transaction per job (each is one atomic retry
                # commit); a client txn id fans out per-job so retried
                # batches dedupe jobwise.  An absolute `retries` wins
                # over `increment` when both are present (the original
                # precedence).
                outcome = await self._run_commit(
                    "job/retry",
                    {"uuid": uuid,
                     "retries": int(retries if retries is not None
                                    else increment),
                     "increment": retries is None},
                    txn_id=f"{txn_id}:{uuid}" if txn_id else None)
            except (TransactionVetoed, ValueError) as e:
                return _err(400, str(e))
            if not outcome.duplicate:
                # duplicates met their bound when first acked; merging
                # their (possibly reconstructed) seqs would make the
                # batch wait on replication that already happened
                self._merge_batch_seqs(last_seqs, outcome)
            duplicates += outcome.duplicate
        body_out = {"jobs": uuids}
        if self.config.replication_sync_ack and duplicates < len(uuids):
            # one replication wait per touched shard covers the whole
            # batch (acks are cumulative sequence numbers per shard)
            if not await self._await_batch_replication(last_seqs):
                global_registry.counter(
                    "replication_ack_timeouts",
                    "sync-ack replication bounds missed").inc()
                body_out["replicated"] = False
        return web.json_response(body_out, status=201)

    @staticmethod
    def _merge_batch_seqs(last_seqs: dict[int, int],
                          outcome: TxnOutcome) -> None:
        for shard, seq in (outcome.shard_seqs or {0: outcome.seq}).items():
            last_seqs[shard] = max(last_seqs.get(shard, 0), seq)

    async def _await_batch_replication(self,
                                       last_seqs: dict[int, int]) -> bool:
        for shard, seq in sorted(last_seqs.items()):
            if not await self._await_replication(seq, shard):
                return False
        return True

    # ------------------------------------------------------------- pool move

    async def post_pool_move(self, request: web.Request) -> web.Response:
        """Move WAITING jobs to another pool (the reference's pool mover,
        plugins/pool_mover.clj, as an admin mutation instead of a
        submission-time adjuster)."""
        if request["user"] not in self.config.admins:
            return _err(403, "only admins may move jobs between pools")
        body = await request.json()
        uuids = body.get("jobs") or ([body["job"]] if "job" in body else [])
        pool = body.get("pool")
        if not uuids or not pool:
            return _err(400, "jobs and pool required")
        if pool not in self.store.pools:
            return _err(400, f"unknown pool {pool}")
        for uuid in uuids:
            if uuid not in self.store.jobs:
                return _err(404, f"unknown job {uuid}")
        txn_id = request.headers.get("X-Cook-Txn-Id") or None
        moved, skipped = [], []
        last_seqs: dict[int, int] = {}
        duplicates = 0
        for uuid in uuids:
            outcome = await self._run_commit(
                "job/pool-move", {"uuid": uuid, "pool": pool},
                f"{txn_id}:{uuid}" if txn_id else None)
            result = outcome.result or {}
            (moved if result.get("moved") else skipped).append(uuid)
            if not outcome.duplicate:
                self._merge_batch_seqs(last_seqs, outcome)
            duplicates += outcome.duplicate
        body_out = {"moved": moved, "skipped": skipped, "pool": pool}
        # one replication wait per touched shard covers the whole batch
        # (acks are cumulative sequence numbers per shard)
        if self.config.replication_sync_ack and duplicates < len(uuids):
            if not await self._await_batch_replication(last_seqs):
                global_registry.counter(
                    "replication_ack_timeouts",
                    "sync-ack replication bounds missed").inc()
                body_out["replicated"] = False
        return web.json_response(body_out, status=201)

    # ------------------------------------------------------------- queue etc

    async def get_queue(self, request: web.Request) -> web.Response:
        shed = self._shed("/queue")
        if shed is not None:
            return shed
        if not self.leader and self.leader_url:
            # non-leader nodes send queue queries to the leader
            # (reference: leader proxying, rest/api.clj:2408)
            raise web.HTTPTemporaryRedirect(
                f"{self.leader_url}/queue"
            )
        if self.scheduler is None:
            return _err(503, "no scheduler attached")
        out = {}
        for pool_name, queue in self.scheduler.pool_queues.items():
            out[pool_name] = [
                {"uuid": j.uuid, "user": j.user, "dru": queue.dru.get(j.uuid)}
                for j in queue.jobs[:100]
            ]
        return web.json_response(out)

    async def get_running(self, request: web.Request) -> web.Response:
        shed = self._shed("/running")
        if shed is not None:
            return shed
        out = []
        for pool_name in self.store.pools:
            for job in self.store.running_jobs(pool_name):
                out.append(self._job_json(job))
        return web.json_response(out)

    async def get_list(self, request: web.Request) -> web.Response:
        shed = self._shed("/list")
        if shed is not None:
            return shed
        user = request.query.get("user")
        if not user:
            return _err(400, "user required")
        states = set(
            s
            for q in request.query.getall("state", [])
            for s in q.replace("+", ",").split(",")
        )
        start = int(request.query.get("start-ms", 0))
        end = int(request.query.get("end-ms", 2**62))
        limit = int(request.query.get("limit", 1000))
        out = []
        for job in self.store.user_jobs(user):
            if states and job.state.value not in states:
                continue
            if not (start <= job.submit_time_ms <= end):
                continue
            out.append(self._job_json(job))
            if len(out) >= limit:
                break
        return web.json_response(out)

    async def get_unscheduled(self, request: web.Request) -> web.Response:
        shed = self._shed("/unscheduled_jobs")
        if shed is not None:
            return shed
        from cook_tpu.scheduler.monitor import starvation_stats

        uuids = request.query.getall("job", [])
        telemetry = self._telemetry()
        out = []
        for uuid in uuids:
            job = self.store.jobs.get(uuid)
            if job is None:
                return _err(404, f"unknown job {uuid}")
            entry = {
                "uuid": uuid,
                "reasons": self._unscheduled_reasons(job),
            }
            if job.state.value == "waiting":
                # starvation echo: how long THIS job has queued, against
                # its pool's oldest wait — so "why isn't it running" and
                # "is the whole pool starving" answer in one reply
                sv = starvation_stats(self.store, job.pool)
                start = (job.last_waiting_start_time_ms
                         or job.submit_time_ms)
                entry["starvation"] = {
                    "job_wait_s": max(
                        0.0, (self.store.clock() - start) / 1000.0),
                    "pool_oldest_wait_s": sv["oldest_age_s"],
                    "pool_worst_user": sv.get("worst_user", ""),
                }
            if telemetry is not None:
                # the pool's last device solve (padded problem shape,
                # backend, compile flag) so a reason code correlates
                # with compile behavior without a /debug/cycles join
                solve = telemetry.solve_info(job.pool)
                if solve is not None:
                    entry["pool_solve"] = solve
            out.append(entry)
        return web.json_response(out)

    def _unscheduled_reasons(self, job: Job) -> list[dict]:
        """Why isn't this job running (reference unscheduled.clj:172)."""
        from cook_tpu.models import state as state_mod

        reasons = []
        if job.state.value == "completed":
            return [{"reason": "The job is already completed."}]
        if job.state.value == "running":
            return [{"reason": "The job is running now."}]
        insts = self.store.job_instances(job.uuid)
        if state_mod.all_attempts_consumed(job, insts):
            reasons.append({
                "reason": "The job has exhausted its maximum number of retries.",
            })
        quota = self.store.get_quota(job.user, job.pool)
        usage = self.store.user_usage(job.pool).get(job.user, Resources())
        if (usage.mem + job.resources.mem > quota.resources.mem
                or usage.cpus + job.resources.cpus > quota.resources.cpus):
            reasons.append({
                "reason": "The job would cause you to exceed resource quotas.",
            })
        if self.scheduler is not None:
            # the flight recorder's last-cycle decision beats the static
            # placement-failure text: it carries the machine-readable
            # reason code and the cycle id that produced it
            from cook_tpu.scheduler import flight_recorder as fr

            recorder = self._recorder()
            cycle_reason = (recorder.job_reason(job.uuid)
                            if recorder is not None else None)
            if cycle_reason is not None and cycle_reason[1] != fr.MATCHED:
                # a "matched" entry for a job that is WAITING again means
                # the instance failed since — stale, fall through to the
                # placement-failure text instead of claiming a match
                cycle_id, code, detail = cycle_reason
                reasons.append({
                    "reason": "The job couldn't be placed on any available "
                              "hosts." if code != fr.NOT_CONSIDERED else
                              "The job was not considered in the last "
                              "match cycle.",
                    "data": {"reason_code": code, "cycle": cycle_id,
                             "reasons": ([{"reason": detail}]
                                         if detail else [])},
                })
            else:
                failure = self.scheduler.placement_failures.get(job.uuid)
                if failure:
                    reasons.append({
                        "reason": "The job couldn't be placed on any "
                                  "available hosts.",
                        "data": {"reasons": [{"reason": failure}]},
                    })
            queue = self.scheduler.pool_queues.get(job.pool)
            if queue is not None:
                for pos, qjob in enumerate(queue.jobs):
                    if qjob.uuid == job.uuid:
                        reasons.append({
                            "reason": "You have 1 other jobs ahead in the "
                                      "queue." if pos == 1 else
                                      f"You have {pos} other jobs ahead in "
                                      "the queue.",
                            "data": {"position": pos},
                        })
                        break
        return reasons or [{"reason": "The job is waiting to be matched."}]

    async def get_instance_stats(self, request: web.Request) -> web.Response:
        """Aggregate instance stats (reference task_stats.clj)."""
        shed = self._shed("/stats/instances")
        if shed is not None:
            return shed
        start = int(request.query.get("start-ms", 0))
        end = int(request.query.get("end-ms", 2**62))
        durations = []
        by_status: dict[str, int] = {}
        by_reason: dict[str, int] = {}
        for inst in self.store.instances.values():
            if not inst.status.terminal:
                continue
            if not (start <= inst.end_time_ms <= end):
                continue
            by_status[inst.status.value] = by_status.get(inst.status.value, 0) + 1
            if inst.status.value == "failed":
                reason = REASONS_BY_CODE.get(inst.reason_code)
                key = reason.name if reason else "unknown"
                by_reason[key] = by_reason.get(key, 0) + 1
            durations.append(inst.end_time_ms - inst.start_time_ms)
        percentiles = {}
        if durations:
            qs = statistics.quantiles(durations, n=100) if len(durations) > 1 \
                else [durations[0]] * 99
            percentiles = {"50": qs[49], "75": qs[74], "95": qs[94],
                           "99": qs[98], "100": max(durations)}
        return web.json_response({
            "by-status": by_status,
            "by-reason": by_reason,
            "run-time-ms": {"percentiles": percentiles,
                            "count": len(durations)},
        })

    async def get_pools(self, request: web.Request) -> web.Response:
        return web.json_response([
            {"name": p.name, "purpose": p.purpose, "state": p.state,
             "dru-mode": p.dru_mode.value}
            for p in self.store.pools.values()
        ])

    async def get_settings(self, request: web.Request) -> web.Response:
        payload = {
            "default-pool": self.config.default_pool,
            "max-job-mem": self.config.max_job_mem,
            "max-job-cpus": self.config.max_job_cpus,
            "max-retries-limit": self.config.max_retries_limit,
            "version": self.config.version,
        }
        if self.scheduler is not None:
            # the EFFECTIVE matcher config (after tuned_match.json merge)
            # so operators can verify which kernel production runs
            from cook_tpu.ops.match import vmap_safe_backend

            m = self.scheduler.config.match
            payload["matcher"] = {
                "backend": m.backend, "chunk": m.chunk,
                "rounds": m.chunk_rounds, "passes": m.chunk_passes,
                "kc": m.chunk_kc,
                # the pool-batched/pool-sharded paths coerce pallas->xla
                # (pallas_call under vmap); report what actually runs
                # there so a pallas rollout isn't misread as active
                "backend_batched": vmap_safe_backend(m.backend),
                "quality_audit_every": m.quality_audit_every,
            }
        return web.json_response(payload)

    async def get_info(self, request: web.Request) -> web.Response:
        return web.json_response({
            "authentication-scheme": "http-basic",
            "commit": self.config.version,
            "start-time": 0,
            "version": self.config.version,
            "leader-url": "http://localhost",
        })

    async def get_failure_reasons(self, request: web.Request) -> web.Response:
        return web.json_response([
            {"code": r.code, "name": r.name, "description": r.description,
             "mea_culpa": r.mea_culpa,
             **({"failure_limit": r.failure_limit}
                if r.failure_limit is not None else {})}
            for r in _REASONS
        ])

    # -------------------------------------------------------------- progress

    async def get_progress(self, request: web.Request) -> web.Response:
        inst = self.store.instances.get(request.match_info["uuid"])
        if inst is None:
            return _err(404, "unknown instance")
        return web.json_response({
            "progress": inst.progress,
            "progress_message": inst.progress_message,
        })

    async def post_progress(self, request: web.Request) -> web.Response:
        """Sidecar/executor progress feed (reference: progress.clj +
        rest/api.clj:3995)."""
        task_id = request.match_info["uuid"]
        body = await request.json()
        ok = self.store.update_instance_progress(
            task_id,
            int(body.get("progress_percent", 0)),
            str(body.get("progress_message", "")),
        )
        if not ok and task_id not in self.store.instances:
            return _err(404, "unknown instance")
        return web.json_response({"accepted": ok}, status=202 if ok else 200)

    async def post_heartbeat(self, request: web.Request) -> web.Response:
        """Executor liveness beat (reference: heartbeat framework messages,
        mesos/heartbeat.clj; here the executor POSTs over HTTP)."""
        task_id = request.match_info["uuid"]
        if task_id not in self.store.instances:
            return _err(404, "unknown instance")
        if self.scheduler is not None and \
                getattr(self.scheduler, "heartbeats", None) is not None:
            self.scheduler.heartbeats.notify(task_id)
        return web.json_response({"accepted": True}, status=202)

    # --------------------------------------------------------------- metrics

    async def get_metrics(self, request: web.Request) -> web.Response:
        return web.Response(text=global_registry.render_prometheus(),
                            content_type="text/plain")

    # ------------------------------------------------- dynamic clusters etc.

    async def get_compute_clusters(self, request: web.Request) -> web.Response:
        if self.scheduler is None:
            return web.json_response({"in-mem-configs": []})
        return web.json_response({
            "in-mem-configs": [
                {"name": c.name, "state": c.state.value,
                 "accepts-work": c.accepts_work}
                for c in self.scheduler.clusters
            ]
        })

    async def post_compute_cluster(self, request: web.Request) -> web.Response:
        if request["user"] not in self.config.admins:
            return _err(403, "admin required")
        body = await request.json()
        name = body.get("name")
        new_state = body.get("state")
        if self.scheduler is None:
            return _err(503, "no scheduler attached")
        cluster = self.scheduler.cluster_by_name(name)
        if cluster is None:
            if "kind" not in body:
                return _err(404, f"unknown cluster {name}")
            # dynamic cluster creation (compute-clusters CRUD,
            # rest/api.clj:3914 + compute_cluster.clj:450-530)
            from cook_tpu.components import CLUSTER_FACTORIES

            factory = CLUSTER_FACTORIES.get(body["kind"])
            if factory is None:
                return _err(400, f"unknown cluster kind {body['kind']}")
            try:
                cluster = factory(body, self.store.clock)
                self.scheduler.add_cluster(cluster)
            except (ValueError, KeyError) as e:
                return _err(400, str(e))
            return web.json_response(
                {"name": name, "state": cluster.state.value}, status=201)
        try:
            cluster.set_state(ClusterState(new_state))
        except ValueError as e:
            return _err(400, str(e))
        return web.json_response({"name": name, "state": new_state}, status=201)

    async def delete_compute_cluster(self, request: web.Request) -> web.Response:
        if request["user"] not in self.config.admins:
            return _err(403, "admin required")
        name = request.match_info["name"]
        if self.scheduler is None:
            return _err(503, "no scheduler attached")
        cluster = self.scheduler.cluster_by_name(name)
        if cluster is None:
            return _err(404, f"unknown cluster {name}")
        try:
            cluster.set_state(ClusterState.DELETED)
        except ValueError as e:
            return _err(400, str(e))
        return web.Response(status=204)

    async def get_incremental_config(self, request: web.Request) -> web.Response:
        return web.json_response(self.store.dynamic_config)

    async def post_incremental_config(self, request: web.Request) -> web.Response:
        if request["user"] not in self.config.admins:
            return _err(403, "admin required")
        body = await request.json()
        await self._commit(request, "config/update", {"updates": body})
        return web.json_response(self.store.dynamic_config, status=201)

    async def post_shutdown_leader(self, request: web.Request) -> web.Response:
        if request["user"] not in self.config.admins:
            return _err(403, "admin required")
        self.leader = False
        return web.json_response({"shutdown": "requested"}, status=202)

    # ------------------------------------------------------- replication
    # The Datomic tx-report role (datomic.clj:49): standbys tail the
    # leader's committed-event feed so failover works from the STANDBY's
    # own copy of the state — the leader's disk is not a single point of
    # durability.  Consumed by control/replication.py JournalFollower.

    REPLICATION_BATCH = 2000
    REPLICATION_MAX_WAIT_S = 25.0

    # --- wakeup plumbing: store commits happen on arbitrary writer
    # threads; long-poll and sync-ack waiters live on the aiohttp loop.
    # A store watcher marshals "something committed" onto the loop, which
    # sets every parked waiter's event; waiters re-check their predicate.

    def _ensure_repl_watcher(self) -> None:
        import asyncio

        if self._repl_loop is not None:
            return
        loop = asyncio.get_running_loop()
        self._repl_loop = loop

        def on_commit(_event) -> None:
            try:
                loop.call_soon_threadsafe(self._repl_wake_all)
            except RuntimeError:
                pass  # loop already closed (shutdown)

        self.store.add_watcher(on_commit)

    def _repl_wake_all(self) -> None:
        for waiter in list(self._repl_waiters):
            waiter.set()

    async def _repl_wait(self, timeout_s: float) -> None:
        """Park until the next commit/ack wakeup or timeout."""
        import asyncio

        waiter = asyncio.Event()
        self._repl_waiters.add(waiter)
        try:
            await asyncio.wait_for(waiter.wait(), timeout_s)
        except asyncio.TimeoutError:
            pass
        finally:
            self._repl_waiters.discard(waiter)

    def _replication_store(self, shard: Optional[int]):
        """The store whose feed a follower asked for: shard i of a
        sharded store, or the whole (unsharded) store.  None = bad
        shard index."""
        shards = getattr(self.store, "shards", None)
        if shards is None:
            return self.store if shard in (None, 0) else None
        if shard is None:
            shard = 0
        if not 0 <= shard < len(shards):
            return None
        return shards[shard]

    def _journal_slice(self, after_seq: int, store=None):
        """Copy the event batch under the store lock; encode nothing
        there (events are immutable — serialization happens outside so
        standby polls never stall leader writes)."""
        store = store if store is not None else self.store
        with store._lock:
            last_seq = store.last_seq()
            window = store._events
            oldest = window[0].seq if window else None
            # follower ahead of us: it replicated from a leader history
            # we never saw (e.g. we are a deposed leader's standby that
            # outlived it) — only a snapshot bootstrap can converge it
            if after_seq > last_seq:
                return None, last_seq, False
            # gap: events in (after_seq, oldest) have been trimmed from
            # the window (or predate this process — e.g. a leader that
            # itself recovered from disk); the follower must re-bootstrap
            # from a full snapshot
            if after_seq < last_seq and (oldest is None
                                         or after_seq + 1 < oldest):
                return None, last_seq, False
            events = [e for e in window if e.seq > after_seq]
            batch = events[:self.REPLICATION_BATCH]
            return batch, last_seq, len(events) > len(batch)

    async def get_replication_journal(self, request: web.Request
                                      ) -> web.Response:
        """Committed-event feed for standbys (the Datomic tx-report role,
        datomic.clj:49).  `wait_s` long-polls: when the follower is caught
        up, the request parks until the next commit instead of returning
        empty — replication becomes push-like and write stalls don't scale
        with standby count."""
        import asyncio

        if request["user"] not in self.config.admins:
            return _err(403, "admin required")
        try:
            after_seq = int(request.query.get("after_seq", "0"))
            wait_s = float(request.query.get("wait_s", "0"))
            shard = (int(request.query["shard"])
                     if "shard" in request.query else None)
        except ValueError:
            return _err(400, "after_seq/wait_s/shard must be numeric")
        wait_s = min(wait_s, self.REPLICATION_MAX_WAIT_S)
        target = self._replication_store(shard)
        if target is None:
            return _err(400, f"unknown shard {shard}")
        self._ensure_repl_watcher()
        loop = asyncio.get_running_loop()
        deadline = loop.time() + wait_s
        while True:
            batch, last_seq, more = self._journal_slice(after_seq, target)
            if batch is None:
                return web.json_response({
                    "snapshot_required": True, "last_seq": last_seq,
                    "incarnation": self.incarnation})
            if batch or loop.time() >= deadline:
                break
            await self._repl_wait(deadline - loop.time())
        encoded = await loop.run_in_executor(
            None, lambda: [json.loads(e.to_json()) for e in batch])
        return web.json_response({
            "events": encoded,
            "last_seq": last_seq,
            "more": more,
            "incarnation": self.incarnation,
        })

    async def get_replication_snapshot(self, request: web.Request
                                       ) -> web.Response:
        if request["user"] not in self.config.admins:
            return _err(403, "admin required")
        import asyncio

        from cook_tpu.models import persistence

        try:
            shard = (int(request.query["shard"])
                     if "shard" in request.query else None)
        except ValueError:
            return _err(400, "shard must be an integer")
        target = self._replication_store(shard)
        if target is None:
            return _err(400, f"unknown shard {shard}")
        # snapshot_state copies entity references under the store lock and
        # encodes outside it; the executor keeps the encode off the loop
        state = await asyncio.get_running_loop().run_in_executor(
            None, persistence.snapshot_state, target)
        state["incarnation"] = self.incarnation
        return web.json_response(state)

    async def post_replication_ack(self, request: web.Request
                                   ) -> web.Response:
        """Followers confirm the highest seq they have applied; only acks
        flagged `durable` (applied AND journaled on the follower's own
        disk) count toward the sync-ack bound — a memory-only follower
        confirming a write does not make it survive two machine losses.
        Absent flag defaults to durable for wire compatibility."""
        if request["user"] not in self.config.admins:
            return _err(403, "admin required")
        body = await request.json()
        follower = str(body.get("follower", ""))
        try:
            seq = int(body.get("seq"))
        except (TypeError, ValueError):
            return _err(400, "seq must be an integer")
        if not follower:
            return _err(400, "follower required")
        durable = bool(body.get("durable", True))
        try:
            # sharded feeds ack per shard (sequence numbers are only
            # comparable within one shard's history); unsharded acks are
            # shard 0
            shard = int(body.get("shard", 0))
        except (TypeError, ValueError):
            return _err(400, "shard must be an integer")
        # correlation: the follower reports the txn id of the newest
        # txn/committed event its ack covers, so the ack is attributable
        # to the mutation it makes durable (and the span ring links it)
        last_txn_id = str(body.get("last_txn_id", "") or "")
        import time as _time

        meta_key = follower if shard == 0 else f"{follower}[s{shard}]"
        self.replication_ack_meta[meta_key] = {
            "seq": seq, "durable": durable, "time": _time.monotonic(),
            "last_txn_id": last_txn_id, "shard": shard,
            "follower": follower,
            # the follower's own REST URL (control/replication.py sends
            # it): the fleet observatory's peer registry — a standby
            # that acks is a peer the leader can poll without config
            "url": str(body.get("url", "") or "")}
        global_registry.counter(
            "replication.acks",
            "replication acks received, split durable vs memory-only").inc(
            1, {"durable": str(durable).lower()})
        if durable:
            acks = self.replication_shard_acks.setdefault(shard, {})
            acks[follower] = max(acks.get(follower, 0), seq)
            if shard == 0:
                prev = self.replication_acks.get(follower, 0)
                self.replication_acks[follower] = max(prev, seq)
        if last_txn_id:
            from cook_tpu.utils import tracing

            tracing.record_event("replication.ack", txn_id=last_txn_id,
                                 follower=follower, durable=durable)
        self._repl_wake_all()
        return web.json_response({"ok": True, "counted": durable})

    def _prune_stale_acks(self) -> None:
        """Drop ack entries whose follower has gone quiet for longer than
        the liveness window: a decommissioned standby's last ack (possibly
        a high seq from a diverged history) must not satisfy the
        durability bound forever."""
        ttl = self.config.replication_ack_liveness_s
        if ttl <= 0:
            return
        import time as _time

        now = _time.monotonic()
        for meta_key, meta in list(self.replication_ack_meta.items()):
            if now - meta["time"] > ttl:
                del self.replication_ack_meta[meta_key]
                follower = meta.get("follower", meta_key)
                shard = meta.get("shard", 0)
                self.replication_shard_acks.get(shard, {}).pop(
                    follower, None)
                if shard == 0:
                    self.replication_acks.pop(follower, None)

    async def _await_replication(self, seq: int, shard: int = 0) -> bool:
        """Block until >= replication_min_acks LIVE, durable followers
        confirm `seq` ON `shard`, or the configured timeout lapses.
        True = durability bound met."""
        import asyncio

        self._ensure_repl_watcher()
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.config.replication_ack_timeout_s
        need = self.config.replication_min_acks
        while True:
            self._prune_stale_acks()
            acks = self.replication_shard_acks.get(shard, {})
            if shard == 0 and not acks:
                acks = self.replication_acks
            acked = sum(1 for s in acks.values() if s >= seq)
            if acked >= need:
                return True
            remaining = deadline - loop.time()
            if remaining <= 0:
                return False
            await self._repl_wait(remaining)

    async def _await_replication_outcome(self, outcome: TxnOutcome) -> bool:
        """Sync-ack wait for one commit: every shard the transaction
        touched must meet the durability bound (a cross-shard commit is
        durable only when BOTH segments are replicated)."""
        if outcome.shard_seqs:
            for shard, seq in sorted(outcome.shard_seqs.items()):
                if not await self._await_replication(seq, shard):
                    return False
            return True
        return await self._await_replication(outcome.seq)


def _res_json(res: Resources) -> dict:
    def clean(x):
        return x if x != float("inf") else 1e300
    return {"mem": clean(res.mem), "cpus": clean(res.cpus),
            "gpus": clean(res.gpus)}


def _err(status: int, message: str) -> web.Response:
    return web.json_response({"error": message}, status=status)


def _build_openapi(app: web.Application) -> dict:
    """Minimal OpenAPI 3 doc generated from the registered routes."""
    paths: dict = {}
    for route in app.router.routes():
        if route.method == "HEAD" or route.resource is None:
            continue
        path = route.resource.canonical
        if path in ("/swagger-docs", "/swagger-ui"):
            continue
        handler_doc = (route.handler.__doc__ or "").strip().splitlines()
        summary = handler_doc[0] if handler_doc else route.handler.__name__
        paths.setdefault(path, {})[route.method.lower()] = {
            "summary": summary,
            "operationId": route.handler.__name__,
            "responses": {"200": {"description": "success"}},
        }
    return {
        "openapi": "3.0.0",
        "info": {"title": "cook-tpu scheduler API", "version": "0.1.0"},
        "paths": paths,
    }


def run_server(api: CookApi, host: str = "127.0.0.1", port: int = 12321):
    """Blocking server entry point."""
    web.run_app(api.build_app(), host=host, port=port)
