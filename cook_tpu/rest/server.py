"""Threaded server harness: runs the aiohttp app on a background thread.

Used by tests and by deployments that want the REST layer beside the
scheduler loops in one process (the reference runs jetty in-process,
components.clj:260-294).
"""
from __future__ import annotations

import asyncio
import socket
import threading
from typing import Optional

from aiohttp import web

from cook_tpu.rest.api import CookApi


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ServerThread:
    def __init__(self, api: CookApi, host: str = "127.0.0.1",
                 port: Optional[int] = None):
        self.api = api
        self.host = host
        self.port = port or free_port()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ServerThread":
        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            runner = web.AppRunner(self.api.build_app())
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, self.host, self.port)
            loop.run_until_complete(site.start())
            self._started.set()
            loop.run_forever()
            loop.run_until_complete(runner.cleanup())

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="cook-rest")
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("REST server failed to start")
        return self

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5)
