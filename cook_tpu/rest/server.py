"""Threaded server harness: runs the aiohttp app on a background thread.

Used by tests and by deployments that want the REST layer beside the
scheduler loops in one process (the reference runs jetty in-process,
components.clj:260-294).
"""
from __future__ import annotations

import asyncio
import socket
import threading
from typing import Optional

from aiohttp import web

from cook_tpu.rest.api import CookApi


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ServerThread:
    def __init__(self, api: CookApi, host: str = "127.0.0.1",
                 port: Optional[int] = None):
        self.api = api
        self.host = host
        self.port = port or free_port()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ServerThread":
        def run():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            runner = web.AppRunner(self.api.build_app())
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, self.host, self.port)
            loop.run_until_complete(site.start())
            self._started.set()
            loop.run_forever()
            loop.run_until_complete(runner.cleanup())

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="cook-rest")
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("REST server failed to start")
        return self

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5)


class InprocessControlPlane:
    """A full control-plane write path in one process: JobStore + journal
    (real fsyncs) + transaction pipeline + CookApi on a ServerThread —
    no scheduler, no device.  The harness `tools/loadtest.py --smoke`,
    the bench `control_plane` phase, and the contention tests drive:
    every serialization point the contention observatory instruments
    (store lock, journal fsync, REST) is real; only the match cycle is
    absent, which submission/query/kill traffic never touches."""

    def __init__(self, *, data_dir: Optional[str] = None,
                 pools: tuple = ("default",), config=None, clock=None,
                 journal_kw: Optional[dict] = None, shards: int = 1,
                 history_sample_s: float = 0.5):
        import tempfile
        import time as _time

        from cook_tpu.models import persistence
        from cook_tpu.models.entities import Pool
        from cook_tpu.models.store import JobStore
        from cook_tpu.obs.tsdb import HistoryConfig, MetricsHistory
        from cook_tpu.rest.api import ApiConfig, CookApi
        from cook_tpu.txn import TransactionLog

        self._own_dir = data_dir is None
        self.data_dir = data_dir or tempfile.mkdtemp(prefix="cook-cp-")
        clock = clock or (lambda: int(_time.time() * 1000))
        self.shards = shards
        if shards > 1:
            # sharded control plane (cook_tpu/shard/): N store shards,
            # N journal segments, the sharded commit pipeline
            from cook_tpu.shard import (ShardedStore,
                                        ShardedTransactionLog)
            from cook_tpu.shard import journal as shard_journal

            self.store = ShardedStore(shards, clock=clock)
            self.journals = shard_journal.attach_shard_journals(
                self.store, self.data_dir, **(journal_kw or {}))
            self.journal = None
            self.txn = ShardedTransactionLog(self.store,
                                             journals=self.journals)
        else:
            self.store = JobStore(clock=clock)
            # journal_kw: JournalWriter knobs (fsync_policy,
            # degraded_retry_s, ...) — the chaos fsync scenarios
            # exercise both failure policies
            self.journal = persistence.attach_journal(
                self.store, f"{self.data_dir}/journal.jsonl",
                **(journal_kw or {}))
            self.journals = [self.journal]
            self.txn = TransactionLog(self.store, journal=self.journal)
        for pool in pools:
            self.store.set_pool(Pool(name=pool))
        # fast-sampled, memory-only metrics history: the loadtest's
        # closing report scrapes /debug/history for the run's window
        # (commit-ack p99 trend), so a 2-second smoke run needs more
        # than one tick
        self.history = MetricsHistory(
            config=HistoryConfig(sample_s=history_sample_s))
        self.api = CookApi(self.store, None, config or ApiConfig(),
                           txn=self.txn, history=self.history)
        self.server = ServerThread(self.api)

    @property
    def url(self) -> str:
        return self.server.url

    def start(self) -> "InprocessControlPlane":
        self.server.start()
        self.history.start()
        return self

    def stop(self) -> None:
        import shutil

        self.history.stop()
        self.server.stop()
        for journal in self.journals:
            journal.close()
        if self._own_dir:
            shutil.rmtree(self.data_dir, ignore_errors=True)
