"""Pluggable request authentication.

Reference: the middleware stack assembled in components.clj:267-284 picks
an authenticator — Kerberos SPNEGO (rest/spnego.clj), HTTP basic
(rest/basic_auth.clj), or the one-user dev middleware — and impersonation
wraps whichever is active.  Here the same seam is a small protocol:

    class Authenticator:
        def authenticate(self, request) -> Optional[str]   # None = denied
        def challenge(self) -> dict                        # 401 headers

The composite dev default (basic auth, then the X-Cook-Requesting-User
header, then "anonymous") preserves the development behavior; production
configs select `spnego` or `basic` explicitly, at which point requests
without valid credentials get a 401 with the proper challenge header.

The SPNEGO implementation mirrors spnego.clj's shape: parse the
`Authorization: Negotiate <token>` header, hand the token to a GSS
acceptor, answer 401 + `WWW-Authenticate: Negotiate` when absent or
rejected.  The GSS acceptor itself is injectable (`gss_accept`): in
environments without a KDC the default acceptor rejects everything, which
is the correct closed-by-default posture — the seam and its negative
paths are real, the Kerberos mechanics plug in at deploy time.
"""
from __future__ import annotations

import base64
import binascii
from typing import Callable, Optional, Protocol, Sequence, runtime_checkable

from aiohttp import web


@runtime_checkable
class Authenticator(Protocol):
    def authenticate(self, request: web.Request) -> Optional[str]: ...

    def challenge(self) -> dict: ...


class BasicAuthenticator:
    """HTTP basic auth (rest/basic_auth.clj): the reference trusts the
    username and ignores the password (it fronts Cook with trusted
    proxies); an optional verifier callable tightens that."""

    def __init__(self, verify: Optional[Callable[[str, str], bool]] = None):
        self.verify = verify

    def authenticate(self, request: web.Request) -> Optional[str]:
        header = request.headers.get("Authorization", "")
        if not header.startswith("Basic "):
            return None
        try:
            decoded = base64.b64decode(header[6:]).decode()
            user, _, password = decoded.partition(":")
        except (binascii.Error, UnicodeDecodeError, ValueError):
            # b64decode raises plain ValueError on non-ASCII input
            return None
        if not user:
            return None
        if self.verify is not None and not self.verify(user, password):
            return None
        return user

    def challenge(self) -> dict:
        return {"WWW-Authenticate": 'Basic realm="cook"'}


class DevHeaderAuthenticator:
    """The one-user dev middleware: trust X-Cook-Requesting-User."""

    def __init__(self, default_user: str = "anonymous"):
        self.default_user = default_user

    def authenticate(self, request: web.Request) -> Optional[str]:
        return (request.headers.get("X-Cook-Requesting-User")
                or self.default_user)

    def challenge(self) -> dict:
        return {}


class SpnegoAuthenticator:
    """Kerberos SPNEGO (rest/spnego.clj): Negotiate tokens accepted via
    an injectable GSS acceptor `gss_accept(token_bytes) -> principal or
    None`; the principal's primary component becomes the user."""

    def __init__(self, gss_accept: Optional[Callable[[bytes],
                                                     Optional[str]]] = None):
        # closed by default: no acceptor = nobody authenticates
        self.gss_accept = gss_accept

    def authenticate(self, request: web.Request) -> Optional[str]:
        header = request.headers.get("Authorization", "")
        if not header.startswith("Negotiate "):
            return None
        try:
            token = base64.b64decode(header[len("Negotiate "):])
        except (binascii.Error, ValueError):
            return None
        if self.gss_accept is None:
            return None
        principal = self.gss_accept(token)
        if not principal:
            return None
        # alice/admin@EXAMPLE.COM -> alice (spnego.clj principal parse)
        return principal.split("@", 1)[0].split("/", 1)[0]

    def challenge(self) -> dict:
        return {"WWW-Authenticate": "Negotiate"}


class CompositeAuthenticator:
    """First authenticator to produce a user wins; the challenge headers
    of every member are merged into the 401."""

    def __init__(self, members: Sequence):
        self.members = list(members)

    def authenticate(self, request: web.Request) -> Optional[str]:
        for member in self.members:
            user = member.authenticate(request)
            if user:
                return user
        return None

    def challenge(self) -> dict:
        # schemes share the WWW-Authenticate key; HTTP allows multiple
        # challenges comma-joined in one header value — dropping all but
        # the last would make e.g. SPNEGO unreachable behind a composite
        # (Negotiate clients only send tokens after seeing Negotiate)
        values: dict[str, list[str]] = {}
        for member in self.members:
            for key, value in member.challenge().items():
                bucket = values.setdefault(key, [])
                if value not in bucket:
                    bucket.append(value)
        return {key: ", ".join(vals) for key, vals in values.items()}


def dev_default_authenticator() -> CompositeAuthenticator:
    """Basic auth, then the dev header (which falls back to anonymous) —
    the permissive development stack, never returns None."""
    return CompositeAuthenticator([BasicAuthenticator(),
                                   DevHeaderAuthenticator()])


def authenticator_from_config(conf: dict):
    """Build the configured authenticator
    ({"kind": "dev"|"basic"|"spnego"|"composite", ...})."""
    kind = conf.get("kind", "dev")
    if kind == "dev":
        return dev_default_authenticator()
    if kind == "basic":
        verify = None
        if conf.get("verify"):
            # dotted path to a callable(user, password) -> bool, same
            # plugin mechanism as spnego's gss_accept
            from cook_tpu.scheduler.plugins import load_plugin

            verify = load_plugin(conf["verify"])
            if not callable(verify):
                verify = verify.verify
        return BasicAuthenticator(verify=verify)
    if kind == "spnego":
        acceptor = None
        if conf.get("gss_accept"):
            from cook_tpu.scheduler.plugins import load_plugin

            acceptor = load_plugin(conf["gss_accept"])
            if not callable(acceptor):
                acceptor = acceptor.gss_accept
        elif conf.get("gssapi"):
            # real Kerberos via libgssapi_krb5 (KRB5_KTNAME supplies the
            # keytab in deployment); None when the library is absent,
            # which keeps the closed-by-default posture
            from cook_tpu.rest.gssapi import make_gssapi_acceptor

            acceptor = make_gssapi_acceptor(
                libname=conf.get("gssapi_lib") or None)
        return SpnegoAuthenticator(gss_accept=acceptor)
    if kind == "composite":
        return CompositeAuthenticator(
            [authenticator_from_config(m) for m in conf.get("members", [])])
    raise ValueError(f"unknown authenticator kind {kind!r}")
