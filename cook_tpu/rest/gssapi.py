"""Real GSSAPI acceptor for SPNEGO, bound via ctypes to libgssapi_krb5.

The reference authenticates with Kerberos SPNEGO (rest/spnego.clj), with
the GSS mechanics provided by the JVM.  Here the same single-leg accept is
done against MIT Kerberos' C library directly: `gss_accept_sec_context`
with the default acceptor credential (honours KRB5_KTNAME for the keytab),
then `gss_display_name` for the client principal.

No KDC or keytab exists in the build environment, so against live traffic
every token is rejected with a GSS error — which is the correct
closed-by-default posture; in deployment, pointing KRB5_KTNAME at the
service keytab is the only configuration needed.  Multi-leg negotiation
(GSS_S_CONTINUE_NEEDED) is not supported: Kerberos-backed SPNEGO completes
in one leg, matching the reference's request-scoped accept.

Wire-up: `{"auth": {"kind": "spnego", "gssapi": true}}` or inject
`make_gssapi_acceptor()` as the `gss_accept` callable.
"""
from __future__ import annotations

import ctypes
import ctypes.util
import logging
from typing import Callable, Optional

log = logging.getLogger(__name__)

GSS_S_COMPLETE = 0
GSS_S_CONTINUE_NEEDED = 1


class _GssBuffer(ctypes.Structure):
    _fields_ = [("length", ctypes.c_size_t), ("value", ctypes.c_void_p)]


def _load_lib(libname: Optional[str] = None):
    names = ([libname] if libname else
             ["libgssapi_krb5.so.2", "libgssapi_krb5.so",
              ctypes.util.find_library("gssapi_krb5")])
    for name in names:
        if not name:
            continue
        try:
            return ctypes.CDLL(name)
        except OSError:
            continue
    return None


def make_gssapi_acceptor(
    libname: Optional[str] = None,
) -> Optional[Callable[[bytes], Optional[str]]]:
    """Build `gss_accept(token) -> principal or None` over libgssapi_krb5.

    Returns None when the library cannot be loaded (caller falls back to
    the closed-by-default acceptor)."""
    lib = _load_lib(libname)
    if lib is None:
        log.warning("libgssapi_krb5 not found; SPNEGO stays closed")
        return None

    u32 = ctypes.c_uint32
    ptr = ctypes.c_void_p
    lib.gss_accept_sec_context.restype = u32
    lib.gss_display_name.restype = u32
    lib.gss_release_buffer.restype = u32
    lib.gss_release_name.restype = u32
    lib.gss_delete_sec_context.restype = u32

    def gss_accept(token: bytes) -> Optional[str]:
        minor = u32(0)
        context = ptr(None)
        src_name = ptr(None)
        mech_type = ptr(None)
        output = _GssBuffer(0, None)
        flags = u32(0)
        time_rec = u32(0)
        buf = ctypes.create_string_buffer(token, len(token))
        input_token = _GssBuffer(len(token),
                                 ctypes.cast(buf, ctypes.c_void_p))
        try:
            major = lib.gss_accept_sec_context(
                ctypes.byref(minor), ctypes.byref(context),
                None,                      # acceptor cred: default (keytab)
                ctypes.byref(input_token),
                None,                      # no channel bindings
                ctypes.byref(src_name), ctypes.byref(mech_type),
                ctypes.byref(output), ctypes.byref(flags),
                ctypes.byref(time_rec),
                None,   # delegated cred unused: NULL avoids leaking one
            )
            accept_minor = minor.value
            if output.value:
                lib.gss_release_buffer(ctypes.byref(minor),
                                       ctypes.byref(output))
            if major != GSS_S_COMPLETE:
                # includes CONTINUE_NEEDED (multi-leg unsupported) and all
                # failures (no keytab, clock skew, bad token...)
                log.debug("gss_accept_sec_context major=0x%x minor=%d",
                          major, accept_minor)
                return None
            name_buf = _GssBuffer(0, None)
            major = lib.gss_display_name(ctypes.byref(minor), src_name,
                                         ctypes.byref(name_buf), None)
            if major != GSS_S_COMPLETE or not name_buf.value:
                return None
            principal = ctypes.string_at(
                name_buf.value, name_buf.length).decode("utf-8", "replace")
            lib.gss_release_buffer(ctypes.byref(minor),
                                   ctypes.byref(name_buf))
            return principal
        finally:
            if src_name.value:
                lib.gss_release_name(ctypes.byref(minor),
                                     ctypes.byref(src_name))
            if context.value:
                lib.gss_delete_sec_context(ctypes.byref(minor),
                                           ctypes.byref(context), None)

    return gss_accept
