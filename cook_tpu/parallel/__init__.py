"""Mesh/sharding layer: pool-axis and node-axis sharded scheduling solves."""
from cook_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    node_sharded_chunked_match,
    node_sharded_greedy_match,
    pool_sharded_dru,
    pool_sharded_match,
    shard_pools,
)
