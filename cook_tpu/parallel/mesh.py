"""Device-mesh plumbing: how the scheduling solves scale over ICI/DCN.

Two sharding strategies (SURVEY §2.4, BASELINE config 5):

  * pool-axis sharding — the per-pool problems of one scheduling cycle are
    independent, so a batch of P pools shards P-ways over the mesh and each
    device solves its pools with zero cross-device traffic (the reference
    runs pools round-robin on one thread, scheduler.clj:2508-2517).

  * node-axis sharding — one huge pool (100k jobs x 10k nodes) shards the
    NODE axis: every device holds a slice of node availability, each greedy
    step computes its local best (fitness, node) and a single tiny
    all-gather picks the global winner; only the winning device updates its
    slice.  Per-step traffic is O(devices), not O(nodes) — it rides ICI.

Multi-host: `jax.distributed.initialize()` + the same `Mesh` spanning all
processes gives the DCN scale-out; nothing in the kernels changes.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cook_tpu.ops.common import BIG
from cook_tpu.ops.dru import DruTasks, dru_rank
from cook_tpu.ops.match import (
    MatchProblem,
    MatchResult,
    backend_flags,
    chunked_match,
    greedy_match,
)


def make_mesh(n_devices: Optional[int] = None, axis: str = "pool") -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def shard_pools(mesh: Mesh, tree, axis: str = "pool"):
    """Place a pool-batched pytree (leading axis = pools) with the pool axis
    sharded across the mesh."""
    sharding = NamedSharding(mesh, P(axis))
    return jax.device_put(tree, sharding)


def pool_sharded_match(mesh: Mesh, problems: MatchProblem, *,
                       chunk: int = 0, rounds: int = 4,
                       passes: int = 2, kc: int = 128,
                       backend: str = "xla") -> MatchResult:
    """Solve P pools' match problems concurrently, one shard of pools per
    device.  `problems` leaves have leading axis P (divisible by mesh size).
    chunk=0 selects the exact sequential-greedy kernel; `backend` selects
    the candidate pass like MatchConfig.backend (xla/pallas/bucketed)."""
    fn = (functools.partial(chunked_match, chunk=chunk, rounds=rounds,
                            passes=passes, kc=kc,
                            **backend_flags(backend)) if chunk
          else greedy_match)
    mapped = jax.vmap(fn)
    spec = P("pool")
    shmapped = jax.shard_map(
        mapped, mesh=mesh,
        in_specs=(MatchProblem(spec, spec, spec, spec, spec, spec),),
        out_specs=MatchResult(spec, spec),
    )
    return shmapped(problems)


def pool_sharded_dru(mesh: Mesh, tasks: DruTasks, mem_div, cpu_div, gpu_div):
    """Batched DRU ranking over pools, pool axis sharded."""
    mapped = jax.vmap(lambda t, m, c, g: dru_rank(t, m, c, g))
    spec = P("pool")
    shmapped = jax.shard_map(
        mapped, mesh=mesh,
        in_specs=(DruTasks(spec, spec, spec, spec, spec, spec),
                  spec, spec, spec),
        out_specs=jax.tree.map(lambda _: spec, jax.eval_shape(
            mapped, tasks, mem_div, cpu_div, gpu_div)),
    )
    return shmapped(tasks, mem_div, cpu_div, gpu_div)


def task_sharded_dru(mesh: Mesh, tasks: DruTasks, mem_div, cpu_div, gpu_div,
                     *, gpu_mode: bool = False):
    """DRU ranking with the TASK axis sharded across the mesh.

    This is the problem-size scale axis SURVEY §5 maps to the reference's
    long-context story: when one pool's task tensor outgrows a chip, shard
    T across devices and let XLA parallelize the sorts/cumsums with the
    collectives it chooses (all-to-all sort exchanges over ICI).  Plain
    jit + shardings — no shard_map needed, since every op in the kernel is
    collective-friendly.
    """
    axis = mesh.axis_names[0]
    spec = P(axis)
    sharded = DruTasks(*[
        jax.device_put(leaf, NamedSharding(mesh, spec)) for leaf in tasks
    ])
    divs = [jax.device_put(d, NamedSharding(mesh, P())) for d in
            (mem_div, cpu_div, gpu_div)]
    return dru_rank(sharded, *divs, gpu_mode=gpu_mode)


def node_sharded_greedy_match(mesh: Mesh, problem: MatchProblem) -> MatchResult:
    """Sequential greedy match with the NODE axis sharded across the mesh.

    Each scan step: every device computes (best fitness, best local node)
    over its node shard — O(N/D) work — then an all-gather of D candidate
    pairs picks the global winner; the owner updates its availability
    slice.  This is the ICI-collective path for single huge pools.
    """
    axis = mesh.axis_names[0]
    ndev = mesh.devices.size
    n = problem.avail.shape[0]
    assert n % ndev == 0, "pad nodes to a multiple of mesh size"

    def local_solve(demands, job_valid, avail, totals, node_valid, feasible):
        # runs per-device with avail/totals/node_valid/feasible sharded on nodes
        my = jax.lax.axis_index(axis)
        nloc = avail.shape[0]

        def step(carry, inputs):
            avail = carry
            demand, ok, feas_row = inputs
            fits = jnp.all(avail >= demand[None, :], axis=-1)
            feasible_l = fits & node_valid & feas_row & ok
            used = totals - avail[:, :2]
            denom = jnp.maximum(totals, 1e-30)
            fit = ((used[:, 0] + demand[0]) / denom[:, 0]
                   + (used[:, 1] + demand[1]) / denom[:, 1]) * 0.5
            score = jnp.where(feasible_l, fit, -BIG)
            lbest = jnp.argmax(score)
            lscore = score[lbest]
            # tiny collective: D (score, owner, local-idx) candidates
            all_scores = jax.lax.all_gather(lscore, axis)          # [D]
            all_idx = jax.lax.all_gather(lbest, axis)              # [D]
            winner_dev = jnp.argmax(all_scores)
            placed = all_scores[winner_dev] > -BIG
            winner_local = all_idx[winner_dev]
            i_am_winner = (winner_dev == my) & placed
            delta = jnp.where(i_am_winner, demand, 0.0)
            avail = avail.at[winner_local].add(-delta)
            global_choice = jnp.where(
                placed, winner_dev * nloc + winner_local, -1
            ).astype(jnp.int32)
            return avail, global_choice

        new_avail, assignment = jax.lax.scan(
            step, avail, (demands, job_valid, feasible)
        )
        return assignment, new_avail

    j = problem.demands.shape[0]
    feas = (problem.feasible if problem.feasible is not None
            else jnp.ones((j, ndev), dtype=bool))  # [J,1] per shard
    shmapped = jax.shard_map(
        local_solve, mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis), P(axis), P(None, axis)),
        out_specs=(P(), P(axis)),
        # `assignment` is replicated by construction (every device runs the
        # same all-gather + argmax); vma inference can't see that.
        check_vma=False,
    )
    assignment, new_avail = shmapped(
        problem.demands, problem.job_valid, problem.avail, problem.totals,
        problem.node_valid, feas,
    )
    return MatchResult(assignment=assignment, new_avail=new_avail)
