"""Device-mesh plumbing: how the scheduling solves scale over ICI/DCN.

Two sharding strategies (SURVEY §2.4, BASELINE config 5):

  * pool-axis sharding — the per-pool problems of one scheduling cycle are
    independent, so a batch of P pools shards P-ways over the mesh and each
    device solves its pools with zero cross-device traffic (the reference
    runs pools round-robin on one thread, scheduler.clj:2508-2517).

  * node-axis sharding — one huge pool (100k jobs x 10k nodes) shards the
    NODE axis: every device holds a slice of node availability, each greedy
    step computes its local best (fitness, node) and a single tiny
    all-gather picks the global winner; only the winning device updates its
    slice.  Per-step traffic is O(devices), not O(nodes) — it rides ICI.

Multi-host: `jax.distributed.initialize()` + the same `Mesh` spanning all
processes gives the DCN scale-out; nothing in the kernels changes.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax < 0.6 ships shard_map under experimental only, where today's
# check_vma knob is spelled check_rep; one shim keeps the call sites on
# the modern surface either way
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_legacy(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_vma)

from cook_tpu.ops.common import BIG, binpack_fitness
from cook_tpu.ops.dru import DruTasks, dru_rank
from cook_tpu.ops.match import (
    MatchProblem,
    MatchResult,
    backend_flags,
    chunked_match,
    conflict_round,
    greedy_match,
    vmap_safe_backend,
)


def make_mesh(n_devices: Optional[int] = None, axis: str = "pool") -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def shard_pools(mesh: Mesh, tree, axis: str = "pool"):
    """Place a pool-batched pytree (leading axis = pools) with the pool axis
    sharded across the mesh.  The put is data-plane accounted under the
    `mesh-shard` family (on multi-device meshes this is a real copy; the
    ledger counts logical bytes either way so the number is
    backend-stable)."""
    from cook_tpu.obs import data_plane

    sharding = NamedSharding(mesh, P(axis))
    return data_plane.device_put(tree, sharding,
                                 family=data_plane.FAM_MESH)


def invalid_match_problem(j: int, n: int, n_res: int = 4,
                          with_feasible: bool = True,
                          dtype=jnp.float32) -> MatchProblem:
    """An all-invalid padded problem used to fill the pool axis up to a
    mesh multiple (matcher.match_pools_batched) and the BLOCK axis of the
    hierarchical fine batch (ops/hierarchical.py): job_valid/node_valid
    are all False so the kernels place nothing, and the sharded path
    engages for ANY solvable-pool/block count instead of only exact mesh
    multiples.  `totals` is ones so the binpack fitness arithmetic stays
    finite on the dead lanes.  `with_feasible=False` matches batches
    whose real problems carry no constraint mask (the pytree structures
    must agree for stacking/vmap).  `dtype` must match the real
    problems' cost-tensor dtype (bf16 under MatchConfig.quantized) —
    a mismatched pad lane would silently promote the whole stacked
    batch back to f32."""
    return MatchProblem(
        demands=jnp.zeros((j, n_res), dtype),
        job_valid=jnp.zeros((j,), bool),
        avail=jnp.zeros((n, n_res), dtype),
        totals=jnp.ones((n, 2), dtype),
        node_valid=jnp.zeros((n,), bool),
        feasible=jnp.zeros((j, n), bool) if with_feasible else None,
    )


def pool_sharded_match(mesh: Mesh, problems: MatchProblem, *,
                       chunk: int = 0, rounds: int = 4,
                       passes: int = 2, kc: int = 128,
                       backend: str = "xla") -> MatchResult:
    """Solve P pools' match problems concurrently, one shard of pools per
    device.  `problems` leaves have leading axis P (divisible by mesh size).
    chunk=0 selects the exact sequential-greedy kernel; `backend` selects
    the candidate pass like MatchConfig.backend (xla/pallas/bucketed)."""
    fn = (functools.partial(chunked_match, chunk=chunk, rounds=rounds,
                            passes=passes, kc=kc,
                            **backend_flags(vmap_safe_backend(backend)))
          if chunk else greedy_match)
    mapped = jax.vmap(fn)
    spec = P("pool")
    # a mask-less batch (feasible=None, e.g. the hierarchical fine solve
    # at XL sizes where a [J, N] mask would be GBs) has no leaf there —
    # the spec pytree must match the data pytree's structure; likewise
    # node_bonus only appears when topology scoring stamped one
    feas_spec = spec if problems.feasible is not None else None
    bonus_spec = spec if problems.node_bonus is not None else None
    shmapped = shard_map(
        mapped, mesh=mesh,
        in_specs=(MatchProblem(spec, spec, spec, spec, spec, feas_spec,
                               bonus_spec),),
        out_specs=MatchResult(spec, spec),
    )
    return shmapped(problems)


def pool_sharded_coarse(mesh: Mesh, problems: MatchProblem, *,
                        chunk: int = 4096, rounds: int = 2,
                        passes: int = 8) -> MatchResult:
    """Batched coarse routing for the hierarchical SUPERBLOCK layer: each
    lane is one superblock's jobs x blocks problem (blocks play the node
    role), sharded on the same pool axis as `pool_sharded_match`.  The
    kernel is pinned to the flat coarse pass's exact semantics — kc=1
    single-candidate conflict rounds, no approx top-k (see
    ops/hierarchical._coarse_xla) — so two-level routing matches the
    one-level pass block-for-block on a single-superblock pool."""
    fn = functools.partial(chunked_match, chunk=chunk, rounds=rounds,
                           passes=passes, kc=1, use_approx=False,
                           **backend_flags("xla"))
    mapped = jax.vmap(fn)
    spec = P("pool")
    feas_spec = spec if problems.feasible is not None else None
    bonus_spec = spec if problems.node_bonus is not None else None
    shmapped = shard_map(
        mapped, mesh=mesh,
        in_specs=(MatchProblem(spec, spec, spec, spec, spec, feas_spec,
                               bonus_spec),),
        out_specs=MatchResult(spec, spec),
    )
    return shmapped(problems)


def pool_sharded_dru(mesh: Mesh, tasks: DruTasks, mem_div, cpu_div, gpu_div):
    """Batched DRU ranking over pools, pool axis sharded."""
    mapped = jax.vmap(lambda t, m, c, g: dru_rank(t, m, c, g))
    spec = P("pool")
    shmapped = shard_map(
        mapped, mesh=mesh,
        in_specs=(DruTasks(spec, spec, spec, spec, spec, spec),
                  spec, spec, spec),
        out_specs=jax.tree.map(lambda _: spec, jax.eval_shape(
            mapped, tasks, mem_div, cpu_div, gpu_div)),
    )
    return shmapped(tasks, mem_div, cpu_div, gpu_div)


def task_sharded_dru(mesh: Mesh, tasks: DruTasks, mem_div, cpu_div, gpu_div,
                     *, gpu_mode: bool = False):
    """DRU ranking with the TASK axis sharded across the mesh.

    This is the problem-size scale axis SURVEY §5 maps to the reference's
    long-context story: when one pool's task tensor outgrows a chip, shard
    T across devices and let XLA parallelize the sorts/cumsums with the
    collectives it chooses (all-to-all sort exchanges over ICI).  Plain
    jit + shardings — no shard_map needed, since every op in the kernel is
    collective-friendly.
    """
    from cook_tpu.obs import data_plane

    axis = mesh.axis_names[0]
    spec = P(axis)
    sharded = DruTasks(*[
        data_plane.device_put(leaf, NamedSharding(mesh, spec),
                              family=data_plane.FAM_DRU) for leaf in tasks
    ])
    divs = [data_plane.device_put(d, NamedSharding(mesh, P()),
                                  family=data_plane.FAM_DRU) for d in
            (mem_div, cpu_div, gpu_div)]
    return dru_rank(sharded, *divs, gpu_mode=gpu_mode)


def node_sharded_greedy_match(mesh: Mesh, problem: MatchProblem) -> MatchResult:
    """Sequential greedy match with the NODE axis sharded across the mesh.

    Each scan step: every device computes (best fitness, best local node)
    over its node shard — O(N/D) work — then an all-gather of D candidate
    pairs picks the global winner; the owner updates its availability
    slice.  This is the ICI-collective path for single huge pools.
    """
    axis = mesh.axis_names[0]
    ndev = mesh.devices.size
    n = problem.avail.shape[0]
    assert n % ndev == 0, "pad nodes to a multiple of mesh size"

    def local_solve(demands, job_valid, avail, totals, node_valid, feasible):
        # runs per-device with avail/totals/node_valid/feasible sharded on nodes
        my = jax.lax.axis_index(axis)
        nloc = avail.shape[0]

        def step(carry, inputs):
            avail = carry
            demand, ok, feas_row = inputs
            fits = jnp.all(avail >= demand[None, :], axis=-1)
            feasible_l = fits & node_valid & feas_row & ok
            used = totals - avail[:, :2]
            denom = jnp.maximum(totals, 1e-30)
            fit = binpack_fitness(used[:, 0], used[:, 1], demand[0],
                                  demand[1], denom[:, 0], denom[:, 1])
            score = jnp.where(feasible_l, fit, -BIG)
            lbest = jnp.argmax(score)
            lscore = score[lbest]
            # tiny collective: D (score, owner, local-idx) candidates
            all_scores = jax.lax.all_gather(lscore, axis)          # [D]
            all_idx = jax.lax.all_gather(lbest, axis)              # [D]
            winner_dev = jnp.argmax(all_scores)
            placed = all_scores[winner_dev] > -BIG
            winner_local = all_idx[winner_dev]
            i_am_winner = (winner_dev == my) & placed
            delta = jnp.where(i_am_winner, demand, 0.0)
            avail = avail.at[winner_local].add(-delta)
            global_choice = jnp.where(
                placed, winner_dev * nloc + winner_local, -1
            ).astype(jnp.int32)
            return avail, global_choice

        new_avail, assignment = jax.lax.scan(
            step, avail, (demands, job_valid, feasible)
        )
        return assignment, new_avail

    j = problem.demands.shape[0]
    feas = (problem.feasible if problem.feasible is not None
            else jnp.ones((j, ndev), dtype=bool))  # [J,1] per shard
    shmapped = shard_map(
        local_solve, mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis), P(axis), P(None, axis)),
        out_specs=(P(), P(axis)),
        # `assignment` is replicated by construction (every device runs the
        # same all-gather + argmax); vma inference can't see that.
        check_vma=False,
    )
    assignment, new_avail = shmapped(
        problem.demands, problem.job_valid, problem.avail, problem.totals,
        problem.node_valid, feas,
    )
    return MatchResult(assignment=assignment, new_avail=new_avail)


def node_sharded_chunked_match(
    mesh: Mesh,
    problem: MatchProblem,
    *,
    chunk: int = 1024,
    rounds: int = 3,
    kc: int = 128,
    passes: int = 2,
) -> MatchResult:
    """The chunked production matcher with its candidate pass sharded over
    the NODE axis — the scalable single-huge-pool path.

    The availability state ([N, R], ~256 KB at 16k nodes) is cheap enough
    to keep REPLICATED; what scales with the problem is the [K, N]
    fitness/feasibility sweep, so that is what shards: each device scores
    only its N/D node columns (O(K*N/D) work), takes a local top-kc, and
    one all-gather merges the D*kc candidates into a global top-kc list.
    The conflict-resolution rounds then run identically (deterministic)
    on every device against the replicated availability — per-pass ICI
    traffic is O(D * K * kc), never O(N).

    Same semantics as `chunked_match` up to candidate-selection detail
    (local-then-merged top-k can order equal scores differently than one
    global top-k); parity is bounded by the same >=0.99 packing bar.
    """
    axis = mesh.axis_names[0]
    ndev = mesh.devices.size
    j, n = problem.demands.shape[0], problem.avail.shape[0]
    n_res = problem.demands.shape[-1]
    assert j % chunk == 0, "pad jobs to a multiple of chunk"
    assert n % ndev == 0, "pad nodes to a multiple of mesh size"
    nloc = n // ndev
    kc_local = min(kc, nloc)   # per-device top-k is bounded by its shard
    kc = min(kc, n)            # the MERGED list keeps the requested width

    demands_c = problem.demands.reshape(j // chunk, chunk, n_res)
    ok_c = problem.job_valid.reshape(j // chunk, chunk)
    if problem.feasible is not None:
        feas_c = problem.feasible.reshape(j // chunk, chunk, n)
    else:
        feas_c = jnp.ones((j // chunk, 1, 1), dtype=bool)

    def local_solve(demands_c, ok_c, feas_c, avail0, totals_l, nv_l):
        # totals_l / nv_l / feas_c arrive SHARDED on the node axis (each
        # device holds its nloc columns — the [J, N] constraint mask is
        # the big input, ~1 GB at headline scale, and must not be
        # replicated); avail stays replicated because the conflict rounds
        # gather and update arbitrary global nodes (it is [N, R], tiny)
        my = jax.lax.axis_index(axis)
        col0 = my * nloc
        denom_l = jnp.maximum(totals_l, 1e-30)

        def chunk_step(avail, inputs):
            d, ok, fr_l = inputs

            def candidate_pass(avail, assignment):
                unplaced = assignment < 0
                # my node-column slice of the replicated availability
                avail_l = jax.lax.dynamic_slice_in_dim(avail, col0, nloc)
                fits = jnp.all(avail_l[None, :, :] >= d[:, None, :],
                               axis=-1)
                feasible = (fits & nv_l[None, :] & fr_l
                            & (ok & unplaced)[:, None])
                used0 = totals_l[:, 0] - avail_l[:, 0]
                used1 = totals_l[:, 1] - avail_l[:, 1]
                fit = binpack_fitness(used0[None, :], used1[None, :],
                                      d[:, 0:1], d[:, 1:2],
                                      denom_l[None, :, 0],
                                      denom_l[None, :, 1])
                score = jnp.where(feasible, fit, -BIG)
                lval, lidx = jax.lax.top_k(score, kc_local)  # [K, kc_l]
                gidx = lidx + col0
                # merge: [D, K, kc_l] -> [K, D*kc_l] -> global top-kc
                all_val = jax.lax.all_gather(lval, axis)
                all_idx = jax.lax.all_gather(gidx, axis)
                flat_val = jnp.moveaxis(all_val, 0, 1).reshape(chunk, -1)
                flat_idx = jnp.moveaxis(all_idx, 0, 1).reshape(chunk, -1)
                mval, mpos = jax.lax.top_k(flat_val,
                                           min(kc, ndev * kc_local))
                midx = jnp.take_along_axis(flat_idx, mpos, axis=1)
                return mval, midx

            def round_step(carry, _):
                # the SHARED acceptance step (ops/match.py conflict_round)
                # runs replicated and deterministic on every device
                avail, assignment, cand_val, cand_idx = carry
                avail, assignment = conflict_round(
                    avail, assignment, cand_val, cand_idx, d, n)
                return (avail, assignment, cand_val, cand_idx), None

            assignment = (d[:, 0] * 0).astype(jnp.int32) - 1
            for _ in range(passes):
                cand_val, cand_idx = candidate_pass(avail, assignment)
                (avail, assignment, _, _), _ = jax.lax.scan(
                    round_step, (avail, assignment, cand_val, cand_idx),
                    None, length=rounds,
                )
            return avail, assignment

        new_avail, assignment = jax.lax.scan(
            chunk_step, avail0, (demands_c, ok_c, feas_c))
        return assignment.reshape(j), new_avail

    # the unconstrained placeholder mask ([C,1,1]) cannot shard its size-1
    # node axis; real masks shard so no device holds the full [J, N] bools
    feas_spec = P() if problem.feasible is None else P(None, None, axis)
    shmapped = shard_map(
        local_solve, mesh=mesh,
        in_specs=(P(), P(), feas_spec, P(), P(axis), P(axis)),
        out_specs=(P(), P()),
        # outputs are identical on all devices by construction (the merge
        # collectives + replicated rounds); vma inference can't see that
        check_vma=False,
    )
    assignment, new_avail = shmapped(
        demands_c, ok_c, feas_c, problem.avail, problem.totals,
        problem.node_valid,
    )
    return MatchResult(assignment=assignment, new_avail=new_avail)
