"""Multi-process shard-group runtime (ISSUE 16).

The sharded control plane (cook_tpu/shard/) keeps every shard's lock,
journal segment, and replication feed in ONE process, so the GIL caps
the measured throughput win.  This package places shard-GROUPS in
separate worker processes behind a shard-aware front end:

  * `topology`   — shard -> group assignment + the route map the front
    end serves at GET /debug/shards;
  * `worker`     — the per-group process: only its shards' stores,
    journal segments, idempotency tables, and replication feeds, the
    existing REST surface plus an internal RPC port
    (`python -m cook_tpu.mp.worker`);
  * `twopc`      — cross-group transactions as a two-phase ordered
    apply over RPC, decision-journaled by the coordinator;
  * `router`     — the forwarding front end (connection pooling,
    per-worker circuit breakers, header passthrough, 2PC coordinator);
  * `supervisor` — spawns/monitors the worker fleet, promotes a standby
    to adopt a dead worker's journal segments, plus the `MpRuntime`
    harness loadtest/bench/chaos drive.
"""
from cook_tpu.mp.topology import (GroupShardRouter, ShardGroupTopology,
                                  build_route_map, read_route_map,
                                  write_route_map)

__all__ = [
    "GroupShardRouter",
    "ShardGroupTopology",
    "build_route_map",
    "read_route_map",
    "write_route_map",
]
