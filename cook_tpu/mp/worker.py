"""Shard-group worker: one process, one group's shards — and nothing else.

A worker owns a contiguous block of the global shard space
(`topology.shards_of_group`).  It builds ONLY those shards' stores
(recovered from their journal segments under
`data_dir/shards/shard-NN/`), their journal writers, idempotency
tables, and replication feeds (`CookApi`'s /replication endpoints serve
this worker's segments), wrapped in a `ShardedStore` behind a
`GroupShardRouter` — so a key whose shard this group does not own is a
421, never a silent write into the wrong segment.

Two server surfaces per worker:

  * the EXISTING REST surface (`CookApi` on a `ServerThread`) — the
    front end forwards client requests here verbatim;
  * an internal RPC port — the 2PC participant
    (prepare/commit/abort), uuid -> owner resolution for the front
    end's scatter cache, and `adopt` for standby promotion.

Standby mode (shards=()): only the RPC port serves, answering ping and
waiting for `adopt`, which recovers the dead group's journal segments
and brings the REST surface up on the port reserved at spawn.

Entry point: `python -m cook_tpu.mp.worker --data-dir D --n-shards N
--group G --shards 0,1 ...` (the supervisor's spawn command).
"""
from __future__ import annotations

import asyncio
import json
import logging
import os
import threading
import time
from typing import Optional

from aiohttp import web

from cook_tpu.mp.topology import GroupShardRouter
from cook_tpu.obs import distributed
from cook_tpu.utils import tracing
from cook_tpu.utils.metrics import global_registry

log = logging.getLogger(__name__)

_RPC_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5, float("inf"))

# staged prepares older than this are presumed aborted (the coordinator
# journals commit decisions BEFORE sending commits, so a commit for a
# GC'd prepare still applies from the payload it carries)
PENDING_TTL_S = 120.0


class TwoPCParticipant:
    """The worker-side half of cook_tpu/mp/twopc.py.

    prepare = the full single-process validation (veto now or never),
    staging parsed entities; commit = answer from the idempotency
    table, else apply staged, else re-validate from the payload the
    commit RPC carries (a participant that lost its staged prepare —
    crash between phases, segment adoption — still converges); abort =
    drop the staged prepare.
    """

    def __init__(self, store, txn, api, group: Optional[int] = None):
        self.store = store
        self.txn = txn
        self.api = api
        self.group = group
        self._lock = threading.Lock()
        self._pending: dict[str, dict] = {}  # txn_id -> staged payload

    def _span_tags(self) -> dict:
        """Tags that route a participant span to this worker's pid
        track in the merged trace (obs/distributed.py)."""
        if self.group is None:
            return {}
        return {"group": self.group,
                "process": distributed.worker_process_label(self.group)}

    # ------------------------------------------------------------ phases

    def prepare(self, txn_id: str, op: str, user: str, payload: dict,
                *, parent: Optional[str] = None) -> dict:
        with tracing.correlate(txn_id), tracing.span(
                "mp.participant.prepare", parent=parent, op=op,
                **self._span_tags()):
            staged, err = self._validate(op, user, payload)
            if err is not None:
                # name the vetoing group in the ring: the stitched
                # trace for an aborted txn must say WHO said no
                tracing.record_event("twopc.veto", op=op,
                                     status=err.get("status"),
                                     **self._span_tags())
                return {"ok": False, **err}
            import time as _time

            with self._lock:
                self._gc(_time.monotonic())
                self._pending[txn_id] = {"op": op, "staged": staged,
                                         "at": _time.monotonic()}
            return {"ok": True, "uuids": staged.get("uuids", [])}

    def commit(self, txn_id: str, op: str, user: str, payload: dict,
               *, parent: Optional[str] = None) -> dict:
        with tracing.correlate(txn_id), tracing.span(
                "mp.participant.commit", parent=parent, op=op,
                **self._span_tags()):
            return self._commit(txn_id, op, user, payload)

    def _commit(self, txn_id: str, op: str, user: str,
                payload: dict) -> dict:
        cached = self.store.txn_results.get(txn_id)
        if cached is not None:
            return {"ok": True, "duplicate": True,
                    "result": cached.get("result")}
        with self._lock:
            entry = self._pending.pop(txn_id, None)
        if entry is None or entry["op"] != op:
            # lost prepare (restart / adoption): re-validate from the
            # payload the decision carries
            entry_staged, err = self._validate(op, user, payload)
            if err is not None:
                # post-decision validation failure: the local state
                # changed between prepare and replay (e.g. a killed
                # job's submit uuid reused).  Surface it — the
                # coordinator logs and leaves it pending.
                return {"ok": False, **err}
        else:
            entry_staged = entry["staged"]
        from cook_tpu.models.store import TransactionVetoed

        try:
            outcome = self.txn.commit(op, entry_staged["payload"],
                                      txn_id=txn_id)
        except TransactionVetoed as e:
            return {"ok": False, "status": 400, "error": str(e)}
        return {"ok": True, "duplicate": outcome.duplicate,
                "result": outcome.result,
                "shard_seqs": {str(s): q for s, q in
                               (outcome.shard_seqs or {}).items()}}

    def abort(self, txn_id: str, *,
              parent: Optional[str] = None) -> dict:
        with tracing.correlate(txn_id), tracing.span(
                "mp.participant.abort", parent=parent,
                **self._span_tags()):
            with self._lock:
                dropped = self._pending.pop(txn_id, None) is not None
            return {"ok": True, "dropped": dropped}

    # -------------------------------------------------------- validation

    def _validate(self, op: str, user: str, payload: dict):
        """(staged, None) on success, (None, error-dict) on veto.
        Staged carries the entity-object payload `txn.commit` consumes
        plus the uuids the coordinator reports back."""
        from cook_tpu.shard.router import MisroutedKey

        try:
            if op == "jobs/submit":
                jobs, groups, err = self.api.parse_submission(
                    payload.get("jobs", []), payload.get("groups", []),
                    user)
                if err:
                    return None, {"status": 400, "error": err}
                return {"payload": {"jobs": jobs,
                                    "groups": list(groups.values())},
                        "uuids": [j.uuid for j in jobs]}, None
            if op == "jobs/kill":
                uuids = list(payload.get("uuids", ()))
                admins = self.api.config.admins
                for uuid in uuids:
                    job = self.store.jobs.get(uuid)
                    if job is None:
                        return None, {"status": 404,
                                      "error": f"unknown job {uuid}"}
                    if job.user != user and user not in admins:
                        return None, {
                            "status": 403,
                            "error": f"user {user} may not kill {uuid}"}
                return {"payload": {"uuids": uuids}, "uuids": uuids}, None
            return None, {"status": 400,
                          "error": f"op {op} not supported over 2PC"}
        except MisroutedKey as e:
            return None, {"status": 421, "error": str(e)}

    def _gc(self, now: float) -> None:
        stale = [txn_id for txn_id, entry in self._pending.items()
                 if now - entry["at"] > PENDING_TTL_S]
        for txn_id in stale:
            del self._pending[txn_id]


class _RpcSurface:
    """The worker's internal RPC app (ServerThread-compatible via
    build_app).  No auth stack: this port is fleet-internal (bind it to
    loopback or the supervisor's private network, docs/operations.md)."""

    def __init__(self, worker: "ShardGroupWorker"):
        self.worker = worker
        self._rpc_seconds = global_registry.histogram(
            "mp.rpc_seconds",
            "worker internal-RPC service seconds per method",
            buckets=_RPC_BUCKETS)

    def build_app(self) -> web.Application:
        app = web.Application()
        r = app.router
        r.add_get("/rpc/ping", self.ping)
        r.add_get("/rpc/resolve", self.resolve)
        r.add_post("/rpc/2pc/prepare", self.twopc("prepare"))
        r.add_post("/rpc/2pc/commit", self.twopc("commit"))
        r.add_post("/rpc/2pc/abort", self.twopc("abort"))
        r.add_post("/rpc/adopt", self.adopt)
        return app

    async def ping(self, request: web.Request) -> web.Response:
        return web.json_response(self.worker.describe())

    async def resolve(self, request: web.Request) -> web.Response:
        """uuid -> owned-entity kind, for the front end's scatter
        resolution (a kill/read names uuids, not pools)."""
        if not self.worker.active:
            return web.json_response({"error": "standby"}, status=503)
        store = self.worker.store
        owned = {}
        for uuid in request.query.getall("uuid", []):
            if uuid in store.jobs:
                owned[uuid] = "job"
            elif uuid in store.instances:
                owned[uuid] = "instance"
            elif uuid in store.groups:
                owned[uuid] = "group"
        return web.json_response({"group": self.worker.group,
                                  "owned": owned})

    def twopc(self, method: str):
        async def handler(request: web.Request) -> web.Response:
            import time as _time

            if not self.worker.active:
                return web.json_response(
                    {"ok": False, "error": "standby"}, status=503)
            body = await request.json()
            participant = self.worker.participant
            # the coordinator's trace context: the participant's span
            # parents under the X-Cook-Parent-Span phase span
            parent = request.headers.get(distributed.PARENT_SPAN_HEADER)
            t0 = _time.perf_counter()
            if method == "abort":
                call = (lambda: participant.abort(body["txn_id"],
                                                  parent=parent))
            else:
                call = (lambda: getattr(participant, method)(
                    body["txn_id"], body.get("op", ""),
                    body.get("user", ""), body.get("payload") or {},
                    parent=parent))
            # commits end in fsync — keep them off the event loop
            reply = await asyncio.get_running_loop().run_in_executor(
                None, call)
            self._rpc_seconds.observe(_time.perf_counter() - t0,
                                      {"method": method})
            return web.json_response(reply)

        return handler

    async def adopt(self, request: web.Request) -> web.Response:
        """Standby promotion: recover the named group's journal
        segments, bring the REST surface up on the reserved port, and
        start answering as that group."""
        body = await request.json()
        if self.worker.active:
            return web.json_response(
                {"ok": False,
                 "error": f"already serving group {self.worker.group}"},
                status=409)
        group = int(body["group"])
        parent = request.headers.get(distributed.PARENT_SPAN_HEADER)
        corr = request.headers.get(distributed.TXN_HEADER)

        def run_adopt():
            # the adopting group names itself in the failover trace
            with tracing.correlate(corr), tracing.span(
                    "mp.adopt", parent=parent, group=group,
                    process=distributed.worker_process_label(group)):
                return self.worker.adopt(
                    group, [int(s) for s in body["shards"]],
                    tuple(body.get("pools") or ("default",)))

        try:
            describe = await asyncio.get_running_loop().run_in_executor(
                None, run_adopt)
        except Exception as e:  # noqa: BLE001 — adoption failure must
            # reach the supervisor as a reply, not a hung socket
            log.exception("adoption failed")
            return web.json_response(
                {"ok": False, "error": f"{type(e).__name__}: {e}"},
                status=500)
        return web.json_response({"ok": True, **describe})


class ShardGroupWorker:
    """One worker process's internals (also embeddable in-process for
    tests and the loadtest harness)."""

    def __init__(self, *, data_dir: str, n_shards: int,
                 group: Optional[int] = None, shards=(),
                 pools: tuple = ("default",),
                 port: Optional[int] = None,
                 rpc_port: Optional[int] = None,
                 config=None, clock=None,
                 journal_kw: Optional[dict] = None,
                 history_sample_s: float = 0.5):
        from cook_tpu.rest.server import ServerThread, free_port

        self.data_dir = data_dir
        self.n_shards = n_shards
        self.group = group
        self.shards: tuple = tuple(sorted(shards))
        self.pools = tuple(pools)
        self.config = config
        # wall-clock ms by default (rest/server.py uses the same): job
        # timestamps must share a domain with the 2PC decision log's
        # wall stamps or the front end's stitched timeline events
        # render decades away from the worker's own
        self.clock = clock or (lambda: int(time.time() * 1000))
        self.journal_kw = dict(journal_kw or {})
        self.history_sample_s = history_sample_s
        self.port = port or free_port()
        self.rpc_port = rpc_port or free_port()
        self.store = None
        self.txn = None
        self.api = None
        self.history = None
        self.journals: list = []
        self.participant: Optional[TwoPCParticipant] = None
        self.rest_server = None
        self._rest_started = False
        self.rpc_server = ServerThread(_RpcSurface(self),
                                       port=self.rpc_port)
        self._adoptions = global_registry.counter(
            "mp.adoptions",
            "standby adoptions of a dead worker's journal segments")
        if self.shards:
            self._activate()

    @property
    def active(self) -> bool:
        return self.store is not None

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    @property
    def rpc_url(self) -> str:
        return f"http://127.0.0.1:{self.rpc_port}"

    def describe(self) -> dict:
        return {"ok": True, "active": self.active, "group": self.group,
                "shards": list(self.shards), "url": self.url,
                "rpc_url": self.rpc_url, "pid": os.getpid(),
                "pools": list(self.pools)}

    # ------------------------------------------------------------- build

    def _activate(self) -> None:
        """Build this group's slice of the control plane: recover each
        owned shard from its GLOBAL segment dir, wire journals, the
        sharded commit pipeline, and the REST api."""
        from cook_tpu.models import persistence
        from cook_tpu.models.entities import Pool
        from cook_tpu.obs.tsdb import HistoryConfig, MetricsHistory
        from cook_tpu.rest.api import ApiConfig, CookApi
        from cook_tpu.rest.server import ServerThread
        from cook_tpu.shard import ShardedStore, ShardedTransactionLog
        from cook_tpu.shard import journal as shard_journal

        clock = self.clock
        router = GroupShardRouter(self.n_shards, self.shards)
        locals_: list = []
        for gi in self.shards:
            directory = shard_journal.shard_dir(self.data_dir, gi)
            recovered = persistence.recover(
                directory, clock=clock,
                store_factory=shard_journal._shard_factory(gi, clock))
            locals_.append(recovered
                           or shard_journal._shard_factory(gi, clock)())
        self.store = ShardedStore(len(self.shards), clock=clock,
                                  router=router, shards=locals_)
        for gi, shard in zip(self.shards, self.store.shards):
            directory = shard_journal.shard_dir(self.data_dir, gi)
            os.makedirs(directory, exist_ok=True)
            writer = persistence.JournalWriter(
                os.path.join(directory, "journal.jsonl"),
                **self.journal_kw)
            shard.add_watcher(writer)
            self.journals.append(writer)
        self.txn = ShardedTransactionLog(self.store,
                                         journals=self.journals)
        for pool in self.pools:
            # register ONLY the pools this group owns: fleet-wide reads
            # (/list, /usage) iterate registered pools, and an unowned
            # pool would trip MisroutedKey mid-read.  A submit for an
            # unowned pool is still rejected (unknown pool) — the front
            # end never sends one unless its map is stale.
            try:
                self.store.shard_for_pool(pool)
            except Exception:  # noqa: BLE001 — MisroutedKey
                continue
            if pool not in self.store.pools:
                self.store.set_pool(Pool(name=pool))
        self.history = MetricsHistory(
            config=HistoryConfig(sample_s=self.history_sample_s))
        self.api = CookApi(self.store, None, self.config or ApiConfig(),
                           txn=self.txn, history=self.history)
        # REST-side spans/walls route to this worker's merged-trace pid
        # track and X-Cook-Hop-Walls header (obs/distributed.py)
        self.api.process_label = distributed.worker_process_label(
            self.group)
        self.participant = TwoPCParticipant(self.store, self.txn,
                                            self.api, group=self.group)
        self.rest_server = ServerThread(self.api, port=self.port)

    def adopt(self, group: int, shards, pools: tuple) -> dict:
        """Standby -> worker: take over a dead group's segments."""
        if self.active:
            raise RuntimeError(f"already serving group {self.group}")
        self.group = group
        self.shards = tuple(sorted(shards))
        self.pools = tuple(pools)
        self._activate()
        self.rest_server.start()
        self._rest_started = True
        self.history.start()
        self._adoptions.inc()
        log.info("adopted group %d (shards %s), serving at %s",
                 group, list(self.shards), self.url)
        return self.describe()

    # --------------------------------------------------------- lifecycle

    def start(self) -> "ShardGroupWorker":
        self.rpc_server.start()
        if self.active:
            self.rest_server.start()
            self._rest_started = True
            self.history.start()
        return self

    def stop(self) -> None:
        if self.history is not None:
            self.history.stop()
        if self._rest_started and self.rest_server is not None:
            self.rest_server.stop()
        self.rpc_server.stop()
        for journal in self.journals:
            journal.close()
        self.journals = []


def main(argv=None) -> int:
    # workers never touch the device: the control plane is host-only
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import argparse
    import signal

    parser = argparse.ArgumentParser(
        description="cook mp shard-group worker process")
    parser.add_argument("--data-dir", required=True)
    parser.add_argument("--n-shards", type=int, required=True)
    parser.add_argument("--group", type=int, default=None)
    parser.add_argument("--shards", default="",
                        help="comma-separated global shard ids; empty "
                             "for a standby")
    parser.add_argument("--pools", default="default")
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--rpc-port", type=int, default=None)
    parser.add_argument("--ready-file", default="")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    shards = tuple(int(s) for s in args.shards.split(",") if s != "")
    worker = ShardGroupWorker(
        data_dir=args.data_dir, n_shards=args.n_shards,
        group=args.group, shards=shards,
        pools=tuple(p for p in args.pools.split(",") if p),
        port=args.port, rpc_port=args.rpc_port).start()
    ready = worker.describe()
    if args.ready_file:
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(ready, f)
        os.replace(tmp, args.ready_file)
    print(json.dumps(ready), flush=True)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    worker.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
