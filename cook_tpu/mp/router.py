"""Shard-aware front end: one public endpoint over the worker fleet.

Clients talk to the front end exactly as they would a single-process
control plane; it maps each request's key to its owning shard-group
(`topology.ShardGroupTopology` over the same stable hash the workers
use) and forwards it there, preserving the request's idempotency key
(X-Cook-Txn-Id) and propagating the worker's staleness / replication
headers back out.  What lands where:

  * pool-keyed writes (POST /jobs) — split by pool; one group means a
    raw forward, several means a cross-group 2PC
    (`twopc.TwoPCCoordinator`);
  * uuid-keyed requests (kill, /jobs/{uuid}, /retry, ...) — owner
    resolved via a TTL cache backed by a parallel /rpc/resolve scatter;
  * fleet-wide reads (/queue, /running, /list, /usage, ...) —
    scatter-gather with a structural merge;
  * meta-keyed ops (/pools, /settings, config) — the group owning the
    META shard;
  * GET /debug/shards — the route map, for shard-aware clients
    (client/jobclient.py --route-map) that want to skip the hop.

Forwarding rides one shared aiohttp session (connection pooling) with a
per-worker `CircuitBreaker`: transport failures open the breaker and
requests for that group fail fast with 503 + Retry-After until the
cooldown's half-open probe closes it — a dead worker degrades ONLY the
keys it owns.  The supervisor rewrites the route map on failover; the
front end re-reads it on mtime change, clears its resolve cache, and
replays outstanding 2PC decisions against the promoted standby's urls.
"""
from __future__ import annotations

import asyncio
import json
import logging
import os
import threading
import time
from typing import Optional

from aiohttp import web

from cook_tpu.faults.breaker import BreakerParams, CircuitBreaker
from cook_tpu.mp.topology import (ShardGroupTopology, read_route_map,
                                  topology_of)
from cook_tpu.mp.twopc import DecisionLog, TwoPCCoordinator
from cook_tpu.obs import distributed
from cook_tpu.obs.incident import IncidentRecorder, add_default_collectors
from cook_tpu.txn.transaction import new_txn_id
from cook_tpu.utils import tracing
from cook_tpu.utils.metrics import global_registry

log = logging.getLogger(__name__)

_FWD_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0, 2.5, float("inf"))

# request headers forwarded to workers / response headers propagated
# back to the client, by prefix
_HEADER_PREFIX = "X-Cook-"
_RESP_EXTRA = ("Retry-After",)

RESOLVE_TTL_S = 30.0
MAP_CHECK_INTERVAL_S = 0.25

# scatter-gather read routes: ask every alive group, merge structurally
# (/pools is here because each worker registers only its OWNED pools —
# the union is the cluster's pool list)
SCATTER_ROUTES = frozenset({
    "/queue", "/running", "/list", "/unscheduled_jobs",
    "/stats/instances", "/usage", "/pools",
    # pool-keyed fairness bodies: pools are group-owned and disjoint, so
    # the dict-union merge composes them without summing anything
    "/debug/fairness",
})


def _merge(a, b):
    """Structural merge for scatter-gather replies: dicts union
    (recursing on collisions), lists concatenate, numbers sum, anything
    else keeps the first answer."""
    if isinstance(a, dict) and isinstance(b, dict):
        out = dict(a)
        for k, v in b.items():
            out[k] = _merge(out[k], v) if k in out else v
        return out
    if isinstance(a, list) and isinstance(b, list):
        return a + b
    if isinstance(a, bool) or isinstance(b, bool):
        return a
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a + b
    return a


class _Reservoir:
    """Bounded latency sample for /debug/frontend percentiles."""

    def __init__(self, cap: int = 2048):
        self.cap = cap
        self.samples: list[float] = []
        self.count = 0
        self._lock = threading.Lock()

    def add(self, value: float) -> None:
        with self._lock:
            self.count += 1
            if len(self.samples) < self.cap:
                self.samples.append(value)
            else:
                self.samples[self.count % self.cap] = value

    def quantile(self, q: float) -> float:
        with self._lock:
            if not self.samples:
                return 0.0
            ordered = sorted(self.samples)
            return ordered[min(len(ordered) - 1,
                               int(q * len(ordered)))]


class FrontEnd:
    """ServerThread-compatible (build_app) forwarding app."""

    def __init__(self, route_map_path: Optional[str] = None, *,
                 route_map: Optional[dict] = None,
                 decision_log_path: Optional[str] = None,
                 default_pool: str = "default",
                 rpc_timeout_s: float = 10.0,
                 forward_timeout_s: float = 30.0,
                 breaker_params: Optional[BreakerParams] = None):
        if route_map is None and route_map_path is None:
            raise ValueError("need route_map or route_map_path")
        self.route_map_path = route_map_path
        self._map = route_map or read_route_map(route_map_path)
        if self._map is None:
            raise ValueError(f"no route map at {route_map_path}")
        self._map_mtime = (os.path.getmtime(route_map_path)
                           if route_map_path
                           and os.path.exists(route_map_path) else 0.0)
        self._map_checked = 0.0
        self._map_lock = threading.Lock()
        self.topology = topology_of(self._map)
        self.default_pool = default_pool
        self.forward_timeout_s = forward_timeout_s
        self._session = None  # created on the app's loop
        params = breaker_params or BreakerParams(
            window=20, min_samples=5, error_threshold=0.5, cooldown_s=2.0)
        self.breakers = {g: CircuitBreaker(f"worker-{g}", params)
                         for g in range(self.topology.n_groups)}
        decisions = DecisionLog(
            decision_log_path
            or os.path.join("/tmp", f"cook-2pc-{os.getpid()}.jsonl"))
        self.decisions = decisions
        self.coordinator = TwoPCCoordinator(
            self._post_json, decisions, rpc_timeout_s=rpc_timeout_s)
        self._resolve_cache: dict[str, tuple[int, float]] = {}
        self._latency = {g: _Reservoir()
                         for g in range(self.topology.n_groups)}
        self._twopc_stats = {"commits": 0, "vetoes": 0, "errors": 0}
        # per-(group, hop) forward-time split: queue / transport /
        # apply / fsync / replication_ack (obs/distributed.py)
        self.hops = distributed.HopAttribution()
        # federated mp incidents: the supervisor's fleet observatory
        # points at this recorder (MpRuntime wiring), so a worker's
        # ok->degraded edge captures the decision-log tail, breaker
        # states, and route map in ONE bundle alongside the standard
        # trace/faults evidence
        self.incidents = add_default_collectors(IncidentRecorder())
        distributed.add_mp_collectors(
            self.incidents, decision_log_path=decisions.path,
            breakers_fn=lambda: {str(g): b.state.value
                                 for g, b in self.breakers.items()},
            route_map_fn=lambda: dict(self._map))
        self._forward_seconds = global_registry.histogram(
            "mp.forward_seconds",
            "front-end forward round-trip seconds per shard-group",
            buckets=_FWD_BUCKETS)
        self._forwarded = global_registry.counter(
            "mp.forwarded",
            "front-end forwarded requests per group and outcome "
            "(ok/error/breaker_open)")
        self._resolves = global_registry.counter(
            "mp.resolve.lookups",
            "uuid -> owning-group resolutions per source "
            "(cache/scatter/miss)")

    # --------------------------------------------------------- route map

    def _maybe_reload_map(self) -> None:
        if not self.route_map_path:
            return
        now = time.monotonic()
        with self._map_lock:
            if now - self._map_checked < MAP_CHECK_INTERVAL_S:
                return
            self._map_checked = now
        try:
            mtime = os.path.getmtime(self.route_map_path)
        except OSError:
            return
        if mtime == self._map_mtime:
            return
        new_map = read_route_map(self.route_map_path)
        if new_map is None:
            return
        with self._map_lock:
            self._map = new_map
            self._map_mtime = mtime
            # entity ownership may have moved with the segments
            self._resolve_cache.clear()
        log.info("route map reloaded (map_seq=%s)",
                 new_map.get("map_seq"))
        # finish any decision whose participant moved to a new url
        asyncio.get_running_loop().create_task(
            self.coordinator.replay(self._rpc_urls()))

    def _entry(self, group: int) -> dict:
        return self._map["groups"][group]

    def _rpc_urls(self) -> dict[int, str]:
        return {e["group"]: e["rpc_url"] for e in self._map["groups"]
                if e.get("rpc_url")}

    def _alive_groups(self) -> list[int]:
        return [e["group"] for e in self._map["groups"] if e["alive"]]

    # --------------------------------------------------------- transport

    async def _ensure_session(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=64,
                                               limit_per_host=16))
        return self._session

    async def _post_json(self, url: str, body: dict, timeout_s: float,
                         headers: Optional[dict] = None
                         ) -> tuple[int, dict]:
        """The 2PC transport (twopc.PostFn); `headers` carry the
        coordinator's trace context (X-Cook-Txn-Id +
        X-Cook-Parent-Span)."""
        import aiohttp

        session = await self._ensure_session()
        async with session.post(
                url, json=body, headers=headers,
                timeout=aiohttp.ClientTimeout(total=timeout_s)) as resp:
            try:
                payload = await resp.json()
            except Exception:  # noqa: BLE001 — non-JSON reply
                payload = {"ok": False,
                           "error": (await resp.text())[:200]}
            return resp.status, payload

    async def _forward(self, group: int, request: web.Request, *,
                       path: Optional[str] = None,
                       body: Optional[bytes] = None) -> web.Response:
        """Forward `request` to `group`'s worker, preserving X-Cook-*
        headers both ways and stamping X-Cook-Shard-Group."""
        import aiohttp

        breaker = self.breakers[group]
        if not breaker.allows_work():
            self._forwarded.inc(1, {"group": str(group),
                                    "outcome": "breaker_open"})
            return web.json_response(
                {"error": f"shard-group {group} unavailable "
                          f"(circuit open)"},
                status=503, headers={"Retry-After": "2",
                                     "X-Cook-Shard-Group": str(group)})
        entry = self._entry(group)
        if not entry["alive"] or not entry["url"]:
            self._forwarded.inc(1, {"group": str(group),
                                    "outcome": "error"})
            return web.json_response(
                {"error": f"shard-group {group} has no live worker"},
                status=503, headers={"Retry-After": "2",
                                     "X-Cook-Shard-Group": str(group)})
        target = entry["url"] + (path if path is not None
                                 else request.path_qs)
        headers = {k: v for k, v in request.headers.items()
                   if k.startswith(_HEADER_PREFIX)
                   or k == "Content-Type"}
        # trace context on EVERY forward: a client-provided txn id is
        # preserved (it is also the idempotency key); one is minted
        # otherwise so the hop is traceable end to end.  The worker
        # opens its server-side span under our forward span.
        txn_id = headers.get(distributed.TXN_HEADER) or new_txn_id()
        headers[distributed.TXN_HEADER] = txn_id
        headers[distributed.PARENT_SPAN_HEADER] = "mp.forward"
        if body is None and request.can_read_body:
            body = await request.read()
        session = await self._ensure_session()
        t0 = time.perf_counter()
        # front-end queue hop: arrival (stamped by _map_middleware) to
        # forward start — resolve scatters, body reads, map reloads
        queue_s = max(0.0, t0 - request.get("t_arrival", t0))
        try:
            async with session.request(
                    request.method, target, data=body, headers=headers,
                    timeout=aiohttp.ClientTimeout(
                        total=self.forward_timeout_s)) as resp:
                payload = await resp.read()
                elapsed = time.perf_counter() - t0
                breaker.note_success()
                self._latency[group].add(elapsed)
                self._forward_seconds.observe(elapsed,
                                              {"group": str(group)})
                self._forwarded.inc(1, {"group": str(group),
                                        "outcome": "ok"})
                self.hops.attribute(
                    group, total_s=elapsed, queue_s=queue_s,
                    walls=distributed.parse_hop_walls(
                        resp.headers.get(distributed.HOP_WALLS_HEADER)))
                tracing.record_span(
                    "mp.forward", elapsed, group=group, txn_id=txn_id,
                    process=distributed.PROCESS_FRONTEND)
                out_headers = {
                    k: v for k, v in resp.headers.items()
                    if k.startswith(_HEADER_PREFIX) or k in _RESP_EXTRA}
                out_headers["X-Cook-Shard-Group"] = str(group)
                out_headers.setdefault(distributed.TXN_HEADER, txn_id)
                return web.Response(
                    body=payload, status=resp.status,
                    content_type=resp.content_type,
                    headers=out_headers)
        except Exception as e:  # noqa: BLE001 — transport failure, not
            # an app error: the worker is unreachable
            breaker.note_failure()
            self._forwarded.inc(1, {"group": str(group),
                                    "outcome": "error"})
            tracing.record_span(
                "mp.forward", time.perf_counter() - t0, group=group,
                txn_id=txn_id, error=True,
                process=distributed.PROCESS_FRONTEND)
            return web.json_response(
                {"error": f"shard-group {group} unreachable: "
                          f"{type(e).__name__}"},
                status=502, headers={"X-Cook-Shard-Group": str(group)})

    # -------------------------------------------------------- resolution

    async def _resolve_uuids(self, uuids) -> dict[str, int]:
        """uuid -> owning group, TTL cache over a parallel
        /rpc/resolve scatter.  Unknown uuids are absent from the
        result."""
        now = time.monotonic()
        owners: dict[str, int] = {}
        missing: list[str] = []
        for uuid in uuids:
            cached = self._resolve_cache.get(uuid)
            if cached is not None and now - cached[1] < RESOLVE_TTL_S:
                owners[uuid] = cached[0]
                self._resolves.inc(1, {"source": "cache"})
            else:
                missing.append(uuid)
        if not missing:
            return owners
        session = await self._ensure_session()
        import aiohttp

        query = "&".join(f"uuid={u}" for u in missing)

        async def ask(group: int) -> tuple[int, dict]:
            rpc = self._entry(group).get("rpc_url", "")
            if not rpc:
                return group, {}
            try:
                async with session.get(
                        f"{rpc}/rpc/resolve?{query}",
                        timeout=aiohttp.ClientTimeout(total=3.0)) as r:
                    reply = await r.json()
                    return group, reply.get("owned", {})
            except Exception:  # noqa: BLE001 — dead worker: its keys
                # resolve nowhere until the standby adopts
                return group, {}

        replies = await asyncio.gather(
            *(ask(g) for g in self._alive_groups()))
        for group, owned in replies:
            for uuid in owned:
                owners[uuid] = group
                self._resolve_cache[uuid] = (group, now)
                self._resolves.inc(1, {"source": "scatter"})
        for uuid in missing:
            if uuid not in owners:
                self._resolves.inc(1, {"source": "miss"})
        return owners

    # ---------------------------------------------------------- handlers

    async def post_jobs(self, request: web.Request) -> web.Response:
        body_bytes = await request.read()
        try:
            body = json.loads(body_bytes or b"{}")
        except ValueError:
            return web.json_response({"error": "request body must be "
                                               "valid JSON"}, status=400)
        specs = body.get("jobs", [])
        group_specs = body.get("groups", [])
        by_group: dict[int, list] = {}
        for spec in specs:
            pool = spec.get("pool") or self.default_pool
            by_group.setdefault(
                self.topology.group_for_pool(pool), []).append(spec)
        if len(by_group) <= 1:
            # one owner: raw forward, headers (txn id) and body intact
            g = next(iter(by_group), self.topology.meta_group)
            return await self._forward(g, request, body=body_bytes)
        # cross-group: pin uuids here so the per-group payloads are
        # stable under 2PC replay
        from cook_tpu.models.entities import new_uuid

        for spec in specs:
            spec.setdefault("uuid", new_uuid())
        txn_id = request.headers.get("X-Cook-Txn-Id") or new_txn_id()
        user = request.headers.get("X-Cook-Requesting-User", "")
        lowest = min(by_group)
        per_group = {
            g: {"jobs": gspecs,
                # explicit group specs ride the lowest group (the
                # single-process plan's convention); other participants
                # materialize implicit groups from job references
                "groups": group_specs if g == lowest else []}
            for g, gspecs in sorted(by_group.items())}
        t0 = time.perf_counter()
        outcome = await self.coordinator.run(
            txn_id=txn_id, op="jobs/submit", user=user,
            per_group=per_group, rpc_urls=self._rpc_urls())
        # the front-end track of the 2PC waterfall (the coordinator's
        # phase spans ride their own pid track)
        tracing.record_span(
            "mp.submit_2pc", time.perf_counter() - t0, txn_id=txn_id,
            groups=len(per_group), process=distributed.PROCESS_FRONTEND,
            **({} if outcome["ok"] else {"error": True}))
        if not outcome["ok"]:
            self._twopc_stats["vetoes" if outcome["status"] < 500
                              else "errors"] += 1
            return web.json_response({"error": outcome["error"]},
                                     status=outcome["status"])
        self._twopc_stats["commits"] += 1
        uuids: list[str] = []
        for g in sorted(outcome["results"]):
            uuids.extend(
                outcome["results"][g].get("result", {}).get("jobs", []))
        return web.json_response(
            {"jobs": uuids}, status=201,
            headers={"X-Cook-Txn-Id": txn_id,
                     "X-Cook-Shard-Group":
                         ",".join(str(g) for g in sorted(per_group))})

    async def delete_jobs(self, request: web.Request) -> web.Response:
        uuids = request.query.getall("job", []) \
            + request.query.getall("uuid", [])
        owners = await self._resolve_uuids(uuids)
        unknown = [u for u in uuids if u not in owners]
        if unknown:
            return web.json_response(
                {"error": f"unknown jobs: {unknown}"}, status=404)
        groups = sorted(set(owners.values()))
        if len(groups) <= 1:
            g = groups[0] if groups else self.topology.meta_group
            return await self._forward(g, request)
        txn_id = request.headers.get("X-Cook-Txn-Id") or new_txn_id()
        user = request.headers.get("X-Cook-Requesting-User", "")
        per_group = {g: {"uuids": [u for u in uuids if owners[u] == g]}
                     for g in groups}
        t0 = time.perf_counter()
        outcome = await self.coordinator.run(
            txn_id=txn_id, op="jobs/kill", user=user,
            per_group=per_group, rpc_urls=self._rpc_urls())
        tracing.record_span(
            "mp.kill_2pc", time.perf_counter() - t0, txn_id=txn_id,
            groups=len(per_group), process=distributed.PROCESS_FRONTEND,
            **({} if outcome["ok"] else {"error": True}))
        if not outcome["ok"]:
            self._twopc_stats["vetoes" if outcome["status"] < 500
                              else "errors"] += 1
            return web.json_response({"error": outcome["error"]},
                                     status=outcome["status"])
        self._twopc_stats["commits"] += 1
        return web.Response(status=204,
                            headers={"X-Cook-Txn-Id": txn_id})

    async def by_uuid(self, request: web.Request) -> web.Response:
        """Requests keyed by entity uuid (path segment, query params, or
        JSON body `job` field): resolve the owner, forward there."""
        uuids = [u for u in (request.match_info.get("uuid"),) if u]
        for param in ("uuid", "job", "instance"):
            uuids.extend(request.query.getall(param, []))
        body = None
        if not uuids and request.can_read_body:
            body = await request.read()
            try:
                parsed = json.loads(body or b"{}")
                for field in ("job", "uuid", "jobs"):
                    value = parsed.get(field)
                    if isinstance(value, str):
                        uuids.append(value)
                    elif isinstance(value, list):
                        uuids.extend(value)
            except ValueError:
                pass
        if not uuids:
            return await self._forward(self.topology.meta_group,
                                       request, body=body)
        owners = await self._resolve_uuids(uuids)
        if not owners:
            return web.json_response(
                {"error": f"unknown entity: {uuids}"}, status=404)
        groups = sorted(set(owners.values()))
        if len(groups) > 1:
            return web.json_response(
                {"error": "entities span shard-groups; issue one "
                          "request per group"}, status=400)
        return await self._forward(groups[0], request, body=body)

    async def by_user(self, request: web.Request) -> web.Response:
        """share/quota: keyed by pool when given, else by user (the
        ShardRouter plan's convention)."""
        body = None
        pool = request.query.get("pool")
        user = request.query.get("user")
        if request.can_read_body:
            body = await request.read()
            try:
                parsed = json.loads(body or b"{}")
                pool = pool or parsed.get("pool")
                user = user or parsed.get("user")
            except ValueError:
                pass
        if pool:
            g = self.topology.group_for_pool(pool)
        elif user:
            g = self.topology.group_for_user(user)
        else:
            g = self.topology.meta_group
        return await self._forward(g, request, body=body)

    async def scatter(self, request: web.Request) -> web.Response:
        """Fleet-wide read: ask every alive group, merge structurally,
        stamp the WORST staleness seen (a merged read is only as fresh
        as its stalest contributor)."""
        alive = self._alive_groups()
        replies = await asyncio.gather(
            *(self._forward(g, request) for g in alive))
        merged = None
        worst_staleness = -1.0
        errors = []
        for g, resp in zip(alive, replies):
            if resp.status >= 400:
                errors.append(g)
                continue
            try:
                part = json.loads(resp.body or b"null")
            except ValueError:
                continue
            merged = part if merged is None else _merge(merged, part)
            staleness = resp.headers.get("X-Cook-Staleness-Ms")
            if staleness is not None:
                worst_staleness = max(worst_staleness, float(staleness))
        if merged is None:
            return web.json_response(
                {"error": f"no shard-group answered "
                          f"(failed: {errors})"}, status=502)
        headers = {}
        if worst_staleness >= 0:
            headers["X-Cook-Staleness-Ms"] = str(worst_staleness)
        if errors:
            headers["X-Cook-Partial-Groups"] = \
                ",".join(str(g) for g in errors)
        return web.json_response(merged, headers=headers)

    async def to_meta(self, request: web.Request) -> web.Response:
        return await self._forward(self.topology.meta_group, request)

    async def get_metrics(self, request: web.Request) -> web.Response:
        # the front end's OWN registry (forward/2pc/breaker series);
        # worker registries are scraped at the workers
        return web.Response(text=global_registry.render_prometheus(),
                            content_type="text/plain")

    async def get_debug_shards(self, request: web.Request) \
            -> web.Response:
        with self._map_lock:
            route_map = dict(self._map)
        route_map["breakers"] = {
            str(g): b.state.value for g, b in self.breakers.items()}
        return web.json_response(route_map)

    async def get_debug_frontend(self, request: web.Request) \
            -> web.Response:
        per_group = {}
        for g, reservoir in self._latency.items():
            per_group[str(g)] = {
                "forwarded": reservoir.count,
                "p50_ms": round(reservoir.quantile(0.5) * 1e3, 3),
                "p99_ms": round(reservoir.quantile(0.99) * 1e3, 3),
                "breaker": self.breakers[g].state.value,
                "alive": self._entry(g)["alive"],
                # forward time split by hop (queue / transport / apply /
                # fsync / replication_ack), from the worker's
                # X-Cook-Hop-Walls response headers
                "hops": self.hops.snapshot(g),
            }
        return web.json_response({
            "map_seq": self._map.get("map_seq"),
            "n_groups": self.topology.n_groups,
            "n_shards": self.topology.n_shards,
            "per_group": per_group,
            "twopc": dict(self._twopc_stats),
            "resolve_cache": len(self._resolve_cache),
        })

    async def get_debug_trace(self, request: web.Request) \
            -> web.Response:
        """Federated trace collection: scatter GET /debug/trace?txn_id=
        to every live group, merge the slices with the front end's own
        spans (dedup + per-process pid tracks), and emit ONE
        Chrome-trace file (`?format=raw` for the merged ring entries).
        A txn id is required — the whole-ring export lives on the
        workers; this endpoint answers "show me THIS request's
        cross-process critical path"."""
        txn_id = request.query.get("txn_id")
        if not txn_id:
            return web.json_response(
                {"error": "txn_id is required (per-transaction merged "
                          "trace; whole-ring exports live on the "
                          "workers' /debug/trace)"}, status=400)
        fmt = request.query.get("format", "chrome")
        if fmt not in ("chrome", "raw"):
            return web.json_response(
                {"error": f"unknown format {fmt!r} (chrome | raw)"},
                status=400)
        sources = [{"process": distributed.PROCESS_FRONTEND,
                    "spans": tracing.spans_for_txn(txn_id)}]
        alive = self._alive_groups()
        worker_path = f"/debug/trace?txn_id={txn_id}&format=raw"
        replies = await asyncio.gather(
            *(self._forward(g, request, path=worker_path)
              for g in alive))
        failed: list[int] = []
        for g, resp in zip(alive, replies):
            if resp.status != 200:
                failed.append(g)
                continue
            try:
                payload = json.loads(resp.body or b"{}")
            except ValueError:
                failed.append(g)
                continue
            sources.append({
                "process": (payload.get("process")
                            or distributed.worker_process_label(g)),
                "spans": payload.get("spans") or []})
        merged = distributed.merge_process_traces(sources)
        distributed.note_collection(
            "empty" if not merged else
            "partial" if failed else "merged")
        if fmt == "raw":
            return web.json_response(
                {"txn_id": txn_id, "spans": merged,
                 "groups_asked": alive, "groups_failed": failed})
        return web.json_response(distributed.merged_chrome_trace(merged))

    async def get_debug_incidents(self, request: web.Request) \
            -> web.Response:
        """The front end's OWN federated incident index (worker-local
        bundles stay on the workers' /debug/incidents)."""
        return web.json_response({
            "incidents": self.incidents.bundles(),
            "capacity": self.incidents.capacity,
            "cooldown_s": self.incidents.cooldown_s,
            "dir": self.incidents.dir,
        })

    async def get_debug_incident(self, request: web.Request) \
            -> web.Response:
        incident_id = request.match_info["incident_id"]
        bundle = self.incidents.get(incident_id)
        if bundle is None:
            return web.json_response(
                {"error": f"incident {incident_id} not retained"},
                status=404)
        return web.json_response(bundle)

    async def get_job_timeline(self, request: web.Request) \
            -> web.Response:
        """/jobs/{uuid}/timeline with the cross-group hop stitched in:
        the owning worker renders the job's lifecycle, and when the job
        arrived via a cross-group 2PC the commit decision's prepare
        walls / decision / done timestamps (decision log) are folded
        into the event stream (obs/distributed.py
        stitch_twopc_events)."""
        uuid = request.match_info.get("uuid", "")
        owners = await self._resolve_uuids([uuid])
        if uuid not in owners:
            return web.json_response(
                {"error": f"unknown entity: ['{uuid}']"}, status=404)
        resp = await self._forward(owners[uuid], request)
        if resp.status != 200:
            return resp
        record, done_t = await asyncio.get_running_loop() \
            .run_in_executor(
                None, self.decisions.find_for_uuid, uuid)
        if record is None:
            return resp  # single-group job: the worker's view is whole
        try:
            timeline = json.loads(resp.body or b"{}")
        except ValueError:
            return resp
        out_headers = {k: v for k, v in resp.headers.items()
                       if k.startswith(_HEADER_PREFIX)}
        return web.json_response(
            distributed.stitch_twopc_events(timeline, record, done_t),
            headers=out_headers)

    async def get_debug_health(self, request: web.Request) \
            -> web.Response:
        alive = self._alive_groups()
        replies = await asyncio.gather(
            *(self._forward(g, request) for g in alive))
        per_group, worst = {}, 200
        for g, resp in zip(alive, replies):
            try:
                per_group[str(g)] = json.loads(resp.body or b"{}")
            except ValueError:
                per_group[str(g)] = {"error": resp.status}
            worst = max(worst, resp.status)
        dead = [e["group"] for e in self._map["groups"]
                if not e["alive"]]
        if dead:
            worst = max(worst, 503)
        return web.json_response(
            {"groups": per_group, "dead_groups": dead},
            status=worst if worst != 200 else 200)

    async def post_pool_move(self, request: web.Request) \
            -> web.Response:
        body = await request.read()
        try:
            parsed = json.loads(body or b"{}")
        except ValueError:
            parsed = {}
        dest = parsed.get("pool", "")
        uuids = parsed.get("jobs") or \
            ([parsed["job"]] if parsed.get("job") else [])
        owners = await self._resolve_uuids(uuids)
        groups = sorted(set(owners.values()))
        if dest and groups and \
                any(self.topology.group_for_pool(dest) != g
                    for g in groups):
            # moving a job between shard-groups means moving it between
            # journal segments — out of scope for this runtime
            # (ROADMAP: cross-group rebalancing)
            return web.json_response(
                {"error": "pool-move across shard-groups is not "
                          "supported by the mp runtime"}, status=501)
        g = groups[0] if groups else self.topology.meta_group
        return await self._forward(g, request, body=body)

    # -------------------------------------------------------------- app

    @web.middleware
    async def _map_middleware(self, request: web.Request, handler):
        # arrival stamp: everything between here and the forward's
        # session.request is the "queue" hop of the per-hop split
        request["t_arrival"] = time.perf_counter()
        self._maybe_reload_map()
        return await handler(request)

    def build_app(self) -> web.Application:
        app = web.Application(middlewares=[self._map_middleware])
        r = app.router
        for path in ("/rawscheduler", "/jobs"):
            r.add_post(path, self.post_jobs)
            r.add_delete(path, self.delete_jobs)
            r.add_get(path, self.by_uuid)
        r.add_get("/jobs/{uuid}", self.by_uuid)
        r.add_get("/jobs/{uuid}/timeline", self.get_job_timeline)
        r.add_get("/instances/{uuid}", self.by_uuid)
        r.add_get("/instances", self.by_uuid)
        r.add_delete("/instances", self.by_uuid)
        r.add_get("/group", self.by_uuid)
        r.add_delete("/group", self.by_uuid)
        r.add_get("/retry", self.by_uuid)
        r.add_post("/retry", self.by_uuid)
        r.add_put("/retry", self.by_uuid)
        r.add_get("/progress/{uuid}", self.by_uuid)
        r.add_post("/progress/{uuid}", self.by_uuid)
        r.add_post("/heartbeat/{uuid}", self.by_uuid)
        r.add_post("/pool-move", self.post_pool_move)
        for path in ("/share", "/quota"):
            r.add_get(path, self.by_user)
            r.add_post(path, self.by_user)
            r.add_delete(path, self.by_user)
        for path in sorted(SCATTER_ROUTES):
            r.add_get(path, self.scatter)
        r.add_get("/metrics", self.get_metrics)
        r.add_get("/debug/shards", self.get_debug_shards)
        r.add_get("/debug/frontend", self.get_debug_frontend)
        r.add_get("/debug/health", self.get_debug_health)
        r.add_get("/debug/trace", self.get_debug_trace)
        r.add_get("/debug/incidents", self.get_debug_incidents)
        r.add_get("/debug/incidents/{incident_id}",
                  self.get_debug_incident)
        # everything else (pools/settings/info/config/debug) lives on
        # the meta group
        r.add_route("*", "/{tail:.*}", self.to_meta)

        async def _on_startup(app):
            await self._ensure_session()
            await self.coordinator.replay(self._rpc_urls())

        async def _on_cleanup(app):
            if self._session is not None:
                await self._session.close()
                self._session = None
            self.coordinator.decisions.close()

        app.on_startup.append(_on_startup)
        app.on_cleanup.append(_on_cleanup)
        return app
