"""Cross-group transactions: two-phase ordered apply over RPC.

This generalizes `cook_tpu/shard/txn.py`'s in-process discipline to
workers in separate processes:

  * ascending order — participants are contacted in ascending GROUP
    order for both phases, the cross-process analog of the ascending
    shard-lock acquisition that makes concurrent cross-shard commits
    deadlock-free;
  * all-or-nothing veto — prepare runs the full single-process
    validation on every participant (rest/api.py `parse_submission`
    for submits, existence + ownership for kills); ANY veto aborts the
    whole transaction and the client sees the same 4xx a one-process
    submit would have produced;
  * single journaled decision — the coordinator appends
    {"decision": "commit"} (fsynced) BEFORE sending any commit, and
    {"decision": "done"} after every participant acknowledged.  A
    decision with no "done" is replayed on reconnect/restart; no
    decision means presumed abort (participants GC their staged
    prepare after a TTL);
  * idempotent replay — the commit RPC CARRIES the payload, so a
    participant that lost its staged prepare (crash between phases, a
    standby that adopted the segments) re-validates and applies from
    the payload, while one that already applied answers from its
    per-shard idempotency table.  Replaying a decision any number of
    times converges.

The coordinator is async (it lives on the front end's event loop); the
injectable `post` transport is how tests drive veto/abort/replay paths
without sockets.
"""
from __future__ import annotations

import asyncio
import json
import logging
import os
import threading
import time
from typing import Awaitable, Callable, Optional

from cook_tpu.obs import distributed
from cook_tpu.utils import tracing
from cook_tpu.utils.metrics import global_registry

log = logging.getLogger(__name__)

# cross-group txns pay two RPC rounds + the participants' fsyncs: ms to
# seconds under fsync stalls
_TXN_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5, 5.0, 10.0, float("inf"))

# transport: async (url, body_dict, timeout_s, headers) -> (status:int,
# body:dict) — headers carry the trace context (X-Cook-Txn-Id +
# X-Cook-Parent-Span) so participants open child spans under the
# coordinator's phase span (obs/distributed.py header contract)
PostFn = Callable[[str, dict, float, Optional[dict]], Awaitable[tuple]]


class DecisionLog:
    """The coordinator's write-ahead decision journal (jsonl).

    Two record kinds per txn_id: the COMMIT decision (op, user, and the
    per-group payload split — everything replay needs to re-send
    commits) and the DONE marker once every participant acknowledged.
    Append is flush+fsync: the decision must be durable before the
    first commit RPC leaves, or a coordinator crash could leave some
    participants committed with no record to finish the rest.
    """

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(path, "a", encoding="utf-8")

    def append(self, record: dict) -> None:
        line = json.dumps(record, separators=(",", ":"))
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()
            os.fsync(self._f.fileno())

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()

    def _scan(self):
        """Durable records, oldest first (torn tail dropped — a
        half-written line is a decision that never became durable,
        i.e. presumed abort)."""
        if not os.path.exists(self.path):
            return
        with open(self.path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    return  # torn tail: nothing after it is durable

    def outstanding(self) -> dict[str, dict]:
        """Committed-but-not-done decisions, replayed at coordinator
        start (and after failovers): txn_id -> decision record."""
        pending: dict[str, dict] = {}
        for record in self._scan():
            txn_id = record.get("txn_id")
            if record.get("decision") == "commit":
                pending[txn_id] = record
            elif record.get("decision") == "done":
                pending.pop(txn_id, None)
        return pending

    def find_for_uuid(self, uuid: str) -> tuple[Optional[dict],
                                                Optional[float]]:
        """The newest commit decision whose per-group payload pins this
        job uuid, plus its done-marker timestamp (None while commits
        are still pending replay) — the timeline stitch's source."""
        found: Optional[dict] = None
        done_t: Optional[float] = None
        for record in self._scan():
            if record.get("decision") == "commit":
                for payload in (record.get("groups") or {}).values():
                    jobs = (payload or {}).get("jobs") or []
                    uuids = (payload or {}).get("uuids") or []
                    if uuid in uuids or any(
                            j.get("uuid") == uuid for j in jobs):
                        found, done_t = record, None
                        break
            elif (found is not None and record.get("decision") == "done"
                    and record.get("txn_id") == found.get("txn_id")):
                done_t = record.get("t")
        return found, done_t


class TwoPCCoordinator:
    """Drives prepare/decide/commit across worker RPC endpoints."""

    def __init__(self, post: PostFn, decisions: DecisionLog, *,
                 rpc_timeout_s: float = 10.0,
                 commit_attempts: int = 3,
                 retry_backoff_s: float = 0.2):
        self.post = post
        self.decisions = decisions
        self.rpc_timeout_s = rpc_timeout_s
        self.commit_attempts = commit_attempts
        self.retry_backoff_s = retry_backoff_s
        self._prepares = global_registry.counter(
            "mp.txn.prepares",
            "cross-group 2PC prepare RPCs, per outcome (ok/veto/error)")
        self._commits = global_registry.counter(
            "mp.txn.commits",
            "cross-group 2PC commit RPCs, per outcome (ok/failed)")
        self._aborts = global_registry.counter(
            "mp.txn.aborts", "cross-group 2PC aborts sent to participants")
        self._txn_seconds = global_registry.histogram(
            "mp.txn.seconds",
            "cross-group transaction wall seconds (first prepare sent -> "
            "last commit acked), per op", buckets=_TXN_BUCKETS)

    async def _rpc(self, rpc_url: str, method: str, body: dict, *,
                   group: Optional[int] = None) -> tuple[int, dict]:
        # trace context on every 2PC RPC: the participant opens its
        # child span under the coordinator's phase span
        headers = {distributed.PARENT_SPAN_HEADER: f"twopc.{method}"}
        if body.get("txn_id"):
            headers[distributed.TXN_HEADER] = body["txn_id"]
        t0 = time.perf_counter()
        try:
            status, payload = await self.post(
                f"{rpc_url}/rpc/2pc/{method}", body, self.rpc_timeout_s,
                headers)
            if not isinstance(payload, dict):
                payload = {"ok": False, "error": f"non-JSON {method} reply"}
        except Exception as e:  # noqa: BLE001 — transport failure is a
            # participant outcome, not a coordinator crash
            status, payload = 0, {"ok": False, "transport_error": True,
                                  "error": f"{type(e).__name__}: {e}"}
        tracing.record_span(
            f"twopc.{method}", time.perf_counter() - t0,
            txn_id=body.get("txn_id"),
            process=distributed.PROCESS_COORDINATOR,
            **({} if group is None else {"group": group}),
            **({} if payload.get("ok") else {"error": True}))
        return status, payload

    async def run(self, *, txn_id: str, op: str, user: str,
                  per_group: dict[int, dict],
                  rpc_urls: dict[int, str]) -> dict:
        """One cross-group transaction.  Returns
        {"ok": True, "results": {group: commit-reply},
         "pending_groups": [...]} on commit (pending_groups lists
        participants whose commit RPC kept failing — the decision
        stands and replay finishes them), or
        {"ok": False, "status": http-ish, "error": str} on veto/error.
        """
        groups = sorted(per_group)
        t0 = time.perf_counter()
        prepared: list[int] = []
        prepare_s: dict[str, float] = {}
        for g in groups:  # ascending group order, both phases
            tp0 = time.perf_counter()
            status, reply = await self._rpc(rpc_urls[g], "prepare", {
                "txn_id": txn_id, "op": op, "user": user,
                "payload": per_group[g]}, group=g)
            prepare_s[str(g)] = time.perf_counter() - tp0
            if not reply.get("ok"):
                outcome = ("error" if reply.get("transport_error")
                           or status >= 500 else "veto")
                self._prepares.inc(1, {"outcome": outcome})
                await self._abort(txn_id, prepared, rpc_urls)
                return {"ok": False,
                        "status": int(reply.get("status")
                                      or (502 if outcome == "error"
                                          else 400)),
                        "error": reply.get("error", "prepare failed"),
                        "vetoed_by": g}
            self._prepares.inc(1, {"outcome": "ok"})
            prepared.append(g)
        # the single decision: durable BEFORE any participant applies.
        # `t` + per-group prepare walls ride in the record so the
        # timeline stitch can place the cross-group hop without a
        # second fsync.
        decision = {"txn_id": txn_id, "op": op, "user": user,
                    "decision": "commit", "t": time.time(),
                    "prepare_s": prepare_s,
                    "groups": {str(g): per_group[g] for g in groups},
                    "rpc_urls": {str(g): rpc_urls[g] for g in groups}}
        td0 = time.perf_counter()
        await asyncio.get_running_loop().run_in_executor(
            None, self.decisions.append, decision)
        # the fsynced decision write is its own span on the
        # coordinator's pid track — it IS the commit point
        tracing.record_span(
            "twopc.decision_write", time.perf_counter() - td0,
            txn_id=txn_id, op=op,
            process=distributed.PROCESS_COORDINATOR)
        results, pending = await self._commit_all(txn_id, op, user,
                                                  per_group, rpc_urls)
        if not pending:
            await asyncio.get_running_loop().run_in_executor(
                None, self.decisions.append,
                {"txn_id": txn_id, "decision": "done", "t": time.time()})
        wall = time.perf_counter() - t0
        self._txn_seconds.observe(wall, {"op": op})
        tracing.record_span(
            "twopc.txn", wall, txn_id=txn_id, op=op,
            process=distributed.PROCESS_COORDINATOR)
        return {"ok": True, "results": results,
                "pending_groups": sorted(pending)}

    async def _commit_all(self, txn_id: str, op: str, user: str,
                          per_group: dict[int, dict],
                          rpc_urls: dict[int, str]):
        results: dict[int, dict] = {}
        pending: set[int] = set()
        for g in sorted(per_group):
            reply = None
            for attempt in range(self.commit_attempts):
                _status, reply = await self._rpc(rpc_urls[g], "commit", {
                    "txn_id": txn_id, "op": op, "user": user,
                    "payload": per_group[g]}, group=g)
                if reply.get("ok"):
                    break
                await asyncio.sleep(self.retry_backoff_s * (attempt + 1))
            if reply.get("ok"):
                self._commits.inc(1, {"outcome": "ok"})
                results[g] = reply
            else:
                # the decision stands; this participant applies on
                # replay (or after a standby adopts its segments)
                self._commits.inc(1, {"outcome": "failed"})
                log.warning("2pc %s: commit to group %d failed (%s); "
                            "left for replay", txn_id, g,
                            reply.get("error"))
                pending.add(g)
        return results, pending

    async def _abort(self, txn_id: str, prepared: list[int],
                     rpc_urls: dict[int, str]) -> None:
        """Best-effort abort of already-prepared participants (reverse
        order — unwinding the ascending acquisition).  Participants
        also GC staged prepares by TTL, so a lost abort only delays
        cleanup (presumed abort: no decision record means the txn never
        happened)."""
        for g in reversed(prepared):
            self._aborts.inc()
            await self._rpc(rpc_urls[g], "abort", {"txn_id": txn_id},
                            group=g)

    async def replay(self, rpc_urls: Optional[dict[int, str]]
                     = None) -> dict:
        """Finish outstanding decisions (coordinator restart, worker
        reconnect, post-failover).  Commits are idempotent on the
        participants, so replaying a decision that already applied is a
        duplicate answer, not a re-apply.  `rpc_urls` overrides the
        endpoints recorded in the decision (a promoted standby serves
        the dead worker's groups at a NEW url)."""
        outstanding = await asyncio.get_running_loop().run_in_executor(
            None, self.decisions.outstanding)
        finished, still_pending = 0, 0
        for txn_id, record in outstanding.items():
            per_group = {int(g): payload
                         for g, payload in record["groups"].items()}
            urls = {int(g): url
                    for g, url in (record.get("rpc_urls") or {}).items()}
            if rpc_urls:
                urls.update(rpc_urls)
            _results, pending = await self._commit_all(
                txn_id, record["op"], record.get("user", ""),
                per_group, urls)
            if pending:
                still_pending += 1
            else:
                finished += 1
                await asyncio.get_running_loop().run_in_executor(
                    None, self.decisions.append,
                    {"txn_id": txn_id, "decision": "done",
                     "t": time.time()})
        return {"outstanding": len(outstanding), "finished": finished,
                "still_pending": still_pending}
