"""Shard-group topology: which worker process owns which shards.

The multi-process runtime splits the N global shards (the
`cook_tpu/shard/` keyspace — pool-hash routing, per-shard journal
segments) over G worker processes.  Assignment is CONTIGUOUS blocks in
shard order: group g owns `shards_of_group(g)`, computed purely from
(n_shards, n_groups) so every process — front end, workers, supervisor,
clients holding a route map — derives the identical mapping without
coordination.  Key -> shard stays `ShardRouter`'s stable crc32 hash
(identical across processes and restarts, or journal-segment adoption
would scatter entities onto the wrong workers); key -> group is just
`group_of_shard(shard_for_key)`.

The ROUTE MAP is the serialized topology plus each group's live
endpoints.  The supervisor owns the file (data_dir/mp/routemap.json,
rewritten with a bumped `map_seq` on every failover) and the front end
serves it at GET /debug/shards, which is where shard-aware clients
fetch it for direct reads (client/jobclient.py) — a stale map shows up
as a 421/404 and the client falls back to the front end.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Optional, Sequence

from cook_tpu.shard.router import META_SHARD, MisroutedKey, ShardRouter

ROUTEMAP_SCHEMA = "cook-routemap/v1"


@dataclass(frozen=True)
class ShardGroupTopology:
    """Deterministic (n_shards, n_groups) -> ownership mapping."""

    n_shards: int
    n_groups: int

    def __post_init__(self):
        if self.n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {self.n_shards}")
        if not 1 <= self.n_groups <= self.n_shards:
            raise ValueError(
                f"n_groups must be in [1, {self.n_shards}], "
                f"got {self.n_groups}")

    def shards_of_group(self, group: int) -> tuple[int, ...]:
        """Group g's contiguous shard block; the first
        `n_shards % n_groups` groups carry one extra shard."""
        if not 0 <= group < self.n_groups:
            raise ValueError(f"no group {group} in {self.n_groups}")
        base, rem = divmod(self.n_shards, self.n_groups)
        start = group * base + min(group, rem)
        return tuple(range(start, start + base + (1 if group < rem else 0)))

    def group_of_shard(self, shard: int) -> int:
        if not 0 <= shard < self.n_shards:
            raise ValueError(f"no shard {shard} in {self.n_shards}")
        base, rem = divmod(self.n_shards, self.n_groups)
        # invert the block layout: the first `rem` groups are (base+1)
        # wide, the rest `base` wide
        boundary = rem * (base + 1)
        if shard < boundary:
            return shard // (base + 1)
        return rem + (shard - boundary) // base

    # the group owning the META shard (global config, capacity ledger):
    # pool-less / global ops route here
    @property
    def meta_group(self) -> int:
        return self.group_of_shard(META_SHARD)

    # --------------------------------------------------------------- keys

    def group_for_pool(self, pool: str) -> int:
        return self.group_of_shard(
            ShardRouter(self.n_shards).shard_for_pool(pool))

    def group_for_user(self, user: str) -> int:
        return self.group_of_shard(
            ShardRouter(self.n_shards).shard_for_user(user))

    def pools_for_distinct_groups(self, prefix: str = "pool") -> list[str]:
        """One pool name per GROUP (probing the stable hash, the
        `pools_for_distinct_shards` pattern): a per-pool traffic split
        is then also a per-worker split — the killed-worker chaos drill
        and `loadtest --mp` blast-radius accounting depend on it."""
        found: dict[int, str] = {}
        i = 0
        while len(found) < self.n_groups:
            name = f"{prefix}{i}"
            found.setdefault(self.group_for_pool(name), name)
            i += 1
        return [found[g] for g in sorted(found)]


class GroupShardRouter(ShardRouter):
    """A worker's view of the global router: keys hash over the GLOBAL
    shard space, then map to this group's local shard indices.

    The worker's ShardedStore holds only its owned shards (local index
    order = ascending global shard id), so `plan()` and every facade
    lookup keep working unchanged — they just see local indices.  A key
    whose global shard this group does not own raises `MisroutedKey`
    (stale front-end map / stale client map), which the REST layer
    answers with 421 instead of writing into the wrong journal segment.
    """

    def __init__(self, n_global_shards: int, owned: Sequence[int]):
        owned = tuple(sorted(owned))
        if not owned:
            raise ValueError("a shard group must own at least one shard")
        for shard in owned:
            if not 0 <= shard < n_global_shards:
                raise ValueError(f"shard {shard} outside global space "
                                 f"of {n_global_shards}")
        # n_shards is the LOCAL count: ShardedStore sizes its shard list
        # and RoutePlan indices off it
        super().__init__(len(owned))
        self.n_global_shards = n_global_shards
        self.owned = owned
        self._local = {g: i for i, g in enumerate(owned)}

    def _localize(self, global_shard: int, key: str) -> int:
        local = self._local.get(global_shard)
        if local is None:
            raise MisroutedKey(key, global_shard, self.owned)
        return local

    def shard_for_pool(self, pool: str) -> int:
        return self._localize(
            ShardRouter(self.n_global_shards).shard_for_pool(pool),
            f"pool {pool!r}")

    def shard_for_user(self, user: str) -> int:
        return self._localize(
            ShardRouter(self.n_global_shards).shard_for_user(user),
            f"user {user!r}")


# ------------------------------------------------------------- route map


def build_route_map(topology: ShardGroupTopology,
                    entries: dict, map_seq: int = 1) -> dict:
    """The serialized topology + live endpoints.  `entries` maps group
    -> {"url", "rpc_url", "alive"}; groups without an entry render as
    dead (alive=False, empty urls) so a partially-booted fleet still
    serializes."""
    groups = []
    for g in range(topology.n_groups):
        entry = entries.get(g, {})
        groups.append({
            "group": g,
            "shards": list(topology.shards_of_group(g)),
            "url": entry.get("url", ""),
            "rpc_url": entry.get("rpc_url", ""),
            "alive": bool(entry.get("alive", False)),
        })
    return {
        "schema": ROUTEMAP_SCHEMA,
        "map_seq": int(map_seq),
        "n_shards": topology.n_shards,
        "n_groups": topology.n_groups,
        "groups": groups,
    }


def topology_of(route_map: dict) -> ShardGroupTopology:
    return ShardGroupTopology(int(route_map["n_shards"]),
                              int(route_map["n_groups"]))


def write_route_map(path: str, route_map: dict) -> None:
    """Atomic rewrite (tmp + fsync + rename): the front end and clients
    re-read on mtime change, and must never see a torn map."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(route_map, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_route_map(path: str) -> Optional[dict]:
    if not os.path.exists(path):
        return None
    with open(path) as f:
        route_map = json.load(f)
    if route_map.get("schema") != ROUTEMAP_SCHEMA:
        raise ValueError(f"unknown route map schema in {path}: "
                         f"{route_map.get('schema')!r}")
    return route_map
