"""Worker-fleet supervisor: spawn, watch, fail over.

The supervisor owns the fleet's shape: it writes the shard manifest,
spawns one `cook_tpu.mp.worker` process per shard-group plus N warm
standbys (RPC port up, no shards), writes the route map the front end
and shard-aware clients read, and then watches.

Death detection is two-signal: the child's exit status
(`Popen.poll()`) catches clean crashes instantly, and a
`FleetObservatory` polling each worker's REST /debug/health catches
the uglier half — a live process that stopped answering (hung event
loop, SIGSTOP, network partition in a real deployment).  Either
signal, sustained for `unreachable_threshold` consecutive checks
(exit is immediate), triggers failover:

  1. the dead group's route-map entry is marked dead (map_seq bump,
     atomic rewrite) — the front end starts failing fast for those
     keys instead of burning its breaker on a corpse;
  2. a standby is told to `adopt` the group: it recovers the group's
     journal segments from `data_dir/shards/shard-NN/` (every acked
     commit is an fsynced journal line, so nothing acked is lost) and
     brings the REST surface up;
  3. the map is rewritten again with the standby's urls (alive=True),
     the front end re-reads it, clears its resolve cache, and replays
     any outstanding 2PC decisions at the new rpc_url;
  4. a replacement standby is spawned to restore the spare pool.

With no standby available the supervisor falls back to a cold respawn
of the group (same recovery path, slower by one process boot).

`MpRuntime` is the one-call harness (supervisor + front end) that
tools/loadtest.py --mp, tools/chaos.py killed-worker, and the
control_plane_mp bench phase drive.  spawn/fetch are injectable so
tests exercise failover without processes or sockets
(`Supervisor.check_once()` runs one monitor pass synchronously).
"""
from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Callable, Optional

from cook_tpu.obs import distributed
from cook_tpu.txn.transaction import new_txn_id
from cook_tpu.utils import tracing
from cook_tpu.mp.topology import (ShardGroupTopology, build_route_map,
                                  write_route_map)
from cook_tpu.utils.metrics import global_registry

log = logging.getLogger(__name__)

READY_TIMEOUT_S = 90.0  # a worker boot imports jax; generous on 1 cpu


class SubprocessHandle:
    """A spawned worker process + the describe dict it wrote at boot."""

    def __init__(self, proc: subprocess.Popen, describe: dict,
                 log_path: str = ""):
        self.proc = proc
        self.describe = describe
        self.log_path = log_path

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self, sig: int = signal.SIGTERM) -> None:
        if self.alive():
            self.proc.send_signal(sig)

    def join(self, timeout: float = 10.0) -> None:
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=timeout)


class InprocessHandle:
    """A `ShardGroupWorker` embedded in this process (tier-1 tests and
    smoke harnesses: no subprocess boot, no jax re-import)."""

    def __init__(self, worker):
        self.worker = worker
        self.describe = worker.describe()
        self._killed = False

    def alive(self) -> bool:
        return not self._killed

    def kill(self, sig: int = signal.SIGTERM) -> None:
        self._killed = True
        self.worker.stop()

    def join(self, timeout: float = 10.0) -> None:
        pass


class Supervisor:
    def __init__(self, data_dir: str, *, n_shards: int, n_groups: int,
                 pools: tuple = ("default",), standbys: int = 1,
                 spawn_fn: Optional[Callable] = None,
                 fetch_fn: Optional[Callable] = None,
                 post_fn: Optional[Callable] = None,
                 poll_s: float = 0.5,
                 unreachable_threshold: int = 3,
                 journal_kw: Optional[dict] = None):
        self.data_dir = data_dir
        self.topology = ShardGroupTopology(n_shards, n_groups)
        self.pools = tuple(pools)
        self.n_standbys = standbys
        self.spawn_fn = spawn_fn or self._spawn_subprocess
        self.post_fn = post_fn or self._post
        self.poll_s = poll_s
        self.unreachable_threshold = unreachable_threshold
        self.journal_kw = dict(journal_kw or {})
        self.workers: dict[int, object] = {}  # group -> handle
        self.standbys: list = []
        self.map_seq = 0
        self.map_path = os.path.join(data_dir, "mp", "routemap.json")
        self._miss: dict[int, int] = {}  # group -> consecutive misses
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.observatory = None
        self._fetch_fn = fetch_fn
        self._failovers = global_registry.counter(
            "mp.failovers",
            "standby promotions / cold respawns per shard-group")
        self._alive_gauge = global_registry.gauge(
            "mp.workers_alive",
            "shard-group workers currently serving (standbys excluded)")

    # ------------------------------------------------------------- spawn

    def _spawn_subprocess(self, *, group: Optional[int],
                          shards: tuple) -> SubprocessHandle:
        from cook_tpu.rest.server import free_port

        name = f"g{group}" if group is not None \
            else f"standby-{int(time.monotonic() * 1e3) % 100000}"
        mp_dir = os.path.join(self.data_dir, "mp")
        os.makedirs(mp_dir, exist_ok=True)
        ready_file = os.path.join(mp_dir, f"ready-{name}.json")
        if os.path.exists(ready_file):
            os.remove(ready_file)
        log_path = os.path.join(mp_dir, f"worker-{name}.log")
        cmd = [sys.executable, "-m", "cook_tpu.mp.worker",
               "--data-dir", self.data_dir,
               "--n-shards", str(self.topology.n_shards),
               "--shards", ",".join(str(s) for s in shards),
               "--pools", ",".join(self.pools),
               "--port", str(free_port()),
               "--rpc-port", str(free_port()),
               "--ready-file", ready_file]
        if group is not None:
            cmd += ["--group", str(group)]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        log_f = open(log_path, "ab")
        proc = subprocess.Popen(cmd, stdout=log_f, stderr=log_f,
                                env=env)
        log_f.close()
        deadline = time.monotonic() + READY_TIMEOUT_S
        while time.monotonic() < deadline:
            if os.path.exists(ready_file):
                with open(ready_file) as f:
                    describe = json.load(f)
                return SubprocessHandle(proc, describe, log_path)
            if proc.poll() is not None:
                raise RuntimeError(
                    f"worker {name} died at boot "
                    f"(exit {proc.returncode}); see {log_path}")
            time.sleep(0.05)
        proc.kill()
        raise RuntimeError(f"worker {name} missed the ready deadline")

    def _post(self, url: str, body: dict, timeout_s: float = 30.0,
              headers: Optional[dict] = None):
        req = urllib.request.Request(
            url, method="POST", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json",
                     **(headers or {})})
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return r.status, json.loads(r.read() or b"{}")

    # --------------------------------------------------------- lifecycle

    def start(self) -> "Supervisor":
        from cook_tpu.obs.fleet import FleetObservatory
        from cook_tpu.shard.journal import write_manifest

        os.makedirs(self.data_dir, exist_ok=True)
        write_manifest(self.data_dir, self.topology.n_shards)
        for g in range(self.topology.n_groups):
            self.workers[g] = self.spawn_fn(
                group=g, shards=self.topology.shards_of_group(g))
        for _ in range(self.n_standbys):
            self.standbys.append(self.spawn_fn(group=None, shards=()))
        self._write_map()
        # the observatory polls each worker's REST surface; its rows
        # (row["ok"]) are the liveness signal check_once consumes
        self.observatory = FleetObservatory(
            peers=tuple(h.describe["url"] for h in self.workers.values()),
            poll_s=self.poll_s, timeout_s=2.0,
            fetch_fn=self._fetch_fn)
        self._alive_gauge.set(float(len(self.workers)))
        self._thread = threading.Thread(target=self._monitor,
                                        name="mp-supervisor",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        for handle in list(self.workers.values()) + self.standbys:
            handle.kill(signal.SIGTERM)
        for handle in list(self.workers.values()) + self.standbys:
            handle.join()

    # --------------------------------------------------------- route map

    def _write_map(self) -> None:
        with self._lock:
            self.map_seq += 1
            entries = {
                g: {"url": h.describe["url"],
                    "rpc_url": h.describe["rpc_url"],
                    "alive": h.alive()}
                for g, h in self.workers.items()}
            write_route_map(self.map_path, build_route_map(
                self.topology, entries, map_seq=self.map_seq))

    # --------------------------------------------------------- monitoring

    def _monitor(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 — the monitor must outlive
                # any single bad poll
                log.exception("supervisor check failed")

    def check_once(self) -> list[int]:
        """One monitor pass; returns the groups failed over (tests call
        this directly instead of racing the thread)."""
        rows = self.observatory.poll_once() if self.observatory else {}
        failed: list[int] = []
        for g, handle in list(self.workers.items()):
            if not handle.alive():
                misses = self.unreachable_threshold  # exit: no grace
            else:
                row = rows.get(handle.describe["url"].rstrip("/"), {})
                if row and not row.get("ok", False):
                    misses = self._miss.get(g, 0) + 1
                else:
                    misses = 0
            self._miss[g] = misses
            if misses >= self.unreachable_threshold:
                failed.append(g)
        for g in failed:
            self.failover(g)
        self._alive_gauge.set(float(sum(
            1 for h in self.workers.values() if h.alive())))
        return failed

    # ----------------------------------------------------------- failover

    def failover(self, group: int) -> None:
        """Promote a standby to adopt `group`'s journal segments (cold
        respawn when the spare pool is empty)."""
        t_failover = time.perf_counter()
        old = self.workers[group]
        old_url = old.describe["url"]
        old.kill(signal.SIGKILL)  # ensure the corpse releases nothing
        self._miss[group] = 0
        log.warning("failing over shard-group %d (was %s)", group,
                    old_url)
        # phase 1: the map shows the group dead so the front end fails
        # fast instead of timing out against the corpse
        self._write_map()
        shards = self.topology.shards_of_group(group)
        # trace context: the adoption RPC carries a failover correlation
        # id so the adopter's `mp.adopt` span lands in a stitched trace
        # naming the adopting group (GET /debug/trace?txn_id=<this>)
        failover_txn = f"failover-{group}-{new_txn_id()}"
        adopt_headers = {distributed.TXN_HEADER: failover_txn,
                         distributed.PARENT_SPAN_HEADER: "mp.failover"}
        promoted = None
        while self.standbys and promoted is None:
            standby = self.standbys.pop(0)
            try:
                status, reply = self.post_fn(
                    standby.describe["rpc_url"] + "/rpc/adopt",
                    {"group": group, "shards": list(shards),
                     "pools": list(self.pools)},
                    headers=adopt_headers)
                if status == 200 and reply.get("ok"):
                    standby.describe = {**standby.describe, **reply}
                    promoted = standby
                else:
                    log.error("standby refused adoption: %s", reply)
                    standby.kill(signal.SIGTERM)
            except Exception:  # noqa: BLE001 — a dead standby: try the
                # next one
                log.exception("standby adoption failed")
                standby.kill(signal.SIGTERM)
        if promoted is None:
            log.warning("no standby for group %d; cold respawn", group)
            promoted = self.spawn_fn(group=group, shards=shards)
        self.workers[group] = promoted
        # phase 2: the map points at the adopter; front end re-reads,
        # clears its resolve cache, replays outstanding 2PC decisions
        self._write_map()
        if self.observatory is not None:
            self.observatory.forget_peer(old_url)
            self.observatory.peers = tuple(
                h.describe["url"] for h in self.workers.values())
        self._failovers.inc(1, {"group": str(group)})
        tracing.record_span(
            "mp.failover", time.perf_counter() - t_failover,
            txn_id=failover_txn, group=group,
            process=distributed.PROCESS_FRONTEND)
        # restore the spare pool in the background (a standby boot
        # imports jax: seconds on a small box)
        threading.Thread(target=self._replenish_standby,
                         daemon=True).start()

    def _replenish_standby(self) -> None:
        try:
            self.standbys.append(self.spawn_fn(group=None, shards=()))
        except Exception:  # noqa: BLE001
            log.exception("standby replenish failed")

    # -------------------------------------------------------------- chaos

    def kill_worker(self, group: int,
                    sig: int = signal.SIGKILL) -> None:
        """Chaos entry point: hard-kill a group's worker and let the
        monitor discover it."""
        self.workers[group].kill(sig)


class MpRuntime:
    """Supervisor + front end in one handle: the multi-process analog
    of `rest.server.InprocessControlPlane` (loadtest --mp, the
    killed-worker chaos drill, and the control_plane_mp bench phase all
    drive this)."""

    def __init__(self, *, n_groups: int = 4,
                 n_shards: Optional[int] = None,
                 data_dir: Optional[str] = None,
                 pools: Optional[tuple] = None,
                 standbys: int = 1,
                 inprocess: bool = False,
                 poll_s: float = 0.5,
                 journal_kw: Optional[dict] = None):
        import tempfile

        from cook_tpu.rest.server import ServerThread

        self._tmp = None
        if data_dir is None:
            self._tmp = tempfile.mkdtemp(prefix="cook-mp-")
            data_dir = self._tmp
        self.data_dir = data_dir
        n_shards = n_shards or n_groups
        topology = ShardGroupTopology(n_shards, n_groups)
        if pools is None:
            pools = ("default",
                     *topology.pools_for_distinct_groups())
        self.pools = tuple(pools)
        self._n_shards = n_shards
        self._journal_kw = dict(journal_kw or {})
        spawn_fn = self._spawn_inprocess if inprocess else None
        self.supervisor = Supervisor(
            data_dir, n_shards=n_shards, n_groups=n_groups,
            pools=self.pools, standbys=standbys, spawn_fn=spawn_fn,
            poll_s=poll_s, journal_kw=journal_kw)
        self.supervisor.start()
        from cook_tpu.mp.router import FrontEnd

        self.frontend = FrontEnd(
            self.supervisor.map_path,
            decision_log_path=os.path.join(data_dir, "mp",
                                           "2pc-decisions.jsonl"))
        # federated incidents: a worker's ok->degraded edge seen by the
        # supervisor's fleet poller captures through the FRONT END's
        # recorder — whose collectors embed the 2PC decision-log tail,
        # breaker states, and route map alongside each peer's newest
        # bundle reference (obs/distributed.py add_mp_collectors)
        if self.supervisor.observatory is not None:
            self.supervisor.observatory.incidents = \
                self.frontend.incidents
        self.server = ServerThread(self.frontend)
        self.server.start()

    def _spawn_inprocess(self, *, group: Optional[int],
                         shards: tuple) -> InprocessHandle:
        from cook_tpu.mp.worker import ShardGroupWorker

        worker = ShardGroupWorker(
            data_dir=self.data_dir, n_shards=self._n_shards,
            group=group, shards=shards, pools=self.pools,
            journal_kw=self._journal_kw).start()
        return InprocessHandle(worker)

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.server.port}"

    def stop(self) -> None:
        self.server.stop()
        self.supervisor.stop()
        if self._tmp:
            import shutil

            shutil.rmtree(self._tmp, ignore_errors=True)
