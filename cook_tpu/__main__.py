"""`python -m cook_tpu --config config.json` — run one scheduler node.

Reference: cook.components/-main (components.clj:345).
"""
from __future__ import annotations

import argparse
import logging
import sys

from cook_tpu.components import build_process, shutdown, start_leader_duties
from cook_tpu.utils.config import read_config


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="cook-tpu")
    parser.add_argument("--config", help="path to config json")
    parser.add_argument("--port", type=int)
    parser.add_argument("--no-leader", action="store_true",
                        help="serve REST only (hot standby)")
    parser.add_argument("--mp", type=int, metavar="N",
                        help="multi-process mode: N shard-group worker "
                             "processes behind a shard-aware front end "
                             "(cook_tpu.mp)")
    parser.add_argument("--mp-standbys", type=int, default=1,
                        help="warm standby workers for --mp failover")
    parser.add_argument("--mp-shards", type=int, default=None,
                        help="global shard count for --mp "
                             "(default: one shard per group)")
    parser.add_argument("--data-dir", default=None,
                        help="journal root for --mp "
                             "(default: a temp dir)")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    if args.mp:
        return _mp_main(args)
    overrides = {}
    if args.port:
        overrides["port"] = args.port
    settings = read_config(args.config, overrides)
    if settings.platform:
        # pin the jax platform BEFORE any backend init: a wedged
        # accelerator (or a site hook that force-registers one) must not
        # stall the scheduling loops of a node configured for cpu
        import jax

        jax.config.update("jax_platforms", settings.platform)
    process = build_process(settings)
    print(f"cook-tpu listening on :{settings.port} "
          f"(member {process.member_id})", file=sys.stderr)
    try:
        if not args.no_leader:
            start_leader_duties(process)
        else:
            import time

            while True:
                time.sleep(3600)
    finally:
        shutdown(process)
    return 0


def _mp_main(args) -> int:
    """`python -m cook_tpu --mp 4`: supervised worker fleet + front
    end, blocking until interrupted."""
    import time

    from cook_tpu.mp.supervisor import MpRuntime

    runtime = MpRuntime(n_groups=args.mp, n_shards=args.mp_shards,
                        data_dir=args.data_dir,
                        standbys=args.mp_standbys)
    workers = runtime.supervisor.workers
    print(f"cook-tpu mp front end at {runtime.url} "
          f"({len(workers)} shard-group workers, "
          f"{args.mp_standbys} standby)", file=sys.stderr)
    for g, handle in sorted(workers.items()):
        print(f"  group {g}: {handle.describe['url']} "
              f"shards={handle.describe['shards']}", file=sys.stderr)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        runtime.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
