"""`python -m cook_tpu --config config.json` — run one scheduler node.

Reference: cook.components/-main (components.clj:345).
"""
from __future__ import annotations

import argparse
import logging
import sys

from cook_tpu.components import build_process, shutdown, start_leader_duties
from cook_tpu.utils.config import read_config


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="cook-tpu")
    parser.add_argument("--config", help="path to config json")
    parser.add_argument("--port", type=int)
    parser.add_argument("--no-leader", action="store_true",
                        help="serve REST only (hot standby)")
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    overrides = {}
    if args.port:
        overrides["port"] = args.port
    settings = read_config(args.config, overrides)
    if settings.platform:
        # pin the jax platform BEFORE any backend init: a wedged
        # accelerator (or a site hook that force-registers one) must not
        # stall the scheduling loops of a node configured for cpu
        import jax

        jax.config.update("jax_platforms", settings.platform)
    process = build_process(settings)
    print(f"cook-tpu listening on :{settings.port} "
          f"(member {process.member_id})", file=sys.stderr)
    try:
        if not args.no_leader:
            start_leader_duties(process)
        else:
            import time

            while True:
                time.sleep(3600)
    finally:
        shutdown(process)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
