"""Process wiring: config -> store/clusters/scheduler/REST/trigger loops.

Reference: cook.components (-main, /root/reference/scheduler/src/cook/
components.clj:257-365) + the trigger channels (`make-trigger-chans`,
mesos.clj:89-110) and leadership wiring (mesos.clj:153-328): the REST
server runs on every node; the scheduling loops run only on the leader;
losing leadership fail-fast exits.
"""
from __future__ import annotations

import itertools
import logging
import threading
import time
import uuid as uuid_mod
from dataclasses import dataclass, field
from typing import Callable, Optional

from cook_tpu.cluster.base import ComputeCluster
from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.control.leader import (
    FileLeaseElector,
    HttpLeaseElector,
    InMemoryElector,
    LeaderSelector,
)
from cook_tpu.models.entities import DruMode, Pool
from cook_tpu.models.store import JobStore
from cook_tpu.rest.api import ApiConfig, CookApi
from cook_tpu.rest.server import ServerThread
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
from cook_tpu.utils.config import Settings
from cook_tpu.utils.logging import log_info
from cook_tpu.utils.tracing import span

log = logging.getLogger(__name__)


def wall_clock_ms() -> int:
    return int(time.time() * 1000)


CLUSTER_FACTORIES: dict[str, Callable[[dict, Callable[[], int]], ComputeCluster]] = {}


def register_cluster_factory(kind: str):
    """Factories also attach the per-cluster launch rate limiter
    (launch-rate-limiter, rate_limit.clj:44) from the cluster config:
    {"launch_rate_per_minute": N, "launch_burst": M} — applies to every
    cluster kind, static or REST-created."""

    def deco(fn):
        def wrapped(conf: dict, clock) -> ComputeCluster:
            cluster = fn(conf, clock)
            rate = float(conf.get("launch_rate_per_minute", 0) or 0)
            if rate > 0:
                from cook_tpu.scheduler.ratelimit import (
                    TokenBucketRateLimiter,
                )

                cluster.launch_rate_limiter = TokenBucketRateLimiter(
                    tokens_replenished_per_minute=rate,
                    bucket_size=float(conf.get("launch_burst", rate)),
                    clock=clock,
                )
            return cluster

        CLUSTER_FACTORIES[kind] = wrapped
        return fn
    return deco


@register_cluster_factory("mock")
def _mock_factory(conf: dict, clock) -> ComputeCluster:
    hosts = [
        MockHost(
            node_id=h["node_id"],
            hostname=h.get("hostname", h["node_id"]),
            mem=float(h["mem"]),
            cpus=float(h["cpus"]),
            gpus=float(h.get("gpus", 0.0)),
            disk=float(h.get("disk", 0.0)),
            pool=h.get("pool", "default"),
            attributes=tuple(sorted(h.get("attributes", {}).items())),
            ports=tuple((int(b), int(e)) for b, e in h.get("ports", [])),
        )
        for h in conf.get("hosts", [])
    ]
    return MockCluster(conf["name"], hosts, clock,
                       default_runtime_ms=int(
                           conf.get("default_runtime_ms", 60_000)))


@register_cluster_factory("k8s")
def _k8s_factory(conf: dict, clock) -> ComputeCluster:
    from cook_tpu.cluster.k8s import FakeKubeApi, KubeCluster

    api = conf.get("api") or FakeKubeApi()
    return KubeCluster(conf["name"], api, clock,
                       synthetic_pod_limits=conf.get("synthetic_pods", {}))


@register_cluster_factory("k8s-http")
def _k8s_http_factory(conf: dict, clock) -> ComputeCluster:
    """A real apiserver-backed cluster (kubernetes/api.clj analog):

        {"kind": "k8s-http", "name": "prod", "url": "https://apiserver",
         "namespace": "cook", "token_file": "/var/run/.../token",
         "ca_file": "...", "file_server_port": 8000}
    """
    from cook_tpu.cluster.k8s import KubeCluster
    from cook_tpu.cluster.k8s_http import HttpKubeApi

    api = HttpKubeApi(
        conf["url"],
        namespace=conf.get("namespace", "default"),
        token_file=conf.get("token_file"),
        ca_file=conf.get("ca_file"),
        insecure_skip_verify=bool(conf.get("insecure_skip_verify", False)),
        default_image=conf.get("default_image", "busybox:stable"),
        file_server_port=int(conf.get("file_server_port", 0)),
        file_server_image=conf.get("file_server_image", ""),
        watch_timeout_s=float(conf.get("watch_timeout_s", 300.0)),
        checkpoint_tools_image=conf.get("checkpoint_tools_image", ""),
    )
    cluster = KubeCluster(conf["name"], api, clock,
                          synthetic_pod_limits=conf.get("synthetic_pods", {}))
    api.start()  # pod watch loop (initialize-pod-watch)
    return cluster


class TriggerLoop:
    """A periodic trigger thread (chime/trigger-chan analog).  Also
    manually fireable for tests/simulator."""

    def __init__(self, name: str, interval_s: float, fn: Callable[[], None]):
        self.name = name
        self.interval_s = interval_s
        self.fn = fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "TriggerLoop":
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.fn()
                except Exception:  # noqa: BLE001 — loops must survive
                    log.exception("trigger %s failed", self.name)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"trigger-{self.name}")
        self._thread.start()
        return self

    def fire(self) -> None:
        self.fn()

    def stop(self) -> None:
        self._stop.set()


@dataclass
class CookProcess:
    """Everything one scheduler process runs."""

    settings: Settings
    store: JobStore = None
    clusters: list = field(default_factory=list)
    scheduler: Scheduler = None
    api: CookApi = None
    server: ServerThread = None
    selector: LeaderSelector = None
    loops: list = field(default_factory=list)
    member_id: str = ""
    progress_aggregator: object = None
    heartbeats: object = None
    sandbox_publisher: object = None
    journal: object = None
    # sharded layout: one JournalWriter per shard segment (journal stays
    # None); unsharded: [journal]
    journals: list = field(default_factory=list)
    follower: object = None  # standby-side journal replication
    # durable multi-resolution metrics history (obs/tsdb.py): sampler
    # runs on EVERY node role — a standby's history is the evidence a
    # post-failover investigation reads
    history: object = None
    fleet: object = None     # leader-side fleet observatory (obs/fleet.py)

    def is_leader(self) -> bool:
        return self.selector is not None and self.selector.is_leader


def build_process(
    settings: Settings,
    *,
    clock: Callable[[], int] = wall_clock_ms,
    start_rest: bool = True,
) -> CookProcess:
    sharded = settings.shards > 1
    store = None
    if settings.data_dir:
        # failover recovery: load the last snapshot, then replay the
        # journal suffix after it.  Durability bound: mutations committed
        # through the transaction pipeline (cook_tpu.txn — every REST
        # mutation) are group-fsynced before the call returns, so every
        # acknowledged REST write survives; scheduler-internal events
        # between txn commits ride the journal's batched fsync
        # (JournalWriter.fsync_every) and a crash of the OS (not just the
        # process) may lose up to that many of them.
        import os

        from cook_tpu.models import persistence

        os.makedirs(settings.data_dir, exist_ok=True)
        if sharded:
            from cook_tpu.shard import journal as shard_journal

            if shard_journal.has_single_journal_layout(settings.data_dir):
                # exactly-once layout conversion (manifest-stamped);
                # tools/migrate_journal.py is the offline form
                outcome = shard_journal.migrate_single_journal(
                    settings.data_dir, settings.shards, clock=clock)
                log_info("migrated data_dir to per-shard journal "
                         "segments", component="startup", **{
                             k: v for k, v in outcome.items()
                             if k != "per_shard_jobs"})
            store = shard_journal.recover_sharded(
                settings.data_dir, settings.shards, clock=clock)
        else:
            store = persistence.recover(settings.data_dir, clock=clock)
        if store is not None:
            store.mea_culpa_limit = settings.mea_culpa_failure_limit
            log_info("recovered store from snapshot+journal",
                     component="startup", jobs=len(store.jobs),
                     **store.recovered_stats)
    if store is None:
        if sharded:
            from cook_tpu.shard import ShardedStore

            store = ShardedStore(
                settings.shards,
                mea_culpa_limit=settings.mea_culpa_failure_limit,
                clock=clock)
        else:
            store = JobStore(
                mea_culpa_limit=settings.mea_culpa_failure_limit,
                clock=clock)
    journal = None
    journals = []
    if settings.data_dir:
        from cook_tpu.models import persistence

        if sharded:
            from cook_tpu.shard import journal as shard_journal

            journals = shard_journal.attach_shard_journals(
                store, settings.data_dir,
                fsync_policy=settings.journal_fsync_policy)
        else:
            journal = persistence.attach_journal(
                store, os.path.join(settings.data_dir, "journal.jsonl"),
                fsync_policy=settings.journal_fsync_policy,
            )
            journals = [journal]
    from cook_tpu.utils.logging import attach_passport

    attach_passport(store)
    for pool_conf in settings.pools:
        store.set_pool(Pool(
            name=pool_conf["name"],
            dru_mode=DruMode(pool_conf.get("dru_mode", "default")),
        ))
    clusters = []
    for conf in settings.clusters:
        factory = CLUSTER_FACTORIES.get(conf.get("kind", "mock"))
        if factory is None:
            raise ValueError(f"unknown cluster kind {conf.get('kind')}")
        clusters.append(factory(conf, clock))
    from cook_tpu.scheduler.plugins import registry_from_config

    plugins = registry_from_config(settings.plugins)
    from cook_tpu.txn import TransactionLog

    # ONE commit pipeline for the process: REST mutations and the
    # elastic capacity plane's pool/capacity-delta commits share the
    # journal-backed log (durable-on-ack for both).  Sharded deployments
    # get the partitioned pipeline — per-shard locks, segments,
    # idempotency — behind the same commit() seam.
    if sharded:
        from cook_tpu.shard import ShardedTransactionLog

        txn = ShardedTransactionLog(
            store, journals=journals if journals else None)
    else:
        txn = TransactionLog(store, journal=journal)
    from cook_tpu.elastic import ElasticParams

    elastic_conf = settings.elastic
    elastic_params = ElasticParams(
        enabled=bool(elastic_conf.get(
            "enabled", settings.elastic_interval_s > 0)),
        headroom=float(elastic_conf.get("headroom", 0.1)),
        rank_half_life=int(elastic_conf.get("rank_half_life", 64)),
        reclaim_window=int(elastic_conf.get("reclaim_window", 100)),
        count_block_headroom=bool(
            elastic_conf.get("count_block_headroom", True)),
        gang_block_hosts=int(elastic_conf.get("gang_block_hosts", 0)),
        resident=bool(elastic_conf.get("resident", False)),
    )
    incident_dir = settings.incident_dir
    if not incident_dir and settings.data_dir:
        incident_dir = os.path.join(settings.data_dir, "incidents")
    scheduler = Scheduler(
        store,
        clusters,
        SchedulerConfig(match=settings.match, rebalancer=settings.rebalancer,
                        elastic=elastic_params,
                        speculation=settings.speculation,
                        speculation_horizon_ms=(
                            settings.speculation_horizon_ms),
                        predictor_quantile=settings.predictor_quantile,
                        predictor_window=settings.predictor_window,
                        predictor_min_samples=settings.predictor_min_samples,
                        backfill_weight=settings.backfill_weight,
                        backfill_norm_ms=settings.backfill_norm_ms,
                        incident_capacity=settings.incident_capacity,
                        incident_cooldown_s=settings.incident_cooldown_s,
                        incident_dir=incident_dir,
                        auto_profile=settings.auto_profile,
                        profile_dir=settings.profile_dir),
        plugins=plugins,
        txn=txn,
    )
    # metrics history: durable under data_dir/metrics when persistence
    # is configured, memory-only rings otherwise; the sampler thread
    # runs on every node role (a standby's history survives into the
    # post-failover investigation).  history_sample_s <= 0 leaves the
    # instance queryable but unsampled.
    from cook_tpu.obs.tsdb import HistoryConfig, MetricsHistory

    history = MetricsHistory(
        dir=(os.path.join(settings.data_dir, "metrics")
             if settings.data_dir else None),
        config=HistoryConfig.from_retention(settings.history_sample_s,
                                            settings.history_retention),
    ).start()
    from cook_tpu.rest.auth import authenticator_from_config
    api = CookApi(store, scheduler, ApiConfig(
        default_pool=settings.default_pool,
        admins=settings.admins,
        submission_rate_per_minute=settings.submission_rate_per_minute,
        cors_origins=settings.cors_origins,
        authenticator=(authenticator_from_config(settings.auth)
                       if settings.auth else None),
        executor_token=settings.executor_token,
        replication_sync_ack=settings.replication_sync_ack,
        replication_min_acks=settings.replication_min_acks,
        replication_ack_timeout_s=settings.replication_ack_timeout_s,
        replication_ack_liveness_s=settings.replication_ack_liveness_s,
        load_shedding=settings.load_shedding,
        fault_injection=settings.fault_injection,
        replica_reads=settings.replica_reads,
        replica_staleness_ceiling_ms=settings.replica_staleness_ceiling_ms,
        replica_refuse_after_s=settings.replica_refuse_after_s,
        max_gang_size=int(settings.api.get("max_gang_size", 64)),
    ), plugins=plugins, txn=txn, history=history)
    # close the overload loop (docs/resilience.md reaction (d)): the
    # contention observatory's shed signal also drives the scheduler's
    # considerable-window scaleback.  One flag governs BOTH halves of
    # the reaction — load_shedding: false must not leave the scheduler
    # silently shrinking considerable windows with no knob to stop it
    if settings.load_shedding:
        scheduler.admission.overload_fn = api.shedder.overloaded
    api.queue_limits.limits.per_pool = settings.queue_limit_per_pool
    api.queue_limits.limits.per_user_per_pool = settings.queue_limit_per_user
    process = CookProcess(settings=settings, store=store, clusters=clusters,
                          scheduler=scheduler, api=api, journal=journal,
                          journals=journals, history=history,
                          member_id=str(uuid_mod.uuid4())[:8])
    if start_rest:
        process.server = ServerThread(api, port=settings.port).start()
    return process


def start_leader_duties(process: CookProcess,
                        *, on_loss: Optional[Callable[[], None]] = None,
                        block: bool = True) -> None:
    """Acquire leadership, then start the scheduling loops
    (mesos.clj takeLeadership)."""
    settings = process.settings
    advertised = settings.advertised_url \
        or f"http://127.0.0.1:{settings.port}"
    if settings.leader_endpoint:
        # networked election (the ZK-session analog): no shared
        # filesystem between schedulers, only the lease service address
        elector = HttpLeaseElector(
            settings.leader_endpoint, settings.leader_group,
            process.member_id, advertised_url=advertised,
            ttl_s=settings.leader_ttl_s)
    elif settings.leader_lease_path:
        elector = FileLeaseElector(
            settings.leader_lease_path, process.member_id,
            advertised_url=advertised, ttl_s=settings.leader_ttl_s)
    else:
        elector = InMemoryElector("cook", process.member_id)
    process.selector = LeaderSelector(elector, on_loss=on_loss)
    # while standing by, surface the current leader for REST proxying and
    # keep the scheduler passive: replicated events maintain its indexes
    # but must not re-execute the leader's side effects
    process.api.leader = False
    process.scheduler.active = False
    if hasattr(elector, "current_leader_url"):
        process.api.leader_url = elector.current_leader_url()

        # tail the leader's journal so promotion works from OUR copy of
        # the state (the Datomic-replication role, control/replication.py)
        from cook_tpu.control.replication import JournalFollower

        def set_leader_url(url: str) -> None:
            if not process.selector.is_leader:
                process.api.leader_url = url if url != advertised else ""

        if settings.shards > 1:
            # one follower per shard segment (cook_tpu/shard/replica.py)
            from cook_tpu.shard.replica import ShardedJournalFollower

            process.follower = ShardedJournalFollower(
                process.store,
                leader_url_fn=elector.current_leader_url,
                self_url=advertised,
                data_dir=settings.data_dir,
                journals=process.journals or None,
                as_user=settings.replication_user,
                member_id=process.member_id,
                on_leader_url=set_leader_url,
            ).start()
        else:
            process.follower = JournalFollower(
                process.store,
                leader_url_fn=elector.current_leader_url,
                self_url=advertised,
                data_dir=settings.data_dir,
                journal=process.journal,
                as_user=settings.replication_user,
                member_id=process.member_id,
                on_leader_url=set_leader_url,
            ).start()
        # replica-served reads: heavy GETs on this standby answer from
        # the replayed journal with the follower's staleness bound
        process.api.staleness_fn = process.follower.staleness_view
    process.selector.wait_for_leadership()
    if not process.selector.is_leader:
        return  # stopped while standing by (shutdown during wait)
    if process.follower is not None:
        # full join (stop waits out any in-flight fetch): a late response
        # from a deposed leader must not clobber the state we now own
        process.follower.stop()
    # promotion invariant: the columnar rank index tracked the leader via
    # replicated-event fan-out; verify, and rebuild if anything drifted —
    # a promoted standby must schedule from its replicated state
    # immediately (no REST write in between).
    columnar = getattr(process.scheduler, "columnar", None)
    if columnar is not None and not columnar.consistent_with_store():
        log.warning("columnar index inconsistent at promotion; rebuilding")
        columnar.rebuild()
    # elastic promotion invariant: converge every cluster's capacity to
    # the replicated loan ledger — the old leader may have died between
    # a pool/capacity-delta commit and the cluster resize (scale() is
    # declarative, so this replay is idempotent)
    if process.scheduler.elastic is not None:
        process.scheduler.elastic.reconcile()
    process.scheduler.active = True
    process.api.leader = True
    process.api.leader_url = ""
    # the leader's reads are authoritative — no staleness stamping
    process.api.staleness_fn = None
    log_info("leadership acquired", component="leader",
             member=process.member_id)
    if settings.fleet_poll_s > 0:
        # fleet observatory (obs/fleet.py), a LEADER duty: poll every
        # known peer — the configured Settings.peers list plus every
        # standby that registered itself (with its URL) through the
        # replication ack registry — and serve the merged verdict at
        # GET /debug/fleet.  A peer's ok->degraded edge captures a
        # federated entry in THIS node's incident ring.
        from cook_tpu.obs.fleet import FleetObservatory

        def peer_urls():
            urls = set(settings.peers)
            for meta in list(process.api.replication_ack_meta.values()):
                url = meta.get("url") or ""
                if url.startswith("http"):
                    urls.add(url)
            return sorted(urls)

        process.fleet = FleetObservatory(
            self_url=advertised,
            peers_fn=peer_urls,
            poll_s=settings.fleet_poll_s,
            incidents=process.api.incidents,
            self_verdict_fn=process.api.health_verdict,
            as_user=settings.replication_user,
        ).start()
        process.api.fleet = process.fleet
    fail_stop_journals = [
        j for j in (process.journals or [process.journal])
        if j is not None and getattr(j, "fsync_policy", "") == "fail-stop"]
    if fail_stop_journals:
        # reaction (e), docs/resilience.md: under the fail-stop policy a
        # journal fsync FAILURE demotes this leader (fail-fast,
        # mesos.clj:296-313) so a standby with a working disk takes
        # over; the failing commit itself already surfaced the error to
        # its client
        def _fsync_fail_stop(exc, _p=process):
            log.error("journal fsync failed (%s): fail-stop leader "
                      "demotion", exc)
            sel = _p.selector
            if sel is None or not sel.is_leader:
                return

            def _demote():
                _p.scheduler.active = False
                _p.api.leader = False
                sel.demote()

            # the hook fires on the committing request's thread, UNDER
            # the journal writer's lock: demote on its own thread so the
            # lease release / on_loss callback never run under that lock
            # and the failing commit's error reaches its client first
            threading.Thread(target=_demote, daemon=True,
                             name="fsync-fail-stop").start()

        # sharded: ANY segment's disk failing demotes — a leader that
        # can only persist some shards' commits is not a leader
        for fs_journal in fail_stop_journals:
            fs_journal.on_fsync_error = _fsync_fail_stop
    process.selector.start_heartbeat_thread()

    scheduler = process.scheduler
    store = process.store

    def pools():
        return [p for p in store.pools.values() if p.schedules_jobs]

    def rank_all():
        for pool in pools():
            with span("rank_cycle", pool=pool.name):
                scheduler.rank_cycle(pool)

    # round-robin match dispatch (scheduler.clj:2508)
    pool_cycle = itertools.cycle([None])

    def match_next():
        ps = pools()
        if not ps:
            return
        if settings.pipelined_match and len(ps) > 1:
            with span("match_cycle_pipelined", pools=len(ps)):
                scheduler.match_cycle_pipelined()
            return
        if settings.batched_match and len(ps) > 1:
            with span("match_cycle_batched", pools=len(ps)):
                scheduler.match_cycle_all_pools()
            return
        # rebuild the cycle if pools changed
        nonlocal pool_cycle
        current = getattr(match_next, "_pools", None)
        if current != [p.name for p in ps]:
            match_next._pools = [p.name for p in ps]
            pool_cycle = itertools.cycle(ps)
        pool = next(pool_cycle)
        with span("match_cycle", pool=pool.name):
            scheduler.match_cycle(pool)

    def rebalance_all():
        for pool in pools():
            with span("rebalance_cycle", pool=pool.name):
                scheduler.rebalance_cycle(pool)

    # aux publishers/monitors (progress.clj, heartbeat.clj, sandbox.clj,
    # monitor.clj equivalents)
    from cook_tpu.scheduler.heartbeat import HeartbeatMonitor
    from cook_tpu.scheduler.monitor import collect_all
    from cook_tpu.scheduler.progress import ProgressAggregator
    from cook_tpu.scheduler.sandbox import SandboxPublisher

    process.progress_aggregator = ProgressAggregator(store)
    process.sandbox_publisher = SandboxPublisher(store)

    def kill_via_cluster(task_id: str) -> None:
        inst = store.instances.get(task_id)
        if inst is None:
            return
        cluster = scheduler.cluster_by_name(inst.compute_cluster)
        if cluster is not None:
            cluster.safe_kill_task(task_id)

    process.heartbeats = HeartbeatMonitor(store, kill_via_cluster)
    scheduler.heartbeats = process.heartbeats  # REST /heartbeat delivery

    # k8s-style clusters: failover recovery + periodic anti-entropy scans
    # (determine-expected-state-on-startup + scan-process)
    scannable = [c for c in process.clusters if hasattr(c, "scan_all")]
    for cluster in scannable:
        cluster.determine_expected_state_on_startup({
            i.task_id for i in store.instances.values()
            if not i.status.terminal
            and i.compute_cluster == cluster.name
        })

    process.loops = [
        TriggerLoop("rank", settings.rank_interval_s, rank_all).start(),
        TriggerLoop("progress-publish", 2.0,
                    process.progress_aggregator.publish).start(),
        TriggerLoop("sandbox-publish", 5.0,
                    process.sandbox_publisher.publish).start(),
        TriggerLoop("heartbeats", 30.0, process.heartbeats.check).start(),
        TriggerLoop("monitor", 30.0, lambda: collect_all(store)).start(),
    ]
    if settings.health_watch_interval_s > 0:
        # incident watch: evaluate the MERGED health verdict on a clock
        # so ok->degraded transitions capture evidence bundles (and the
        # auto profile) even when no external prober polls /debug/health
        process.loops.append(
            TriggerLoop("health-watch", settings.health_watch_interval_s,
                        lambda: process.api.health_verdict()).start())
    if scannable:
        process.loops.append(
            TriggerLoop("k8s-scan", 30.0,
                        lambda: [c.scan_all() for c in scannable]).start()
        )
    # mock clusters complete tasks by virtual time; in a live service the
    # wall clock drives them (the simulator drives advance_to itself)
    advanceable = [c for c in process.clusters if hasattr(c, "advance_to")]
    if advanceable:
        process.loops.append(
            TriggerLoop(
                "mock-advance", 0.5,
                lambda: [c.advance_to(store.clock()) for c in advanceable],
            ).start()
        )
    if settings.data_dir:
        import os as _os

        from cook_tpu.models import persistence as _persistence

        snap_path = _os.path.join(settings.data_dir, "snapshot.json")

        def snapshot_and_rotate():
            if settings.shards > 1:
                from cook_tpu.shard import journal as _shard_journal

                _shard_journal.snapshot_sharded(store, settings.data_dir)
                for j in process.journals:
                    j.rotate()
                return
            _persistence.snapshot(store, snap_path)
            if process.journal is not None:
                process.journal.rotate()

        process.loops.append(
            TriggerLoop("snapshot", settings.snapshot_interval_s,
                        snapshot_and_rotate).start()
        )
    process.loops += [
        TriggerLoop("match",
                    max(settings.match_interval_s / max(len(pools()), 1),
                        0.05),
                    match_next).start(),
        TriggerLoop("rebalancer", settings.rebalancer_interval_s,
                    rebalance_all).start(),
    ]
    if scheduler.elastic is not None and settings.elastic_interval_s > 0:
        def elastic_plan():
            with span("elastic_cycle"):
                scheduler.elastic_cycle()

        process.loops.append(
            TriggerLoop("elastic", settings.elastic_interval_s,
                        elastic_plan).start())
    process.loops += [
        TriggerLoop("lingering", settings.lingering_interval_s,
                    lambda: scheduler.kill_lingering_tasks(store.clock())
                    ).start(),
        TriggerLoop("straggler", settings.straggler_interval_s,
                    lambda: scheduler.kill_stragglers(store.clock())).start(),
        TriggerLoop("cancelled", settings.cancelled_interval_s,
                    scheduler.kill_cancelled_tasks).start(),
    ]
    if settings.optimizer_interval_s > 0:
        from cook_tpu.scheduler.optimizer import OptimizerCycle

        cycle = OptimizerCycle()

        def run_optimizer():
            for pool in pools():
                queue = scheduler.pool_queues.get(pool.name)
                cycle.run(queue.jobs if queue else [],
                          store.running_jobs(pool.name), {})

        process.loops.append(
            TriggerLoop("optimizer", settings.optimizer_interval_s,
                        run_optimizer).start()
        )
    if block:
        try:
            while process.selector.is_leader:
                time.sleep(1.0)
        except KeyboardInterrupt:
            pass


def shutdown(process: CookProcess) -> None:
    for loop in process.loops:
        loop.stop()
    if process.fleet is not None:
        process.fleet.stop()
    if process.history is not None:
        process.history.stop()
    if process.follower is not None:
        process.follower.stop()
    if process.selector is not None:
        process.selector.stop()
    if process.server is not None:
        process.server.stop()
    # backend clients may own watch threads (HttpKubeApi): stop them or
    # they keep mutating the torn-down store after failover
    for cluster in process.clusters:
        api_stop = getattr(getattr(cluster, "api", None), "stop", None)
        if callable(api_stop):
            api_stop()
