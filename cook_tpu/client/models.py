"""Typed client-side views of API responses.

Reference: cookclient's Job/Instance dataclasses
(/root/reference/jobclient/python/cookclient/{jobs,instance}.py) — thin
wrappers over the JSON with typed accessors; the raw dict stays available
as `.raw` for fields the wrapper doesn't surface.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass(frozen=True)
class InstanceView:
    raw: dict[str, Any]

    @property
    def task_id(self) -> str:
        return self.raw["task_id"]

    @property
    def status(self) -> str:
        return self.raw["status"]

    @property
    def hostname(self) -> str:
        return self.raw.get("hostname", "")

    @property
    def reason_code(self) -> Optional[int]:
        return self.raw.get("reason_code")

    @property
    def reason_string(self) -> str:
        return self.raw.get("reason_string", "")

    @property
    def mea_culpa(self) -> bool:
        return bool(self.raw.get("reason_mea_culpa"))

    @property
    def exit_code(self) -> Optional[int]:
        return self.raw.get("exit_code")

    @property
    def output_url(self) -> str:
        return self.raw.get("output_url", "")

    @property
    def progress(self) -> int:
        return int(self.raw.get("progress", 0))


@dataclass(frozen=True)
class JobView:
    raw: dict[str, Any]

    @property
    def uuid(self) -> str:
        return self.raw["uuid"]

    @property
    def status(self) -> str:
        return self.raw["status"]

    @property
    def user(self) -> str:
        return self.raw["user"]

    @property
    def name(self) -> str:
        return self.raw.get("name", "")

    @property
    def pool(self) -> str:
        return self.raw.get("pool", "")

    @property
    def mem(self) -> float:
        return float(self.raw.get("mem", 0.0))

    @property
    def cpus(self) -> float:
        return float(self.raw.get("cpus", 0.0))

    @property
    def gpus(self) -> float:
        return float(self.raw.get("gpus", 0.0))

    @property
    def max_retries(self) -> int:
        return int(self.raw.get("max_retries", 1))

    @property
    def retries_remaining(self) -> int:
        return int(self.raw.get("retries_remaining", 0))

    @property
    def instances(self) -> list[InstanceView]:
        return [InstanceView(i) for i in self.raw.get("instances", [])]

    @property
    def last_instance(self) -> Optional[InstanceView]:
        insts = self.instances
        return insts[-1] if insts else None

    @property
    def completed(self) -> bool:
        return self.status == "completed"

    @property
    def succeeded(self) -> bool:
        last = self.last_instance
        return self.completed and last is not None and last.status == "success"
