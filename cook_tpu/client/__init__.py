"""Python job client.

Reference: jobclient/python (cookclient `JobClient`,
/root/reference/jobclient/python/cookclient/__init__.py:46): submit / query
/ kill / wait over the REST API, with dataclass views of jobs and
instances.
"""
from cook_tpu.client.jobclient import (  # noqa: F401
    JobClient,
    JobClientError,
)
from cook_tpu.client.models import InstanceView, JobView  # noqa: F401
