"""JobClient: the programmatic REST client.

Reference behavior: /root/reference/jobclient/python/cookclient/__init__.py
(submit returns uuids, query returns job dicts, kill, wait-for-completion
polling loop with backoff) and the Java client's retry semantics
(jobclient/java JobClient.java).
"""
from __future__ import annotations

import time
import uuid as uuid_mod
from typing import Any, Callable, Optional, Sequence

import requests

from cook_tpu.client.models import InstanceView, JobView


class JobClientError(Exception):
    def __init__(self, message: str, status: Optional[int] = None):
        super().__init__(message)
        self.status = status


ROUTE_MAP_TTL_S = 10.0


class JobClient:
    def __init__(
        self,
        url: str,
        *,
        user: str = "anonymous",
        session: Optional[requests.Session] = None,
        retries: int = 3,
        retry_backoff_s: float = 0.2,
        direct_reads: bool = False,
        max_staleness_ms: float = 5000.0,
    ):
        self.url = url.rstrip("/")
        self.user = user
        self.session = session or requests.Session()
        self.retries = retries
        self.retry_backoff_s = retry_backoff_s
        # shard-aware direct reads (the mp runtime, cook_tpu/mp/): the
        # client fetches the route map from the front end's
        # GET /debug/shards, remembers which shard-group owns each uuid
        # (learned from the X-Cook-Shard-Group response header), and
        # sends read polls (query/wait) straight to the owning worker —
        # skipping the forwarding hop.  Any direct miss (connection
        # error, 404/421 from a moved segment, a staleness header past
        # `max_staleness_ms`) falls back to the front end and drops the
        # cached mapping.  Off by default: against a single-process
        # server /debug/shards 404s once and direct routing stays off.
        self.direct_reads = direct_reads
        self.max_staleness_ms = max_staleness_ms
        self._route_map: Optional[dict] = None
        self._route_map_at = 0.0
        self._uuid_group: dict[str, int] = {}

    # ------------------------------------------------------------- plumbing

    def _headers(self) -> dict:
        return {"X-Cook-Requesting-User": self.user}

    # ------------------------------------------------------ direct routing

    def _group_url(self, group: Optional[int]) -> Optional[str]:
        """The live worker url for a shard-group, from a TTL-cached
        route map; None turns the caller into a front-end request."""
        if group is None or not self.direct_reads:
            return None
        now = time.monotonic()
        if self._route_map is None \
                or now - self._route_map_at > ROUTE_MAP_TTL_S:
            try:
                resp = self.session.get(
                    f"{self.url}/debug/shards",
                    headers=self._headers(), timeout=10)
                if resp.status_code != 200:
                    self.direct_reads = False  # not an mp front end
                    return None
                self._route_map = resp.json()
                self._route_map_at = now
            except requests.RequestException:
                return None
        for entry in self._route_map.get("groups", []):
            if entry["group"] == group:
                return entry["url"] if entry.get("alive") else None
        return None

    def _learn_owner(self, resp, uuids: Sequence[str]) -> None:
        """Remember uuid -> shard-group from the response header the
        front end (and workers via it) stamp on every reply."""
        if not self.direct_reads:
            return
        header = resp.headers.get("X-Cook-Shard-Group", "")
        if not header or "," in header:  # multi-group (2PC) reply
            return
        try:
            group = int(header)
        except ValueError:
            return
        for uuid in uuids:
            self._uuid_group[uuid] = group

    def _drop_owner(self, uuids: Sequence[str]) -> None:
        self._route_map = None  # refetch: the fleet may have failed over
        for uuid in uuids:
            self._uuid_group.pop(uuid, None)

    def _direct_get(self, path: str, uuids: Sequence[str],
                    **kw) -> Optional[requests.Response]:
        """One direct read against the owning worker; None means route
        through the front end instead (and on a miss the mapping is
        dropped so the next poll re-learns)."""
        groups = {self._uuid_group.get(u) for u in uuids}
        if len(groups) != 1 or None in groups:
            return None
        base = self._group_url(groups.pop())
        if base is None:
            return None
        try:
            resp = self.session.get(f"{base}{path}",
                                    headers=self._headers(),
                                    timeout=30, **kw)
        except requests.RequestException:
            self._drop_owner(uuids)
            return None
        if resp.status_code in (404, 421) or resp.status_code >= 500:
            # stale map: the segment moved (421 Misdirected / adopted
            # elsewhere) or the worker is mid-failover
            self._drop_owner(uuids)
            return None
        staleness = resp.headers.get("X-Cook-Staleness-Ms")
        if staleness is not None \
                and float(staleness) > self.max_staleness_ms:
            return None
        return resp

    def _request(self, method: str, path: str, **kw) -> Any:
        last_exc: Optional[Exception] = None
        for attempt in range(self.retries):
            try:
                resp = self.session.request(
                    method, f"{self.url}{path}", headers=self._headers(),
                    timeout=30, **kw,
                )
            except requests.ConnectionError as e:
                last_exc = e
                time.sleep(self.retry_backoff_s * (2 ** attempt))
                continue
            if resp.status_code >= 500:
                last_exc = JobClientError(resp.text, resp.status_code)
                time.sleep(self.retry_backoff_s * (2 ** attempt))
                continue
            if resp.status_code >= 400:
                try:
                    message = resp.json().get("error", resp.text)
                except Exception:
                    message = resp.text
                raise JobClientError(message, resp.status_code)
            return resp
        raise JobClientError(f"request failed after {self.retries} tries: "
                             f"{last_exc}")

    # ------------------------------------------------------------------ api

    def submit(self, jobs: Sequence[dict], *, groups: Sequence[dict] = (),
               pool: Optional[str] = None,
               gang_size: int = 0) -> list[str]:
        """Submit jobs; fills in uuids when absent; returns the uuids.

        `gang_size` >= 2 marks the batch ONE all-or-nothing gang: the
        batch must hold exactly `gang_size` jobs; each gets
        `gang_size=k` and a shared fresh group (the server promotes it
        to unique-host placement, and the scheduler places all k
        members inside one topology block or none at all)."""
        if gang_size:
            if gang_size < 2 or len(jobs) != gang_size:
                raise ValueError(
                    f"gang_size {gang_size} needs a batch of exactly "
                    f"that many jobs (got {len(jobs)})")
            gang_group = str(uuid_mod.uuid4())
        payload = []
        for job in jobs:
            job = dict(job)
            job.setdefault("uuid", str(uuid_mod.uuid4()))
            if pool is not None:
                job.setdefault("pool", pool)
            if gang_size:
                job.setdefault("gang_size", gang_size)
                job.setdefault("group", gang_group)
            payload.append(job)
        body: dict = {"jobs": payload}
        if groups:
            body["groups"] = list(groups)
        resp = self._request("POST", "/jobs", json=body)
        uuids = resp.json()["jobs"]
        self._learn_owner(resp, uuids)
        return uuids

    def query(self, uuids: Sequence[str]) -> list[dict]:
        params = [("uuid", u) for u in uuids]
        direct = self._direct_get("/jobs", uuids, params=params)
        if direct is not None and direct.status_code < 400:
            return direct.json()
        resp = self._request("GET", "/jobs", params=params)
        self._learn_owner(resp, uuids)
        return resp.json()

    def query_views(self, uuids: Sequence[str]) -> list[JobView]:
        """Typed views over `query` (reference cookclient dataclasses)."""
        return [JobView(d) for d in self.query(uuids)]

    def query_instance_view(self, task_id: str) -> InstanceView:
        return InstanceView(self.query_instance(task_id))

    def query_one(self, uuid: str) -> dict:
        direct = self._direct_get(f"/jobs/{uuid}", [uuid])
        if direct is not None and direct.status_code < 400:
            return direct.json()
        resp = self._request("GET", f"/jobs/{uuid}")
        self._learn_owner(resp, [uuid])
        return resp.json()

    def query_instance(self, task_id: str) -> dict:
        return self._request("GET", f"/instances/{task_id}").json()

    def list_jobs(self, user: Optional[str] = None, *,
                  states: Sequence[str] = (), start_ms: int = 0,
                  end_ms: int = 2**62, limit: int = 1000) -> list[dict]:
        params: list = [("user", user or self.user), ("limit", str(limit)),
                        ("start-ms", str(start_ms)), ("end-ms", str(end_ms))]
        for s in states:
            params.append(("state", s))
        return self._request("GET", "/list", params=params).json()

    def kill(self, uuids: Sequence[str]) -> None:
        self._request("DELETE", "/jobs",
                      params=[("uuid", u) for u in uuids])

    def retry(self, uuid: str, retries: int) -> None:
        self._request("POST", "/retry", json={"job": uuid, "retries": retries})

    def wait(self, uuids: Sequence[str], *, timeout_s: float = 300.0,
             poll_s: float = 1.0,
             sleep: Callable[[float], None] = time.sleep) -> list[dict]:
        """Poll until every job completes (reference: JobClient listener/
        wait loops)."""
        deadline = time.monotonic() + timeout_s
        while True:
            jobs = self.query(uuids)
            if all(j["status"] == "completed" for j in jobs):
                return jobs
            if time.monotonic() > deadline:
                raise JobClientError(
                    f"timed out waiting for {[j['uuid'] for j in jobs if j['status'] != 'completed']}"
                )
            sleep(poll_s)

    def usage(self, user: Optional[str] = None) -> dict:
        return self._request("GET", "/usage",
                             params={"user": user or self.user}).json()

    def timeline(self, uuid: str) -> dict:
        """GET /jobs/{uuid}/timeline: the job's causally-ordered
        lifecycle with per-cycle skip/wait attribution."""
        return self._request("GET", f"/jobs/{uuid}/timeline").json()

    def history(self, metric: str = "", *, since: float = 0.0,
                step: str = "raw") -> dict:
        """GET /debug/history: multi-resolution metrics history — the
        series index when `metric` is empty, else the selected series'
        points at the requested resolution (docs/observability.md)."""
        params: dict = {}
        if metric:
            params["metric"] = metric
        if since:
            params["since"] = since
        if step != "raw":
            params["step"] = step
        return self._request("GET", "/debug/history", params=params).json()

    def fleet(self) -> dict:
        """GET /debug/fleet: the leader's merged fleet verdict (one row
        per node, peer staleness, federation reasons)."""
        return self._request("GET", "/debug/fleet").json()

    def fairness(self, pool: Optional[str] = None,
                 ledger: int = 50) -> dict:
        """GET /debug/fairness: per-(pool, user) DRU trajectories, the
        preemption ledger (preemptor/victim users, wasted-work seconds),
        per-pool rollups + Jain index + fragmentation.  Against the mp
        front end the body merges every shard group's pools."""
        params: dict = {"ledger": ledger}
        if pool:
            params["pool"] = pool
        return self._request("GET", "/debug/fairness", params=params).json()

    def trace(self, txn_id: str) -> dict:
        """GET /debug/trace?txn_id=: one transaction's merged
        cross-process trace (raw span records; the mp front end
        federates worker slices, a single node serves its own ring)."""
        return self._request("GET", "/debug/trace",
                             params={"txn_id": txn_id,
                                     "format": "raw"}).json()

    def unscheduled_reasons(self, uuid: str) -> list[dict]:
        resp = self._request("GET", "/unscheduled_jobs",
                             params={"job": uuid})
        return resp.json()[0]["reasons"]

    def groups(self, uuids: Sequence[str], detailed: bool = False) -> list[dict]:
        params: list = [("uuid", u) for u in uuids]
        if detailed:
            params.append(("detailed", "true"))
        return self._request("GET", "/group", params=params).json()
