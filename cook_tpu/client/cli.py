"""`cs` — the command-line client.

Reference: cli/ (/root/reference/cli/cook/subcommands/*): submit, show,
wait, jobs, kill, usage, queue-position; multi-cluster federation — the CLI
reads a config listing several schedulers and fans queries out to all of
them, reporting which cluster owns each uuid (cli/cook/querying.py).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from dataclasses import dataclass
from typing import Optional, Sequence

from cook_tpu.client.jobclient import JobClient, JobClientError

DEFAULT_CONFIG_PATHS = (
    os.path.expanduser("~/.cs.json"),
    ".cs.json",
)


@dataclass
class ClusterConfig:
    name: str
    url: str


def load_config(path: Optional[str] = None) -> list[ClusterConfig]:
    paths = [path] if path else list(DEFAULT_CONFIG_PATHS)
    for p in paths:
        if p and os.path.exists(p):
            with open(p) as f:
                data = json.load(f)
            return [ClusterConfig(c["name"], c["url"])
                    for c in data.get("clusters", [])]
    url = os.environ.get("COOK_SCHEDULER_URL", "http://localhost:12321")
    return [ClusterConfig("default", url)]


def _clients(args) -> list[tuple[ClusterConfig, JobClient]]:
    clusters = load_config(args.config)
    if args.cluster:
        clusters = [c for c in clusters if c.name == args.cluster]
        if not clusters:
            raise SystemExit(f"no cluster named {args.cluster} in config")
    user = args.user or os.environ.get("USER", "anonymous")
    direct = bool(getattr(args, "route_map", False))
    return [(c, JobClient(c.url, user=user, direct_reads=direct))
            for c in clusters]


def _fan_out_query(args, uuids: Sequence[str]):
    """Find each uuid on whichever cluster knows it (querying.py)."""
    found: dict[str, tuple[str, dict]] = {}
    for cluster, client in _clients(args):
        remaining = [u for u in uuids if u not in found]
        if not remaining:
            break
        for uuid in remaining:
            try:
                job = client.query_one(uuid)
                found[uuid] = (cluster.name, job)
            except JobClientError as e:
                if e.status != 404:
                    raise
    return found


def cmd_submit(args) -> int:
    (cluster, client) = _clients(args)[0]
    command = " ".join(args.command)
    if not command and not sys.stdin.isatty():
        command = sys.stdin.read().strip()
    spec = {"command": command, "mem": args.mem, "cpus": args.cpus}
    if args.gpus:
        spec["gpus"] = args.gpus
    if args.name:
        spec["name"] = args.name
    if args.priority is not None:
        spec["priority"] = args.priority
    if args.max_retries is not None:
        spec["max_retries"] = args.max_retries
    if args.pool:
        spec["pool"] = args.pool
    if args.env:
        spec["env"] = dict(kv.split("=", 1) for kv in args.env)
    if args.gang_size:
        # one gang of k copies, all-or-nothing on one topology block
        uuids = client.submit([spec] * args.gang_size,
                              gang_size=args.gang_size)
    else:
        uuids = client.submit([spec] * args.copies)
    for uuid in uuids:
        print(uuid)
    return 0


def cmd_show(args) -> int:
    found = _fan_out_query(args, args.uuid)
    rc = 0
    for uuid in args.uuid:
        if uuid not in found:
            print(f"{uuid}: not found on any cluster", file=sys.stderr)
            rc = 1
            continue
        cluster_name, job = found[uuid]
        if args.json:
            print(json.dumps({"cluster": cluster_name, **job}, indent=2))
        else:
            print(f"{job['uuid']}  {job['status']:9s}  {job['name']}  "
                  f"(cluster {cluster_name}, user {job['user']}, "
                  f"mem {job['mem']}, cpus {job['cpus']})")
            for inst in job.get("instances", []):
                line = (f"  task {inst['task_id']}  {inst['status']:8s}  "
                        f"host {inst['hostname']}")
                if "reason_string" in inst:
                    line += f"  reason: {inst['reason_string']}"
                print(line)
    return rc


def cmd_wait(args) -> int:
    found = _fan_out_query(args, args.uuid)
    missing = [u for u in args.uuid if u not in found]
    if missing:
        print(f"not found: {missing}", file=sys.stderr)
        return 1
    by_cluster: dict[str, list[str]] = {}
    for uuid, (cluster_name, _) in found.items():
        by_cluster.setdefault(cluster_name, []).append(uuid)
    clients = {c.name: cl for c, cl in _clients(args)}
    deadline = time.monotonic() + args.timeout
    for cluster_name, uuids in by_cluster.items():
        remaining = max(1.0, deadline - time.monotonic())
        clients[cluster_name].wait(uuids, timeout_s=remaining)
    print("completed")
    return 0


def cmd_jobs(args) -> int:
    states = args.state.split(",") if args.state else []
    for cluster, client in _clients(args):
        jobs = client.list_jobs(args.lookup_user, states=states,
                                limit=args.limit)
        for job in jobs:
            print(f"{cluster.name}  {job['uuid']}  {job['status']:9s}  "
                  f"{job['name']}")
    return 0


def cmd_kill(args) -> int:
    found = _fan_out_query(args, args.uuid)
    rc = 0
    clients = {c.name: cl for c, cl in _clients(args)}
    for uuid in args.uuid:
        if uuid not in found:
            print(f"{uuid}: not found", file=sys.stderr)
            rc = 1
            continue
        cluster_name, _ = found[uuid]
        clients[cluster_name].kill([uuid])
        print(f"killed {uuid} on {cluster_name}")
    return rc


def _instance_output_url(args, uuid: str) -> Optional[tuple[str, dict]]:
    """Resolve a job/instance uuid to its sandbox file-server URL."""
    found = _fan_out_query(args, [uuid])
    if uuid not in found:
        print(f"{uuid}: not found", file=sys.stderr)
        return None
    _, job = found[uuid]
    insts = job.get("instances", [])
    if not insts:
        print(f"{uuid}: no instances yet", file=sys.stderr)
        return None
    inst = insts[-1]
    url = inst.get("output_url")
    if not url:
        print(f"{uuid}: no sandbox file server available", file=sys.stderr)
        return None
    return url, inst


def cmd_ls(args) -> int:
    import requests

    resolved = _instance_output_url(args, args.uuid)
    if resolved is None:
        return 1
    url, _ = resolved
    params = {"path": args.path} if args.path else {}
    r = requests.get(f"{url}/files/browse", params=params, timeout=30)
    if r.status_code != 200:
        print(f"error: {r.text}", file=sys.stderr)
        return 1
    for entry in r.json():
        print(f"{entry['mode']} {entry['size']:>12}  {entry['path']}")
    return 0


def cmd_cat(args) -> int:
    import requests

    resolved = _instance_output_url(args, args.uuid)
    if resolved is None:
        return 1
    url, _ = resolved
    offset = 0
    while True:
        r = requests.get(f"{url}/files/read",
                         params={"path": args.path, "offset": offset,
                                 "length": 65536}, timeout=30)
        if r.status_code != 200:
            print(f"error: {r.text}", file=sys.stderr)
            return 1
        data = r.json()["data"]
        if not data:
            return 0
        sys.stdout.write(data)
        offset += len(data.encode())


def cmd_tail(args) -> int:
    import requests

    resolved = _instance_output_url(args, args.uuid)
    if resolved is None:
        return 1
    url, _ = resolved
    # seek to the end (offset=-1 returns the size), back off `lines`-ish
    r = requests.get(f"{url}/files/read",
                     params={"path": args.path, "offset": -1}, timeout=30)
    if r.status_code != 200:
        print(f"error: {r.text}", file=sys.stderr)
        return 1
    size = r.json()["offset"]
    offset = max(0, size - args.bytes)
    while True:
        r = requests.get(f"{url}/files/read",
                         params={"path": args.path, "offset": offset,
                                 "length": 65536}, timeout=30)
        data = r.json().get("data", "")
        if data:
            sys.stdout.write(data)
            sys.stdout.flush()
            offset += len(data.encode())
        if not args.follow:
            if not data:
                return 0
        else:
            time.sleep(args.sleep_interval)


def cmd_why(args) -> int:
    """Explain why a job isn't running (unscheduled_jobs)."""
    found = _fan_out_query(args, [args.uuid])
    if args.uuid not in found:
        print(f"{args.uuid}: not found", file=sys.stderr)
        return 1
    cluster_name, job = found[args.uuid]
    clients = {c.name: cl for c, cl in _clients(args)}
    print(f"{args.uuid} is {job['status']} (cluster {cluster_name})")
    if job["status"] == "waiting":
        for reason in clients[cluster_name].unscheduled_reasons(args.uuid):
            line = f"  - {reason['reason']}"
            data = reason.get("data")
            if data:
                line += f"  {data}"
            print(line)
    return 0


def _fmt_ms(ms: Optional[float]) -> str:
    """Human-scale duration: 4100 -> "4.1s", 3_720_000 -> "1h02m"."""
    if ms is None:
        return "?"
    s = ms / 1000.0
    if s < 60:
        return f"{s:.1f}s"
    if s < 3600:
        return f"{int(s // 60)}m{int(s % 60):02d}s"
    return f"{int(s // 3600)}h{int(s % 3600 // 60):02d}m"


def cmd_timeline(args) -> int:
    """Render a job's lifecycle history (GET /jobs/{uuid}/timeline)."""
    found = _fan_out_query(args, [args.uuid])
    if args.uuid not in found:
        print(f"{args.uuid}: not found", file=sys.stderr)
        return 1
    cluster_name, _ = found[args.uuid]
    clients = {c.name: cl for c, cl in _clients(args)}
    tl = clients[cluster_name].timeline(args.uuid)
    if args.json:
        print(json.dumps(tl, indent=2))
        return 0
    print(f"{tl['uuid']}  {tl['state']}  (cluster {cluster_name}, "
          f"user {tl['user']}, pool {tl['pool']}, "
          f"priority {tl['priority']})")
    t0 = tl["submit_time_ms"]
    for event in tl["events"]:
        offset = _fmt_ms(event["t_ms"] - t0)
        kind = event["kind"]
        if kind == "submitted":
            line = f"submitted to pool {event['pool']}"
        elif kind == "waiting":
            line = event.get("summary") or (
                f"{event['cycles']} cycles skipped: {event['code']}")
            extras = [f"rank {event['last_rank']}"
                      if "last_rank" in event else "",
                      f"dru {event['last_dru']:.3f}"
                      if "last_dru" in event else ""]
            extras = ", ".join(e for e in extras if e)
            if extras:
                line += f"  ({extras})"
        elif kind == "matched":
            line = f"matched to {event.get('host', '?')} " \
                   f"(cycle {event['cycle']}"
            if "rank" in event:
                line += f", rank {event['rank']}"
            if "dru" in event:
                line += f", dru {event['dru']:.3f}"
            line += ")"
        elif kind == "launched":
            line = (f"launched task {event['task_id']} on "
                    f"{event['host']} (cluster {event['cluster']})")
        elif kind == "preempted":
            line = (f"PREEMPTED on {event.get('host', '?')} "
                    f"({event.get('reason', '?')})")
            ledger = event.get("preemption")
            if ledger:
                detail = [f"by user {ledger.get('preemptor_user', '?')}"]
                if ledger.get("dru_at_decision") is not None:
                    detail.append(f"dru {ledger['dru_at_decision']:.3f}")
                if ledger.get("runtime_lost_s") is not None:
                    detail.append(
                        f"runtime lost {ledger['runtime_lost_s']:.1f}s")
                line += "  [" + ", ".join(detail) + "]"
        elif kind == "instance-failed":
            line = (f"instance failed on {event.get('host', '?')} "
                    f"({event.get('reason', '?')})")
        elif kind == "completed":
            line = f"completed on {event.get('host', '?')}"
        elif kind == "re-queued":
            line = "re-queued (waiting again)"
        elif kind == "2pc-commit-decision":
            prepares = ", ".join(
                f"g{g} {ms:.1f}ms" for g, ms in sorted(
                    (event.get("prepare_ms") or {}).items()))
            line = (f"2PC commit decision across groups "
                    f"{event.get('groups')} (txn {event.get('txn_id')}"
                    + (f"; prepare {prepares}" if prepares else "") + ")")
        elif kind == "2pc-done":
            line = (f"2PC done across groups {event.get('groups')} "
                    f"(txn {event.get('txn_id')})")
        else:
            line = json.dumps(event)
        print(f"  +{offset:>8}  {line}")
    waiting = tl.get("waiting", {})
    if waiting.get("total_cycles"):
        parts = ", ".join(f"{code}: {n}" for code, n in sorted(
            waiting["cycles_by_reason"].items()))
        print(f"waiting attribution: {waiting['total_cycles']} cycles "
              f"({parts})")
    phases = tl.get("phases", {})
    summary = []
    if "submit_to_first_match_ms" in phases:
        summary.append("submit->first-match "
                       f"{_fmt_ms(phases['submit_to_first_match_ms'])}")
    summary.append(f"total run {_fmt_ms(phases.get('run_ms_total', 0))}")
    if "waiting_ms_current" in phases:
        summary.append(
            f"waiting now {_fmt_ms(phases['waiting_ms_current'])}")
    print("phases: " + ", ".join(summary))
    return 0


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values: Sequence[float], width: int = 60) -> str:
    """Render a value series as a unicode sparkline, downsampled to
    `width` columns (mean per column) — the terminal form of "what did
    this gauge look like for the last N minutes"."""
    values = [v for v in values if v is not None]
    if not values:
        return ""
    if len(values) > width:
        # mean-pool into `width` columns so a long window still fits
        chunk = len(values) / width
        values = [
            sum(col) / len(col) for col in (
                values[int(i * chunk):max(int(i * chunk) + 1,
                                          int((i + 1) * chunk))]
                for i in range(width))]
    lo, hi = min(values), max(values)
    span = hi - lo
    if span <= 0:
        return _SPARK_BLOCKS[0] * len(values)
    return "".join(
        _SPARK_BLOCKS[min(len(_SPARK_BLOCKS) - 1,
                          int((v - lo) / span * len(_SPARK_BLOCKS)))]
        for v in values)


def _fmt_value(v: Optional[float]) -> str:
    if v is None:
        return "?"
    if v == int(v) and abs(v) < 1e9:
        return str(int(v))
    return f"{v:.4g}"


def cmd_history(args) -> int:
    """Render metric history (GET /debug/history) as sparklines."""
    (cluster, client) = _clients(args)[0]
    body = client.history(args.metric, since=-abs(args.window),
                          step=args.step)
    if args.json:
        print(json.dumps(body, indent=2))
        return 0
    if not args.metric:
        for key, info in sorted(body.get("series", {}).items()):
            print(f"{info['points']:>6}  {key}")
        return 0
    series = body.get("series", {})
    if not any(series.values()):
        print(f"{args.metric}: no points in the last {args.window:.0f}s "
              f"on {cluster.name} (is the history sampler running?)",
              file=sys.stderr)
        return 1
    for key in sorted(series):
        points = series[key]
        if not points:
            continue
        if args.step == "raw":
            values = [v for _, v in points]
        else:
            values = [p["mean"] for p in points]
        print(f"{key}  [{args.step}] "
              f"last={_fmt_value(values[-1])} "
              f"min={_fmt_value(min(values))} "
              f"max={_fmt_value(max(values))} n={len(points)}")
        print(f"  {sparkline(values)}")
    return 0


def cmd_fleet(args) -> int:
    """Render the merged fleet verdict (GET /debug/fleet)."""
    rc = 0
    for cluster, client in _clients(args):
        fleet = client.fleet()
        if args.json:
            print(json.dumps({"cluster": cluster.name, **fleet}, indent=2))
            continue
        if not fleet.get("enabled"):
            print(f"{cluster.name}: fleet observatory disabled "
                  f"({fleet.get('detail', 'no peers configured')})")
            continue
        status = fleet.get("status", "?")
        reasons = ", ".join(fleet.get("reasons", [])) or "-"
        print(f"{cluster.name}: {status}  ({fleet.get('peers', 0)} peers, "
              f"reasons: {reasons})")
        for node in fleet.get("nodes", []):
            mark = "*" if node.get("self") else " "
            stale = node.get("staleness") or {}
            worst = max((ms for ms in stale.values() if ms is not None),
                        default=None)
            head = node.get("headline") or {}
            line = (f" {mark} {node.get('url', '?'):40s} "
                    f"{node.get('status', '?'):12s} "
                    f"poll-age {node.get('poll_age_s', 0):5.1f}s")
            if worst is not None:
                line += f"  staleness {worst:.0f}ms"
            if node.get("reasons"):
                line += f"  [{', '.join(node['reasons'])}]"
            if node.get("error"):
                line += f"  ({node['error']})"
            if head:
                line += "  " + " ".join(
                    f"{k.split('.')[-1]}={_fmt_value(v)}"
                    for k, v in sorted(head.items()))
            print(line)
        worst_shard = fleet.get("worst_shard")
        if worst_shard:
            print(f"  worst shard: {worst_shard['node']} "
                  f"shard {worst_shard['shard']} "
                  f"({worst_shard['staleness_ms']:.0f}ms behind)")
        if status != "ok":
            rc = 1
    return rc


def cmd_fairness(args) -> int:
    """Render the fairness observatory (GET /debug/fairness): per-pool
    Jain index, per-user DRU trajectories, preemption rollups and the
    recent ledger tail."""
    rc = 0
    for cluster, client in _clients(args):
        body = client.fairness(pool=args.pool, ledger=args.ledger)
        if args.json:
            print(json.dumps({"cluster": cluster.name, **body}, indent=2))
            continue
        pools = body.get("pools", {})
        if not pools:
            print(f"{cluster.name}: no fairness samples yet "
                  "(has a rank cycle run?)")
            continue
        for pool, view in sorted(pools.items()):
            jain = view.get("jain_index")
            rollups = view.get("rollups", {})
            wasted = rollups.get("wasted_s", {})
            frag = view.get("fragmentation", {})
            print(f"{cluster.name}/{pool}: jain {jain:.3f}  "
                  f"preemptions {rollups.get('preemptions', 0)} "
                  f"({rollups.get('tasks_preempted', 0)} tasks)  "
                  f"wasted {wasted.get('fairness', 0.0):.1f}s fairness / "
                  f"{wasted.get('mea_culpa', 0.0):.1f}s mea-culpa  "
                  f"fragmentation {frag.get('fragmentation', 0.0):.2f}")
            users = view.get("trajectories", {})
            for user in sorted(users,
                               key=lambda u: users[u].get("dru", 0.0),
                               reverse=True):
                point = users[user]
                usage = point.get("usage", {})
                line = (f"   {user:16s} dru {point.get('dru', 0.0):7.3f}  "
                        f"mem {usage.get('mem', 0.0):8.0f}  "
                        f"cpus {usage.get('cpus', 0.0):5.1f}  "
                        f"queued {point.get('queued', 0)}")
                if point.get("queue_dru") is not None:
                    line += f"  queue-dru {point['queue_dru']:.3f}"
                print(line)
            for entry in view.get("ledger", [])[-args.ledger:]:
                victims = entry.get("victims", [])
                vusers = sorted({v.get("user", "?") for v in victims})
                print(f"   ledger t={entry.get('t_ms', 0)}ms "
                      f"{entry.get('preemptor_user', '?')} preempted "
                      f"{len(victims)} task(s) of {', '.join(vusers)} "
                      f"on {entry.get('hostname', '?')} "
                      f"(dru {entry.get('min_preempted_dru', 0.0):.3f}, "
                      f"wasted {entry.get('wasted_s', 0.0):.1f}s)")
    return rc


def cmd_trace(args) -> int:
    """Render one transaction's merged cross-process trace as a text
    waterfall (GET /debug/trace?txn_id=; against the mp front end the
    body federates the front-end, coordinator, and worker slices)."""
    width = 40
    for cluster, client in _clients(args):
        body = client.trace(args.txn_id)
        spans = body.get("spans") or []
        if not spans:
            continue
        if args.json:
            print(json.dumps({"cluster": cluster.name, **body}, indent=2))
            return 0
        starts = [s["t"] - s.get("duration_s", 0.0) for s in spans]
        t0 = min(starts)
        window = max(max(s["t"] for s in spans) - t0, 1e-9)
        procs = [str(s.get("process") or
                     (s.get("tags") or {}).get("process") or "?")
                 for s in spans]
        proc_w = max(len(p) for p in procs)
        name_w = max(len(s["name"]) for s in spans)
        print(f"{args.txn_id}: {len(spans)} spans, "
              f"{len(set(procs))} process(es), "
              f"{window * 1000:.1f}ms window (cluster {cluster.name})")
        for proc, start, s in zip(procs, starts, spans):
            dur = s.get("duration_s", 0.0)
            lead = int((start - t0) / window * width)
            if dur <= 0.0:  # record_event marker (veto, replication ack)
                bar = " " * min(lead, width - 1) + "·"
                stamp = "event"
            else:
                fill = max(1, round(dur / window * width))
                bar = " " * min(lead, width - fill) + "█" * fill
                stamp = f"{dur * 1000:.2f}ms"
            mark = "  !error" if (s.get("tags") or {}).get("error") else ""
            print(f"  {proc:<{proc_w}}  {s['name']:<{name_w}}  "
                  f"|{bar:<{width}}|  {stamp}{mark}")
        failed = body.get("groups_failed")
        if failed:
            print(f"  (groups unreachable during collection: {failed})")
        return 0
    print(f"{args.txn_id}: no spans retained on any cluster "
          f"(span rings are finite — trace soon after the request)",
          file=sys.stderr)
    return 1


def cmd_usage(args) -> int:
    for cluster, client in _clients(args):
        usage = client.usage(args.lookup_user)
        total = usage["total_usage"]
        print(f"{cluster.name}: mem {total['mem']} cpus {total['cpus']} "
              f"gpus {total['gpus']} jobs {total['jobs']}")
    return 0


def cmd_retry(args) -> int:
    found = _fan_out_query(args, args.uuid)
    clients = {c.name: cl for c, cl in _clients(args)}
    for uuid in args.uuid:
        if uuid not in found:
            print(f"{uuid}: not found", file=sys.stderr)
            return 1
        cluster_name, _ = found[uuid]
        clients[cluster_name].retry(uuid, args.retries)
        print(f"set retries={args.retries} for {uuid}")
    return 0


def cmd_config(args) -> int:
    """Show or edit the federation config (reference: cs config)."""
    path = args.config or next(
        (p for p in DEFAULT_CONFIG_PATHS if os.path.exists(p)),
        DEFAULT_CONFIG_PATHS[0],
    )
    data = {"clusters": []}
    if os.path.exists(path):
        with open(path) as f:
            data = json.load(f)
    changed = False
    if args.add_cluster:
        name, url = args.add_cluster
        data["clusters"] = [c for c in data.get("clusters", [])
                            if c["name"] != name]
        data["clusters"].append({"name": name, "url": url})
        changed = True
    if args.remove_cluster:
        data["clusters"] = [c for c in data.get("clusters", [])
                            if c["name"] != args.remove_cluster]
        changed = True
    if changed:
        with open(path, "w") as f:
            json.dump(data, f, indent=2)
        print(f"wrote {path}")
    for c in data.get("clusters", []):
        print(f"{c['name']}\t{c['url']}")
    return 0


def cmd_admin_set_share(args) -> int:
    import requests

    for cluster, client in _clients(args):
        r = requests.post(
            f"{cluster.url}/share",
            json={"user": args.target_user, "pool": args.pool,
                  "share": {"mem": args.mem, "cpus": args.cpus,
                            "gpus": args.gpus},
                  "reason": args.reason},
            headers=client._headers(), timeout=30)
        print(f"{cluster.name}: {r.status_code}")
    return 0


def cmd_admin_set_quota(args) -> int:
    import requests

    quota = {}
    for key in ("mem", "cpus", "gpus", "count"):
        value = getattr(args, key)
        if value is not None:
            quota[key] = value
    for cluster, client in _clients(args):
        r = requests.post(
            f"{cluster.url}/quota",
            json={"user": args.target_user, "pool": args.pool,
                  "quota": quota, "reason": args.reason},
            headers=client._headers(), timeout=30)
        print(f"{cluster.name}: {r.status_code}")
    return 0


def cmd_admin_drain(args) -> int:
    import requests

    for cluster, client in _clients(args):
        r = requests.post(
            f"{cluster.url}/compute-clusters",
            json={"name": args.name, "state": "draining"},
            headers=client._headers(), timeout=30)
        print(f"{cluster.name}: {r.status_code} {r.text.strip()}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cs", description="cook-tpu scheduler CLI"
    )
    p.add_argument("--config", help="path to cluster config json")
    p.add_argument("--cluster", help="restrict to one named cluster")
    p.add_argument("--user", help="requesting user")
    p.add_argument("--route-map", action="store_true", dest="route_map",
                   help="shard-aware direct reads: fetch the route map "
                        "from GET /debug/shards and poll the owning "
                        "worker directly (mp fleets; falls back to the "
                        "front end on staleness or a moved segment)")
    sub = p.add_subparsers(dest="subcommand", required=True)

    sp = sub.add_parser("submit", help="submit a job")
    sp.add_argument("command", nargs="*", help="command to run")
    sp.add_argument("--mem", type=float, default=128.0)
    sp.add_argument("--cpus", type=float, default=1.0)
    sp.add_argument("--gpus", type=float, default=0.0)
    sp.add_argument("--name")
    sp.add_argument("--priority", type=int)
    sp.add_argument("--max-retries", type=int, dest="max_retries")
    sp.add_argument("--pool")
    sp.add_argument("--env", action="append", metavar="K=V")
    sp.add_argument("--copies", type=int, default=1)
    sp.add_argument("--gang-size", type=int, default=0, dest="gang_size",
                    help="submit K copies as ONE all-or-nothing gang "
                         "(all K place inside one topology block or "
                         "none do; overrides --copies)")
    sp.set_defaults(fn=cmd_submit)

    for name, fn, help_ in [
        ("show", cmd_show, "show jobs"),
        ("wait", cmd_wait, "wait for jobs to complete"),
        ("kill", cmd_kill, "kill jobs"),
    ]:
        q = sub.add_parser(name, help=help_)
        q.add_argument("uuid", nargs="+")
        if name == "show":
            q.add_argument("--json", action="store_true")
        if name == "wait":
            q.add_argument("--timeout", type=float, default=300.0)
        q.set_defaults(fn=fn)

    q = sub.add_parser("retry", help="update a job's retries")
    q.add_argument("uuid", nargs="+")
    q.add_argument("--retries", type=int, required=True)
    q.set_defaults(fn=cmd_retry)

    q = sub.add_parser("jobs", help="list a user's jobs")
    q.add_argument("--lookup-user", dest="lookup_user")
    q.add_argument("--state")
    q.add_argument("--limit", type=int, default=150)
    q.set_defaults(fn=cmd_jobs)

    q = sub.add_parser("usage", help="show a user's usage")
    q.add_argument("--lookup-user", dest="lookup_user")
    q.set_defaults(fn=cmd_usage)

    q = sub.add_parser("why", help="explain why a job isn't running")
    q.add_argument("uuid")
    q.set_defaults(fn=cmd_why)

    q = sub.add_parser(
        "timeline",
        help="render a job's full lifecycle history (per-cycle waits, "
             "launches, preemptions, re-queues)")
    q.add_argument("uuid")
    q.add_argument("--json", action="store_true")
    q.set_defaults(fn=cmd_timeline)

    q = sub.add_parser(
        "trace",
        help="render one transaction's merged cross-process trace "
             "(GET /debug/trace?txn_id=) as a text waterfall")
    q.add_argument("txn_id")
    q.add_argument("--json", action="store_true")
    q.set_defaults(fn=cmd_trace)

    q = sub.add_parser(
        "history",
        help="render a metric's retained history as a sparkline "
             "(GET /debug/history); no metric = list tracked series")
    q.add_argument("metric", nargs="?", default="",
                   help="series key, base name, or trailing-* prefix")
    q.add_argument("--step", choices=("raw", "1m", "10m"), default="raw")
    q.add_argument("--window", type=float, default=3600.0,
                   help="seconds of history to render (default 1h)")
    q.add_argument("--json", action="store_true")
    q.set_defaults(fn=cmd_history)

    q = sub.add_parser(
        "fleet",
        help="render the leader's merged fleet verdict (GET /debug/fleet):"
             " one row per node with peer health/staleness")
    q.add_argument("--json", action="store_true")
    q.set_defaults(fn=cmd_fleet)

    q = sub.add_parser(
        "fairness",
        help="render the fairness observatory (GET /debug/fairness): "
             "per-user DRU trajectories, preemption ledger, Jain index")
    q.add_argument("--pool", default=None, help="narrow to one pool")
    q.add_argument("--ledger", type=int, default=10,
                   help="recent preemption-ledger entries to show")
    q.add_argument("--json", action="store_true")
    q.set_defaults(fn=cmd_fairness)

    q = sub.add_parser("config", help="show or edit the federation config")
    q.add_argument("--add-cluster", nargs=2, metavar=("NAME", "URL"))
    q.add_argument("--remove-cluster", metavar="NAME")
    q.set_defaults(fn=cmd_config)

    q = sub.add_parser("ls", help="list a job's sandbox files")
    q.add_argument("uuid")
    q.add_argument("path", nargs="?", default="")
    q.set_defaults(fn=cmd_ls)

    q = sub.add_parser("cat", help="print a sandbox file")
    q.add_argument("uuid")
    q.add_argument("path")
    q.set_defaults(fn=cmd_cat)

    q = sub.add_parser("admin", help="admin operations")
    asub = q.add_subparsers(dest="admin_cmd", required=True)
    aq = asub.add_parser("set-share")
    aq.add_argument("--for-user", required=True, dest="target_user")
    aq.add_argument("--pool", default="default")
    aq.add_argument("--mem", type=float, default=0)
    aq.add_argument("--cpus", type=float, default=0)
    aq.add_argument("--gpus", type=float, default=0)
    aq.add_argument("--reason", default="")
    aq.set_defaults(fn=cmd_admin_set_share)
    aq = asub.add_parser("set-quota")
    aq.add_argument("--for-user", required=True, dest="target_user")
    aq.add_argument("--pool", default="default")
    aq.add_argument("--mem", type=float)
    aq.add_argument("--cpus", type=float)
    aq.add_argument("--gpus", type=float)
    aq.add_argument("--count", type=int)
    aq.add_argument("--reason", default="")
    aq.set_defaults(fn=cmd_admin_set_quota)
    aq = asub.add_parser("drain-cluster")
    aq.add_argument("name")
    aq.set_defaults(fn=cmd_admin_drain)

    q = sub.add_parser("tail", help="tail a sandbox file")
    q.add_argument("uuid")
    q.add_argument("path")
    q.add_argument("--bytes", type=int, default=2048)
    q.add_argument("--follow", "-f", action="store_true")
    q.add_argument("--sleep-interval", type=float, default=1.0)
    q.set_defaults(fn=cmd_tail)

    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except JobClientError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
