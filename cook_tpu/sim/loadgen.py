"""Deploy-scale workload generator: drive a RUNNING service over HTTP.

Reference: simulator/ (simulator/README.md) — distinct from the in-process
trace simulator (sim/simulator.py), this tool generates a randomized
multi-user workload and replays it against a fully deployed scheduler
through the public REST API, measuring what a user of the deployment
measures: submission latency, time-to-first-schedule, completion.

    python -m cook_tpu.sim.cli loadgen --url http://host:port \
        --jobs 500 --rate 600 --users 10 --seed 7 --out results.json

The arrival process is Poisson at `--rate` jobs/minute (compressed by
`--speedup`), job shapes are drawn from skewed size distributions, and
every job carries a short mock runtime so a mock/k8s-backed deployment
completes it quickly.
"""
from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from cook_tpu.client.jobclient import JobClient


@dataclass
class LoadConfig:
    n_jobs: int = 200
    rate_per_minute: float = 600.0
    n_users: int = 8
    seed: int = 0
    speedup: float = 1.0            # >1 compresses inter-arrival gaps
    pool: Optional[str] = None
    runtime_ms_choices: tuple = (500, 1000, 2000)
    mem_choices: tuple = (128, 256, 512, 1024, 4096)
    cpus_choices: tuple = (0.5, 1, 2, 4)
    batch_max: int = 20             # jobs per submit call (burst arrivals)


@dataclass
class LoadReport:
    submitted: int = 0
    completed: int = 0
    failed: int = 0
    wall_s: float = 0.0
    submit_latency_ms: list = field(default_factory=list)
    schedule_latency_ms: dict = field(default_factory=dict)  # uuid -> ms

    def summary(self) -> dict:
        lat = sorted(self.submit_latency_ms)
        sched = sorted(self.schedule_latency_ms.values())

        def pct(values, q):
            if not values:
                return None
            return round(float(np.percentile(values, q)), 1)

        return {
            "submitted": self.submitted,
            "completed": self.completed,
            "failed": self.failed,
            "wall_s": round(self.wall_s, 2),
            "throughput_jobs_per_s": round(
                self.completed / self.wall_s, 2) if self.wall_s else 0,
            "submit_ms_p50": pct(lat, 50),
            "submit_ms_p99": pct(lat, 99),
            "schedule_ms_p50": pct(sched, 50),
            "schedule_ms_p99": pct(sched, 99),
        }


def generate_workload(config: LoadConfig) -> list[tuple[float, dict]]:
    """(arrival_offset_s, job_spec) pairs — Poisson arrivals, skewed
    shapes, round-robin-ish user mix."""
    rng = np.random.default_rng(config.seed)
    gaps = rng.exponential(60.0 / config.rate_per_minute, config.n_jobs)
    offsets = np.cumsum(gaps) / config.speedup
    out = []
    for i in range(config.n_jobs):
        spec = {
            "command": "true",
            "name": f"loadgen-{i}",
            "mem": float(rng.choice(config.mem_choices)),
            "cpus": float(rng.choice(config.cpus_choices)),
            "max_retries": 3,
            "expected_runtime": int(rng.choice(config.runtime_ms_choices)),
            "labels": {"loadgen-user": f"user{int(rng.integers(config.n_users))}"},
            **({"pool": config.pool} if config.pool else {}),
        }
        out.append((float(offsets[i]), spec))
    return out


def imbalanced_pool_trace(
    *,
    busy_jobs: int = 30,
    busy_hosts: int = 2,
    idle_hosts: int = 4,
    host_mem: float = 64_000.0,
    host_cpus: float = 32.0,
    job_mem: float = 8_000.0,
    job_cpus: float = 8.0,
    runtime_ms: int = 60_000,
    n_users: int = 3,
    seed: int = 0,
):
    """The elastic capacity plane's acceptance scenario: two pools, one
    starving while the other idles — exactly the static-partition
    pathology pool loaning exists to fix.

    Pool "busy" gets a burst of `busy_jobs` at t=0 against only
    `busy_hosts` hosts; pool "idle" holds `idle_hosts` identical hosts
    and no work.  Statically partitioned, busy's queue drains in waves
    bounded by its own capacity while idle's fleet sits unused; with
    the planner on (`SimConfig.elastic_every` / `sim.cli run
    --elastic`), idle's capacity is loaned over and the p50 queued-job
    wait drops (asserted in tests/test_elastic.py).  Returns (jobs,
    hosts) TraceJob/TraceHost lists for sim.simulator.Simulator.
    """
    import numpy as np

    from cook_tpu.sim.simulator import TraceHost, TraceJob

    rng = np.random.default_rng(seed)
    jobs = [
        TraceJob(
            uuid=f"busy-{i:05d}",
            user=f"user{int(rng.integers(n_users))}",
            submit_time_ms=0,
            runtime_ms=runtime_ms,
            mem=job_mem,
            cpus=job_cpus,
            pool="busy",
        )
        for i in range(busy_jobs)
    ]
    hosts = [
        TraceHost(node_id=f"busy-h{i}", hostname=f"busy-h{i}",
                  mem=host_mem, cpus=host_cpus, pool="busy")
        for i in range(busy_hosts)
    ] + [
        TraceHost(node_id=f"idle-h{i}", hostname=f"idle-h{i}",
                  mem=host_mem, cpus=host_cpus, pool="idle")
        for i in range(idle_hosts)
    ]
    return jobs, hosts


def completion_heavy_trace(
    *,
    jobs: int = 24,
    hosts: int = 4,
    runtime_ms: int = 30_000,
    host_mem: float = 1000.0,
    host_cpus: float = 4.0,
    n_users: int = 1,
    seed: int = 0,
):
    """The speculative-cycle acceptance scenario (ROADMAP item 3): a
    deep queue draining in waves, every wave's completions freeing the
    capacity the next wave needs — exactly the cadence prediction-
    assisted speculation exploits.

    Each host fits ONE job (job demand == host capacity) and every job
    runs for exactly `runtime_ms`, so with `SimConfig.cycle_ms ==
    runtime_ms` each cycle completes one full wave and matches the next.
    Runtimes are constant per (user, command), so the rolling-quantile
    predictor converges after its first completed wave; from then on the
    speculative solve dispatched during cycle N places wave N+1 and
    commits (the predicted completions land and nothing else moves).
    Asserted A/B: >= 20% of cycles served from speculation and lower
    cycle-start-to-first-launch p50 vs the same trace without
    speculation (tests/test_prediction.py + bench.py's `speculation`
    phase).  Returns (jobs, hosts) for sim.simulator.Simulator."""
    import numpy as np

    from cook_tpu.sim.simulator import TraceHost, TraceJob

    rng = np.random.default_rng(seed)
    out_jobs = [
        TraceJob(
            uuid=f"wave-{i:05d}",
            user=f"user{int(rng.integers(n_users))}",
            submit_time_ms=0,
            runtime_ms=runtime_ms,
            mem=host_mem,
            cpus=host_cpus,
        )
        for i in range(jobs)
    ]
    out_hosts = [
        TraceHost(node_id=f"h{i:03d}", hostname=f"h{i:03d}",
                  mem=host_mem, cpus=host_cpus)
        for i in range(hosts)
    ]
    return out_jobs, out_hosts


def preemption_heavy_trace(
    *,
    hog_jobs: int = 8,
    late_jobs: int = 6,
    hosts: int = 4,
    host_mem: float = 1000.0,
    host_cpus: float = 4.0,
    runtime_ms: int = 600_000,
    late_arrival_ms: int = 60_000,
    n_late_users: int = 3,
    seed: int = 0,
):
    """The fairness observatory's acceptance scenario: one over-share
    user floods the pool at t=0 with long-running hosts-filling jobs
    (each consumes half a host), then `n_late_users` under-share users
    arrive at `late_arrival_ms` with nothing free.  With the rebalancer
    on (`SimConfig.rebalance_every` + a share set for the default user
    so DRU is finite) the late arrivals can only start by preempting the
    hog — so vs the standard trace the run shows a depressed Jain index
    while the hog monopolizes, nonzero `fairness.wasted_work_seconds`,
    and a populated preemption ledger (asserted A/B in
    tests/test_fairness.py).  Returns (jobs, hosts) TraceJob/TraceHost
    lists for sim.simulator.Simulator."""
    import numpy as np

    from cook_tpu.sim.simulator import TraceHost, TraceJob

    rng = np.random.default_rng(seed)
    jobs = [
        TraceJob(
            uuid=f"hog-{i:05d}",
            user="hog",
            submit_time_ms=0,
            runtime_ms=runtime_ms,
            mem=host_mem / 2.0,
            cpus=host_cpus / 2.0,
        )
        for i in range(hog_jobs)
    ] + [
        TraceJob(
            uuid=f"late-{i:05d}",
            user=f"late{int(rng.integers(n_late_users))}",
            submit_time_ms=late_arrival_ms,
            runtime_ms=runtime_ms // 4,
            mem=host_mem / 2.0,
            cpus=host_cpus / 2.0,
        )
        for i in range(late_jobs)
    ]
    out_hosts = [
        TraceHost(node_id=f"h{i:03d}", hostname=f"h{i:03d}",
                  mem=host_mem, cpus=host_cpus)
        for i in range(hosts)
    ]
    return jobs, out_hosts


def gang_topology_trace(
    *,
    n_blocks: int = 2,
    block_hosts: int = 4,
    gang_sizes: tuple = (4, 4, 2),
    host_mem: float = 1000.0,
    host_cpus: float = 4.0,
    cycle_ms: int = 30_000,
    gang_runtime_cycles: int = 2,
    seed: int = 0,
):
    """Gang scheduling's acceptance scenario (ROADMAP item 3): a blocky
    fleet fully occupied by staggered scalar churn, with mixed-size
    k-host gangs (`gang_sizes`) queued behind it — capacity frees ONE
    host per cycle, in an order scrambled across topology blocks.

    Naive flat placement trickles gang members onto hosts as they free:
    members start cycles apart, land scattered across blocks, and (with
    member runtime shorter than the trickle) the gang's runs never all
    overlap — assembled never, wasted distributed-job work.  With gang
    scheduling on (`MatchConfig.gang_enabled` +
    `topology_block_hosts=block_hosts`) each gang skips
    `gang-incomplete` until one block holds k free hosts, then places
    whole: assembled at first launch, block_spread == 1.  Asserted A/B
    (tests/test_gang_sim.py + bench.py's `gang` phase): higher
    assembled share, lower `SimResult.gang_stats` wait p50, AND lower
    mean block spread than the same trace with gangs disabled.

    Each job's demand equals one host's capacity (1 job per host).
    Churn job i runs for perm(i)+1 cycles, so frees land one per cycle
    in seeded-shuffled host order.  Returns (jobs, hosts) TraceJob/
    TraceHost lists for sim.simulator.Simulator."""
    import numpy as np

    from cook_tpu.sim.simulator import TraceHost, TraceJob

    rng = np.random.default_rng(seed)
    n_hosts = n_blocks * block_hosts
    perm = rng.permutation(n_hosts)
    jobs = [
        TraceJob(
            uuid=f"churn-{i:03d}",
            user="churn",
            submit_time_ms=0,
            runtime_ms=int(perm[i] + 1) * cycle_ms,
            mem=host_mem,
            cpus=host_cpus,
            priority=90,        # churn places first: gangs queue behind
        )
        for i in range(n_hosts)
    ] + [
        TraceJob(
            uuid=f"gang{g}-m{m}",
            user=f"ganguser{g}",
            submit_time_ms=0,
            runtime_ms=gang_runtime_cycles * cycle_ms,
            mem=host_mem,
            cpus=host_cpus,
            priority=50,
            gang=f"gang-{g}",
        )
        for g, k in enumerate(gang_sizes)
        for m in range(k)
    ]
    hosts = [
        TraceHost(node_id=f"b{b}h{i}", hostname=f"b{b}h{i}",
                  mem=host_mem, cpus=host_cpus)
        for b in range(n_blocks)
        for i in range(block_hosts)
    ]
    return jobs, hosts


@dataclass(frozen=True)
class TrafficOp:
    """One control-plane request in a rest_traffic_trace schedule."""

    offset_s: float
    kind: str                     # "submit" | "query" | "kill"
    user: str
    spec: Optional[dict] = None   # submit payload
    ref: int = -1                 # trace index of the submit this
    #                               query/kill targets


def rest_traffic_trace(
    *,
    duration_s: float = 10.0,
    rps: float = 50.0,
    mix: tuple = (0.7, 0.2, 0.1),   # submit : query : kill
    n_users: int = 8,
    burst_every_s: float = 2.0,
    burst_len_s: float = 0.4,
    burstiness: float = 4.0,
    seed: int = 0,
    pool: Optional[str] = None,
) -> list[TrafficOp]:
    """Seeded bursty submit/query/kill schedule — the ONE load shape
    shared by `tools/loadtest.py` (replayed over HTTP against a live
    control plane) and the simulator (`traffic_trace_jobs` converts the
    submit ops to TraceJobs), so bench rounds and offline replays drive
    the same reproducible traffic.

    Arrivals are a non-homogeneous Poisson process: every
    `burst_every_s` a `burst_len_s` window runs at `burstiness` x the
    base rate (the base is scaled down so the long-run average stays at
    `rps`) — the thundering-herd pattern that exposes lock and fsync
    contention, which a smooth arrival stream hides.  Query/kill ops
    target a uniformly-drawn earlier submit (before any submit exists
    they degrade to submits), so the trace is self-contained."""
    rng = np.random.default_rng(seed)
    frac = min(burst_len_s / max(burst_every_s, 1e-9), 1.0)
    # solve mean rate == rps: frac*burst_rate + (1-frac)*base == rps
    base = max(rps * (1.0 - burstiness * frac) / max(1.0 - frac, 1e-9),
               rps * 0.05)
    burst_rate = rps * burstiness
    kinds = ("submit", "query", "kill")
    p = np.asarray(mix, dtype=float)
    p = p / p.sum()
    ops: list[TrafficOp] = []
    submit_indices: list[int] = []
    t = 0.0
    i = 0
    while True:
        rate = burst_rate if (t % burst_every_s) < burst_len_s else base
        t += float(rng.exponential(1.0 / max(rate, 1e-9)))
        if t >= duration_s:
            break
        kind = kinds[int(rng.choice(3, p=p))]
        user = f"user{int(rng.integers(n_users))}"
        if kind != "submit" and not submit_indices:
            kind = "submit"
        if kind == "submit":
            spec = {
                "command": "true",
                "name": f"loadtest-{i}",
                "mem": float(rng.choice((128, 256, 512, 1024))),
                "cpus": float(rng.choice((0.5, 1, 2))),
                "max_retries": 1,
                **({"pool": pool} if pool else {}),
            }
            ops.append(TrafficOp(offset_s=t, kind=kind, user=user,
                                 spec=spec))
            submit_indices.append(i)
        else:
            ref = int(submit_indices[int(rng.integers(
                len(submit_indices)))])
            ops.append(TrafficOp(offset_s=t, kind=kind, user=user,
                                 ref=ref))
        i += 1
    return ops


def traffic_trace_jobs(ops: list[TrafficOp], *, runtime_ms: int = 1000,
                       mem=None, cpus=None):
    """The simulator view of a rest_traffic_trace: submit ops become
    TraceJobs at their arrival offsets (kills/queries are REST-side
    concerns the trace simulator's completion model doesn't replay), so
    the same seeded load shape drives both the live harness and
    offline sim runs."""
    from cook_tpu.sim.simulator import TraceJob

    jobs = []
    for i, op in enumerate(ops):
        if op.kind != "submit":
            continue
        jobs.append(TraceJob(
            uuid=f"traffic-{i:06d}",
            user=op.user,
            submit_time_ms=int(op.offset_s * 1000),
            runtime_ms=runtime_ms,
            mem=float(mem if mem is not None else op.spec["mem"]),
            cpus=float(cpus if cpus is not None else op.spec["cpus"]),
            pool=op.spec.get("pool", "default"),
        ))
    return jobs


def run_load(url: str, config: LoadConfig, *,
             wait_timeout_s: float = 120.0,
             log=lambda *a: None) -> LoadReport:
    """Replay the workload against a live deployment and wait for every
    job to finish."""
    workload = generate_workload(config)
    clients = [JobClient(url, user=f"user{u}")
               for u in range(config.n_users)]
    report = LoadReport()
    submitted: dict[str, float] = {}  # uuid -> submit wall time
    start = time.time()

    i = 0
    while i < len(workload):
        now = time.time() - start
        due = []
        while i < len(workload) and workload[i][0] <= now \
                and len(due) < config.batch_max:
            due.append(workload[i][1])
            i += 1
        if not due:
            time.sleep(min(workload[i][0] - now, 0.05))
            continue
        client = clients[i % len(clients)]
        t0 = time.time()
        uuids = client.submit(due)
        report.submit_latency_ms.append((time.time() - t0) * 1000)
        for uuid in uuids:
            submitted[uuid] = time.time()
        report.submitted += len(uuids)
        if report.submitted % 100 == 0:
            log(f"submitted {report.submitted}/{config.n_jobs}")

    # wait for completion, recording time-to-first-instance; every poll
    # sweep covers the ENTIRE pending set (batched requests), or jobs
    # beyond the first window would get inflated schedule latencies and
    # a wedged prefix would starve the rest
    deadline = time.time() + wait_timeout_s
    pending = set(submitted)
    poll_client = clients[0]
    while pending and time.time() < deadline:
        snapshot = list(pending)
        for batch_start in range(0, len(snapshot), 256):
            for job in poll_client.query(
                    snapshot[batch_start:batch_start + 256]):
                uuid = job["uuid"]
                if uuid not in report.schedule_latency_ms \
                        and job["instances"]:
                    report.schedule_latency_ms[uuid] = (
                        (time.time() - submitted[uuid]) * 1000)
                if job["status"] == "completed":
                    pending.discard(uuid)
                    if any(i.get("status") == "success"
                           for i in job["instances"]):
                        report.completed += 1
                    else:
                        report.failed += 1
        if pending:
            time.sleep(0.2)
    report.wall_s = time.time() - start
    return report
