"""Deterministic faster-than-real-time trace simulator."""
from cook_tpu.sim.simulator import (  # noqa: F401
    SimConfig,
    SimResult,
    Simulator,
    TraceHost,
    TraceJob,
    load_trace,
    synth_trace,
)
