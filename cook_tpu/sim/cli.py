"""Simulator CLI: trace replay from the command line.

Reference: the zz_simulator entry points + docs/simulator.md — JSON trace
in, CSV run-trace out, plus `compare` for determinism/equivalence checking
between two run traces (`traces-equivalent?`, zz_simulator.clj:714).

    python -m cook_tpu.sim.cli run --trace trace.json --out run.csv
    python -m cook_tpu.sim.cli synth --jobs 1000 --hosts 100 --out trace.json
    python -m cook_tpu.sim.cli compare run1.csv run2.csv
"""
from __future__ import annotations

import argparse
import csv
import json

from cook_tpu.scheduler.core import SchedulerConfig
from cook_tpu.scheduler.rebalancer import RebalancerParams
from cook_tpu.utils.config import default_match_config
from cook_tpu.sim.simulator import (
    SimConfig,
    Simulator,
    load_trace,
    synth_trace,
)


def cmd_run(args) -> int:
    jobs, hosts = load_trace(args.trace)
    fault_schedule = None
    if args.faults:
        # chaos-drill mode (docs/resilience.md): a FaultSchedule JSON
        # armed for the run, so recovery behavior replays from a file
        with open(args.faults) as f:
            fault_schedule = json.load(f)
    config = SimConfig(
        cycle_ms=args.cycle_ms,
        rebalance_every=args.rebalance_every,
        elastic_every=(args.elastic_every if args.elastic else 0),
        max_cycles=args.max_cycles,
        batched_match=args.batched,
        speculate=args.speculate,
        resident=args.resident,
        fault_schedule=fault_schedule,
        history_every=args.history_every,
        scheduler=SchedulerConfig(
            # chunk/backend default to the hardware-tuned config
            # (tuned_match.json) like the service; flags override
            match=default_match_config(
                max_jobs_considered=args.considerable,
                **{k: v for k, v in
                   (("chunk", args.chunk), ("backend", args.backend))
                   if v is not None}),
            rebalancer=RebalancerParams(
                safe_dru_threshold=args.safe_dru_threshold,
                min_dru_diff=args.min_dru_diff,
                max_preemption=args.max_preemption,
            ),
        ),
    )
    sim = Simulator(jobs, hosts, config)
    result = sim.run()
    with open(args.out, "w") as f:
        f.write(result.to_csv())
    if args.cycles_out:
        # flight-recorder dump: per-cycle decision records for offline
        # analysis (same schema as GET /debug/cycles)
        with open(args.cycles_out, "w") as f:
            f.write(result.cycle_records_json())
    if args.trace_out:
        # chrome-trace dump of the run's span ring (load in Perfetto /
        # chrome://tracing; same schema as GET /debug/trace?format=chrome)
        from cook_tpu.utils import tracing

        with open(args.trace_out, "w") as f:
            json.dump(tracing.chrome_trace(), f)
    if args.history_out and result.metrics_history:
        # the run's retained metrics history (virtual-clock timestamps,
        # same shape as GET /debug/history) for offline trend analysis
        with open(args.history_out, "w") as f:
            json.dump(result.metrics_history, f, indent=1)
    if args.incidents_out:
        # incident bundles the run captured (same schema as
        # GET /debug/incidents/{id}), one JSON file per bundle
        import os

        os.makedirs(args.incidents_out, exist_ok=True)
        for bundle in result.incidents:
            with open(os.path.join(args.incidents_out,
                                   f"{bundle['id']}.json"), "w") as f:
                json.dump(bundle, f, indent=1, default=str)
    completed = sum(1 for r in result.rows if r["status"] == "success")
    p50 = (sorted(result.cycle_wall_s)[len(result.cycle_wall_s) // 2] * 1000
           if result.cycle_wall_s else 0.0)
    print(json.dumps({
        "cycles": result.cycles,
        "virtual_ms": result.virtual_ms,
        "jobs": len(jobs),
        "completed": completed,
        "utilization": round(result.utilization(hosts), 4),
        "cycle_wall_p50_ms": round(p50, 2),
        "phase_wall_s": {k: round(v, 3)
                         for k, v in result.phase_wall_s.items()},
        # device-telemetry verdict: a run that storms the compiler or
        # drifts from the CPU reference says so in its summary line
        "health": result.health.get("status", "unknown"),
        "health_reasons": result.health.get("reasons", []),
        # incident bundles captured mid-run (ok->degraded transitions)
        "incidents": len(result.incidents),
        # capacity-plane summary: committed plans + queued-wait p50, the
        # number the elastic A/B moves
        "elastic_plans": sum(1 for p in result.elastic_plans if p["moves"]),
        "queued_wait_p50_ms": (
            sorted(waits)[len(waits) // 2]
            if (waits := result.queued_wait_ms()) else None),
        # speculation A/B numbers (with --speculate; zeros otherwise):
        # fraction of cycles served from a committed speculative solve +
        # the cycle-start-to-first-launch p50 it exists to lower
        "speculation": result.speculation_stats(),
        # device data-plane summary (obs/data_plane.py): bytes the run
        # moved host<->device, and how much of the encode traffic was
        # re-transferred unchanged (mean rebuild_fraction ~0 on steady
        # pools = the waste ROADMAP item 2(a) removes)
        "data_plane": result.data_plane,
    }))
    if args.health_out:
        with open(args.health_out, "w") as f:
            json.dump(result.health, f, indent=1)
    return 0


def cmd_synth(args) -> int:
    if args.completion_heavy:
        # the speculative-cycle wave-drain scenario (sim/loadgen.py
        # completion_heavy_trace); pair with `run --speculate`
        from cook_tpu.sim.loadgen import completion_heavy_trace

        jobs, hosts = completion_heavy_trace(jobs=args.jobs,
                                             seed=args.seed)
    elif args.imbalanced:
        # the elastic capacity plane's two-pool starving/idle scenario
        # (sim/loadgen.py imbalanced_pool_trace); pair with `run --elastic`
        from cook_tpu.sim.loadgen import imbalanced_pool_trace

        jobs, hosts = imbalanced_pool_trace(
            busy_jobs=args.jobs, seed=args.seed)
    else:
        jobs, hosts = synth_trace(
            args.jobs, args.hosts, n_users=args.users, seed=args.seed,
            mean_runtime_ms=args.mean_runtime_ms,
            submit_span_ms=args.submit_span_ms,
        )
    with open(args.out, "w") as f:
        json.dump({
            "jobs": [vars(j) for j in jobs],
            "hosts": [
                {k: (dict(v) if k == "attributes" else v)
                 for k, v in vars(h).items()}
                for h in hosts
            ],
        }, f)
    print(f"wrote {len(jobs)} jobs / {len(hosts)} hosts to {args.out}")
    return 0


def load_rows(path: str) -> list[dict]:
    with open(path) as f:
        return list(csv.DictReader(f))


def traces_equivalent(rows1: list[dict], rows2: list[dict],
                      *, keys=("job_uuid", "start_ms", "host", "status")
                      ) -> tuple[bool, list[str]]:
    """Order-insensitive equality on the decision-relevant columns."""
    def norm(rows):
        return sorted(tuple(r.get(k, "") for k in keys) for r in rows)

    n1, n2 = norm(rows1), norm(rows2)
    if n1 == n2:
        return True, []
    diffs = []
    s1, s2 = set(n1), set(n2)
    for row in list(s1 - s2)[:10]:
        diffs.append(f"only in first:  {row}")
    for row in list(s2 - s1)[:10]:
        diffs.append(f"only in second: {row}")
    return False, diffs


def cmd_compare(args) -> int:
    ok, diffs = traces_equivalent(load_rows(args.trace1),
                                  load_rows(args.trace2))
    if ok:
        print("traces equivalent")
        return 0
    print("traces DIFFER:")
    for d in diffs:
        print(" ", d)
    return 1


def cmd_loadgen(args) -> int:
    import json as json_mod
    import sys

    from cook_tpu.sim.loadgen import LoadConfig, run_load

    config = LoadConfig(
        n_jobs=args.jobs, rate_per_minute=args.rate, n_users=args.users,
        seed=args.seed, speedup=args.speedup, pool=args.pool,
    )
    report = run_load(args.url, config, wait_timeout_s=args.wait_timeout_s,
                      log=lambda *a: print(*a, file=sys.stderr))
    summary = report.summary()
    print(json_mod.dumps(summary))
    if args.out:
        with open(args.out, "w") as f:
            json_mod.dump(summary, f)
    return 0 if summary["failed"] == 0 and \
        summary["completed"] == summary["submitted"] else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cook-tpu-sim")
    sub = p.add_subparsers(dest="cmd", required=True)

    r = sub.add_parser("run", help="replay a trace")
    r.add_argument("--trace", required=True)
    r.add_argument("--out", default="run.csv")
    r.add_argument("--health-out", default="",
                   help="write the end-of-run /debug/health verdict here")
    r.add_argument("--cycles-out", default="",
                   help="dump flight-recorder cycle records (JSON) here")
    r.add_argument("--trace-out", default="",
                   help="dump the run's span ring as a chrome-trace JSON "
                        "(Perfetto-loadable) here")
    r.add_argument("--incidents-out", default="",
                   help="write captured incident bundles (one JSON per "
                        "bundle) into this directory")
    r.add_argument("--cycle-ms", type=int, default=30_000)
    r.add_argument("--rebalance-every", type=int, default=0)
    r.add_argument("--max-cycles", type=int, default=10_000)
    r.add_argument("--chunk", type=int, default=None,
                   help="matcher chunk; default = tuned_match.json / 0")
    r.add_argument("--backend", default=None,
                   choices=["xla", "pallas", "bucketed"],
                   help="candidate-pass backend; default = tuned config")
    r.add_argument("--considerable", type=int, default=1000)
    r.add_argument("--batched", action="store_true",
                   help="one device call for all pools")
    r.add_argument("--safe-dru-threshold", type=float, default=1.0)
    r.add_argument("--min-dru-diff", type=float, default=0.5)
    r.add_argument("--max-preemption", type=int, default=100)
    r.add_argument("--elastic", action="store_true",
                   help="enable the elastic capacity plane (pool "
                        "loaning + reclaim, cook_tpu/elastic/)")
    r.add_argument("--speculate", action="store_true",
                   help="prediction-assisted speculative match cycles "
                        "(scheduler/prediction.py): overlap cycle N+1's "
                        "solve with cycle N's drain")
    r.add_argument("--resident", action="store_true",
                   help="device-resident match state "
                        "(scheduler/device_state.py): encode tensors "
                        "stay on device across cycles, O(delta) updates")
    r.add_argument("--faults", default="",
                   help="FaultSchedule JSON file armed for the run "
                        "(cook_tpu.faults; see docs/resilience.md)")
    r.add_argument("--history-every", type=int, default=0,
                   help="cycles between metrics-history sample ticks on "
                        "the virtual clock (0 = off); pair with "
                        "--history-out")
    r.add_argument("--history-out", default="",
                   help="write the run's multi-resolution metrics "
                        "history dump (GET /debug/history schema) here")
    r.add_argument("--elastic-every", type=int, default=1,
                   help="cycles between capacity plans (with --elastic)")
    r.set_defaults(fn=cmd_run)

    s = sub.add_parser("synth", help="generate a synthetic trace")
    s.add_argument("--jobs", type=int, default=1000)
    s.add_argument("--hosts", type=int, default=100)
    s.add_argument("--users", type=int, default=10)
    s.add_argument("--seed", type=int, default=0)
    s.add_argument("--mean-runtime-ms", type=int, default=120_000)
    s.add_argument("--submit-span-ms", type=int, default=300_000)
    s.add_argument("--imbalanced", action="store_true",
                   help="two-pool starving/idle elastic scenario instead "
                        "of the skewed single-pool workload")
    s.add_argument("--completion-heavy", action="store_true",
                   help="wave-drain speculation scenario (one job per "
                        "host per cycle); pair with `run --speculate`")
    s.add_argument("--out", default="trace.json")
    s.set_defaults(fn=cmd_synth)

    c = sub.add_parser("compare", help="diff two run traces")
    c.add_argument("trace1")
    c.add_argument("trace2")
    c.set_defaults(fn=cmd_compare)

    lg = sub.add_parser(
        "loadgen",
        help="generate + replay a workload against a DEPLOYED service "
             "over HTTP (the deploy-scale simulator, simulator/README.md)")
    lg.add_argument("--url", required=True)
    lg.add_argument("--jobs", type=int, default=200)
    lg.add_argument("--rate", type=float, default=600.0,
                    help="arrival rate, jobs/minute")
    lg.add_argument("--users", type=int, default=8)
    lg.add_argument("--seed", type=int, default=0)
    lg.add_argument("--speedup", type=float, default=1.0)
    lg.add_argument("--pool", default=None)
    lg.add_argument("--wait-timeout-s", type=float, default=300.0)
    lg.add_argument("--out", default="")
    lg.set_defaults(fn=cmd_loadgen)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
