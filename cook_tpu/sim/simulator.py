"""Faster-than-real-time trace simulator: the framework's acceptance rig.

Reference: `zz_simulator.clj` + `mesos_mock.clj` + `docs/simulator.md` —
drive the REAL scheduler against the in-memory mock backend with frozen,
manually-advanced virtual time; trigger channels replace timers; each cycle
is: flush completions -> submit due jobs -> rank -> match -> [rebalance].
Inputs are a job trace + host list; output is a run trace (job, task,
submit/start/end, host, status) suitable for determinism diffs and packing/
latency measurement.  Decisions, not wall-clock, are what replay measures —
but we also record per-phase wall times since the TPU solve latency is this
project's headline metric.
"""
from __future__ import annotations

import csv
import io
import json
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import (
    DruMode,
    Group,
    GroupPlacementType,
    HostPlacement,
    Job,
    Pool,
    Resources,
)
from cook_tpu.models.store import JobStore
from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
from cook_tpu.utils.tracing import span


@dataclass
class TraceJob:
    """One job in the input trace."""

    uuid: str
    user: str
    submit_time_ms: int
    runtime_ms: int
    mem: float
    cpus: float
    gpus: float = 0.0
    priority: int = 50
    pool: str = "default"
    # gang scheduling: non-empty marks this job one member of the named
    # gang — every trace job sharing the tag submits as ONE atomic batch
    # under a UNIQUE-placement group with gang_size = member count, so
    # the matcher's all-or-nothing block rule applies (scheduler/gang.py)
    gang: str = ""

    @classmethod
    def from_dict(cls, d: dict) -> "TraceJob":
        return cls(
            uuid=str(d["uuid"]),
            user=d["user"],
            submit_time_ms=int(d["submit_time_ms"]),
            runtime_ms=int(d["runtime_ms"]),
            mem=float(d["mem"]),
            cpus=float(d["cpus"]),
            gpus=float(d.get("gpus", 0.0)),
            priority=int(d.get("priority", 50)),
            pool=d.get("pool", "default"),
            gang=str(d.get("gang", "")),
        )


@dataclass
class TraceHost:
    node_id: str
    hostname: str
    mem: float
    cpus: float
    gpus: float = 0.0
    pool: str = "default"
    attributes: tuple = ()

    @classmethod
    def from_dict(cls, d: dict) -> "TraceHost":
        return cls(
            node_id=str(d["node_id"]),
            hostname=d.get("hostname", str(d["node_id"])),
            mem=float(d["mem"]),
            cpus=float(d["cpus"]),
            gpus=float(d.get("gpus", 0.0)),
            pool=d.get("pool", "default"),
            attributes=tuple(sorted(d.get("attributes", {}).items())),
        )


@dataclass
class SimConfig:
    cycle_ms: int = 30_000           # virtual time per cycle
    rebalance_every: int = 0         # cycles between rebalances (0 = off)
    # cycles between elastic capacity plans (0 = off); setting this
    # enables the scheduler's capacity plane (cook_tpu/elastic/)
    elastic_every: int = 0
    max_cycles: int = 10_000
    scheduler: SchedulerConfig = field(default_factory=SchedulerConfig)
    pools: tuple = (("default", "default"),)  # (name, dru_mode)
    batched_match: bool = False      # one device call for all pools
    # prediction-assisted speculative cycles (scheduler/prediction.py):
    # enables the scheduler's speculator with the horizon pinned to ONE
    # sim cycle — a running task predicted to finish by the next cycle's
    # clock is assumed complete by the speculative solve
    speculate: bool = False
    # device-resident match state (scheduler/device_state.py): keep the
    # encode tensors on device across cycles with O(delta) updates —
    # flips the scheduler's MatchConfig.device_residency knob
    resident: bool = False
    # fault-injection schedule (cook_tpu/faults.FaultSchedule.from_dict
    # shape: {"seed": .., "rules": [{"point": .., "mode": .., ...}]}),
    # armed for the duration of run() — the chaos scenarios
    # (tools/chaos.py) script launch failures, device solve errors, etc.
    # against the REAL scheduler through this knob
    fault_schedule: Optional[dict] = None
    # cycles between in-run health evaluations (0 = end-of-run only).
    # Each evaluation reports to the scheduler's incident observatory,
    # so a mid-run degradation (e.g. an armed device fault) captures an
    # incident bundle the run dumps (SimResult.incidents); the interval
    # must stay below device_fallback_cycles or a short degraded window
    # can recover unobserved
    health_every: int = 4
    # cycles between metrics-history sample ticks (0 = off).  A long run
    # retains the same multi-resolution series a live node's sampler
    # would (obs/tsdb.MetricsHistory on the VIRTUAL clock), dumped in
    # SimResult.metrics_history / `sim run --history-out` — so "what did
    # the queue gauge look like before the fault fired" is answerable
    # offline, same as GET /debug/history on a service node
    history_every: int = 0


@dataclass
class SimResult:
    rows: list[dict]                 # run trace
    cycles: int
    virtual_ms: int
    phase_wall_s: dict[str, float]
    cycle_wall_s: list[float]        # per-cycle total scheduling wall time
    # flight-recorder dump: one structured record per match cycle (per-
    # phase durations, per-job reason codes, preemptions) for offline
    # analysis — same schema as GET /debug/cycles (docs/observability.md)
    cycle_records: list[dict] = field(default_factory=list)
    # device-telemetry health verdict at end of run (GET /debug/health
    # schema): did the simulated workload drive the solver into
    # recompile storms / quality drift / latency regression?
    health: dict = field(default_factory=dict)
    # elastic capacity-plane dump: planner decisions (GET /debug/elastic
    # schema) + the final loan ledger
    elastic_plans: list[dict] = field(default_factory=list)
    capacity_ledger: list[dict] = field(default_factory=list)
    # incident bundles captured during the run (GET /debug/incidents
    # schema, full evidence) — written by sim.cli --incidents-out
    incidents: list[dict] = field(default_factory=list)
    # device data-plane summary (obs/data_plane.py): H2D/D2H byte deltas
    # this run moved (process-ledger delta, so concurrent sims in one
    # process overlap — the simulator is single-flight in practice) plus
    # the mean rebuild_fraction/padding_waste off the cycle records
    data_plane: dict = field(default_factory=dict)
    # multi-resolution metrics history sampled on the virtual clock
    # (with history_every > 0): {"raw": query-dump, "10m": query-dump} —
    # the same shape GET /debug/history serves (docs/observability.md)
    metrics_history: dict = field(default_factory=dict)
    # fairness observatory snapshot at end of run (GET /debug/fairness
    # schema): per-user DRU trajectories, preemption ledger + rollups,
    # Jain index — so a trace replay reports the same fairness numbers
    # production does
    fairness: dict = field(default_factory=dict)

    def queued_wait_ms(self) -> list[int]:
        """Per-started-task queued wait (start - submit): the metric the
        elastic A/B compares (lower p50 with loaning enabled)."""
        return [r["start_ms"] - r["submit_ms"] for r in self.rows
                if r["start_ms"] is not None]

    def speculation_stats(self) -> dict:
        """Speculation A/B summary off the cycle records: fraction of
        job-considering cycles served from a committed speculative solve
        plus the cycle-start-to-first-launch p50 (the latency speculation
        exists to lower; scheduler/prediction.py PRE_LAUNCH_PHASES)."""
        from cook_tpu.scheduler.prediction import pre_launch_ms

        active = [r for r in self.cycle_records if r.get("considered")]
        hits = sum(1 for r in active if r.get("speculation") == "hit")
        latencies = sorted(pre_launch_ms(r) for r in active)
        return {
            "cycles": len(active),
            "hits": hits,
            "hit_fraction": hits / len(active) if active else 0.0,
            "pre_launch_p50_ms": (latencies[len(latencies) // 2]
                                  if latencies else 0.0),
        }

    def cycle_records_json(self) -> str:
        return json.dumps({"cycles": self.cycle_records}, indent=1)

    def gang_stats(self, jobs: Sequence["TraceJob"],
                   hosts: Sequence["TraceHost"] = (),
                   *, nodes_per_block: int = 0) -> dict:
        """Gang A/B summary off the run trace (the numbers the gang
        scheduling acceptance compares against naive flat placement):

        - a gang is *assembled* when all k members were RUNNING at the
          same virtual instant (the point of gang scheduling — trickled
          members whose runs never overlap did distributed-job work
          for nothing);
        - ``wait_ms`` is assembly time minus submit; unassembled gangs
          score the full simulated span (they waited out the run);
        - ``block_spread`` is how many topology blocks the gang's
          members landed on (1 = contiguous, the fragmentation the
          block rule exists to prevent).  Blocks are `nodes_per_block`
          chunks of the sorted hostname list — the matcher's
          decomposition."""
        by_gang: dict[str, list] = {}
        for tj in jobs:
            if getattr(tj, "gang", ""):
                by_gang.setdefault(tj.gang, []).append(tj)
        by_gang = {g: ms for g, ms in by_gang.items() if len(ms) >= 2}
        if not by_gang:
            return {"gangs": 0, "assembled": 0, "assembled_share": 0.0,
                    "wait_ms_p50": 0.0, "mean_block_spread": 0.0,
                    "per_gang": []}
        names = sorted(h.hostname for h in hosts)
        npb = nodes_per_block if nodes_per_block > 0 else max(len(names), 1)
        block_of = {h: i // npb for i, h in enumerate(names)}
        runs: dict[str, list[dict]] = {}
        for r in self.rows:
            if r["start_ms"] is not None:
                runs.setdefault(r["job_uuid"], []).append(r)
        per_gang = []
        for g, members in sorted(by_gang.items()):
            submit = min(m.submit_time_ms for m in members)
            last = [max(runs[m.uuid], key=lambda r: r["start_ms"])
                    for m in members if m.uuid in runs]
            spread = len({block_of.get(r["host"], -1) for r in last}) \
                if last else 0
            assembled_at = None
            if len(last) == len(members):
                start = max(r["start_ms"] for r in last)
                end = min(r["end_ms"] if r["end_ms"] is not None
                          else self.virtual_ms for r in last)
                if start < end:
                    assembled_at = start
            per_gang.append({
                "gang": g,
                "size": len(members),
                "placed_members": len(last),
                "block_spread": spread,
                "assembled": assembled_at is not None,
                "wait_ms": (assembled_at - submit)
                if assembled_at is not None else None,
            })
        waits = sorted(
            d["wait_ms"] if d["wait_ms"] is not None else self.virtual_ms
            for d in per_gang
        )
        spreads = [d["block_spread"] for d in per_gang
                   if d["placed_members"]]
        assembled = sum(1 for d in per_gang if d["assembled"])
        return {
            "gangs": len(per_gang),
            "assembled": assembled,
            "assembled_share": assembled / len(per_gang),
            "wait_ms_p50": float(waits[len(waits) // 2]),
            "mean_block_spread": (sum(spreads) / len(spreads)
                                  if spreads else 0.0),
            "per_gang": per_gang,
        }

    def utilization(self, hosts: Sequence[TraceHost]) -> float:
        """Fraction of total cpu-ms capacity actually used by completed
        work over the simulated span."""
        cap = sum(h.cpus for h in hosts) * max(self.virtual_ms, 1)
        used = sum(
            r["cpus"] * max(0, (r["end_ms"] or 0) - (r["start_ms"] or 0))
            for r in self.rows
            if r["start_ms"] is not None
        )
        return used / cap if cap else 0.0

    def to_csv(self) -> str:
        buf = io.StringIO()
        writer = csv.DictWriter(
            buf,
            fieldnames=[
                "job_uuid", "task_id", "user", "mem", "cpus", "gpus",
                "submit_ms", "start_ms", "end_ms", "host", "status",
            ],
        )
        writer.writeheader()
        for r in self.rows:
            writer.writerow({k: r[k] for k in writer.fieldnames})
        return buf.getvalue()


class Simulator:
    def __init__(self, jobs: Sequence[TraceJob], hosts: Sequence[TraceHost],
                 config: Optional[SimConfig] = None):
        # gang members must land in ONE store submit batch (the store's
        # txn-level gang validation): align every member to the gang's
        # latest submit time so the due-jobs sweep picks them up together
        self._gang_size: dict[str, int] = {}
        gang_due: dict[str, int] = {}
        for j in jobs:
            if j.gang:
                self._gang_size[j.gang] = self._gang_size.get(j.gang, 0) + 1
                gang_due[j.gang] = max(gang_due.get(j.gang, 0),
                                       j.submit_time_ms)
        if self._gang_size:
            import dataclasses as _dc

            jobs = [
                _dc.replace(j, submit_time_ms=gang_due[j.gang])
                if j.gang and self._gang_size[j.gang] >= 2 else j
                for j in jobs
            ]
        self.trace_jobs = sorted(jobs, key=lambda j: (j.submit_time_ms, j.uuid))
        self.trace_hosts = list(hosts)
        self.config = config or SimConfig()
        self.now_ms = 0

        # pools: configured list extended by any pool the trace mentions
        pool_names = {name for name, _ in self.config.pools}
        extra = sorted(
            ({j.pool for j in jobs} | {h.pool for h in hosts}) - pool_names
        )
        self.config.pools = tuple(self.config.pools) + tuple(
            (name, "default") for name in extra
        )
        if self.config.elastic_every > 0 \
                and not self.config.scheduler.elastic.enabled:
            import dataclasses as _dc

            self.config.scheduler.elastic = _dc.replace(
                self.config.scheduler.elastic, enabled=True)
        if self.config.resident:
            import dataclasses as _dc

            self.config.scheduler.match = _dc.replace(
                self.config.scheduler.match, device_residency=True)
        if self.config.speculate:
            self.config.scheduler.speculation = True
            # completions flush exactly one cycle_ms ahead: predict to
            # that horizon (a wider one would assume completions the
            # next cycle won't see yet — guaranteed prediction-miss)
            self.config.scheduler.speculation_horizon_ms = \
                float(self.config.cycle_ms)
        self.store = JobStore(clock=lambda: self.now_ms)
        for name, mode in self.config.pools:
            self.store.set_pool(Pool(name=name, dru_mode=DruMode(mode)))
        self.cluster = MockCluster(
            "sim",
            [
                MockHost(
                    node_id=h.node_id,
                    hostname=h.hostname,
                    mem=h.mem,
                    cpus=h.cpus,
                    gpus=h.gpus,
                    attributes=h.attributes,
                    pool=h.pool,
                )
                for h in hosts
            ],
            clock=lambda: self.now_ms,
        )
        self.scheduler = Scheduler(
            self.store, [self.cluster], self.config.scheduler
        )
        # the capture cooldown is a REAL-time flood guard; a sim run
        # compresses hours of virtual time into seconds of wall clock,
        # so the service default would silently drop every incident
        # after the first — a replayed drill must capture each
        # ok->degraded transition (--incidents-out's contract)
        self.scheduler.incidents.cooldown_s = 0.0
        if self.scheduler.recorder is not None:
            # the service default ring (512) would silently truncate the
            # offline dump: size it to hold every cycle of every pool this
            # run can produce (bounded — records only materialize for
            # cycles that actually run)
            from cook_tpu.scheduler.flight_recorder import FlightRecorder

            wanted = min(self.config.max_cycles
                         * max(1, len(self.config.pools)), 1_000_000)
            if wanted > self.scheduler.recorder.capacity:
                self.scheduler.recorder = FlightRecorder(capacity=wanted)
        self._runtime: dict[str, int] = {
            j.uuid: j.runtime_ms for j in self.trace_jobs
        }

    def run(self) -> SimResult:
        from cook_tpu import faults

        cfg = self.config
        prev = faults.ACTIVE  # restore, don't disarm: a test may run the
        if cfg.fault_schedule:  # simulator INSIDE faults.injected(...)
            faults.arm(faults.FaultSchedule.from_dict(cfg.fault_schedule))
        try:
            return self._run()
        finally:
            if cfg.fault_schedule:
                if prev is not None:
                    faults.arm(prev)
                else:
                    faults.disarm()

    def _run(self) -> SimResult:
        from cook_tpu.obs import data_plane as _dp

        led_h2d0, led_d2h0 = _dp.LEDGER.byte_totals()
        cfg = self.config
        history = None
        if cfg.history_every:
            # metrics history on the VIRTUAL clock: points stamp in
            # simulated seconds, so the dump lines up with the trace
            # timeline instead of the host wall clock
            from cook_tpu.obs.tsdb import HistoryConfig, MetricsHistory

            history = MetricsHistory(
                config=HistoryConfig(sample_s=0),
                clock=lambda: self.now_ms / 1000.0)
        submitted = 0
        phase_wall: dict[str, float] = {"rank": 0.0, "match": 0.0,
                                        "rebalance": 0.0, "elastic": 0.0}
        cycle_wall: list[float] = []
        pools = [self.store.pools[name] for name, _ in cfg.pools]
        cycle = 0
        while cycle < cfg.max_cycles:
            cycle += 1
            # 1. flush completions at current virtual time
            self.cluster.advance_to(self.now_ms)
            # 2. submit due jobs — one batch per cycle so gang members
            # (aligned to a shared submit time in __init__) arrive in a
            # single atomic store transaction with their UNIQUE group
            due: list[TraceJob] = []
            while (
                submitted < len(self.trace_jobs)
                and self.trace_jobs[submitted].submit_time_ms <= self.now_ms
            ):
                due.append(self.trace_jobs[submitted])
                submitted += 1
            if due:
                groups: dict[str, Group] = {}
                batch = []
                for tj in due:
                    k = self._gang_size.get(tj.gang, 0) if tj.gang else 0
                    if k >= 2 and tj.gang not in self.store.groups \
                            and tj.gang not in groups:
                        groups[tj.gang] = Group(
                            uuid=tj.gang,
                            name=f"gang-{tj.gang}",
                            host_placement=HostPlacement(
                                type=GroupPlacementType.UNIQUE),
                        )
                    batch.append(Job(
                        uuid=tj.uuid,
                        user=tj.user,
                        pool=tj.pool,
                        priority=tj.priority,
                        resources=Resources(mem=tj.mem, cpus=tj.cpus,
                                            gpus=tj.gpus),
                        expected_runtime_ms=tj.runtime_ms,
                        command="sim",
                        max_retries=5,
                        group_uuid=tj.gang if k >= 2 else None,
                        gang_size=k if k >= 2 else 0,
                    ))
                self.store.submit_jobs(batch, list(groups.values()))
            # 3. rank -> match (-> rebalance) per pool; spans make the
            # run exportable as a chrome trace (sim run --trace-out)
            t_cycle = time.perf_counter()
            if cfg.batched_match and len(pools) > 1:
                t0 = time.perf_counter()
                for pool in pools:
                    with span("sim.rank", pool=pool.name):
                        self.scheduler.rank_cycle(pool)
                t1 = time.perf_counter()
                with span("sim.match_batched", pools=len(pools)):
                    self.scheduler.match_cycle_all_pools()
                t2 = time.perf_counter()
                phase_wall["rank"] += t1 - t0
                phase_wall["match"] += t2 - t1
                if cfg.rebalance_every and cycle % cfg.rebalance_every == 0:
                    for pool in pools:
                        with span("sim.rebalance", pool=pool.name):
                            self.scheduler.rebalance_cycle(pool)
                    phase_wall["rebalance"] += time.perf_counter() - t2
            else:
                for pool in pools:
                    t0 = time.perf_counter()
                    with span("sim.rank", pool=pool.name):
                        self.scheduler.rank_cycle(pool)
                    t1 = time.perf_counter()
                    with span("sim.match", pool=pool.name):
                        self.scheduler.match_cycle(pool)
                    t2 = time.perf_counter()
                    phase_wall["rank"] += t1 - t0
                    phase_wall["match"] += t2 - t1
                    if cfg.rebalance_every and cycle % cfg.rebalance_every == 0:
                        with span("sim.rebalance", pool=pool.name):
                            self.scheduler.rebalance_cycle(pool)
                        phase_wall["rebalance"] += time.perf_counter() - t2
            # 3b. elastic capacity plan (after matching, so demand is the
            # genuinely-unmatched queue; loans land in the NEXT cycle's
            # offers — node-provisioning latency, one cycle coarse)
            if (cfg.elastic_every and cycle % cfg.elastic_every == 0
                    and self.scheduler.elastic is not None):
                t3 = time.perf_counter()
                with span("sim.elastic"):
                    self.scheduler.elastic_cycle()
                phase_wall["elastic"] += time.perf_counter() - t3
            cycle_wall.append(time.perf_counter() - t_cycle)
            # 3c. in-run health watch: an ok->degraded transition mid-run
            # captures an incident bundle through the scheduler's
            # observatory (the same path the service's health-watch loop
            # drives) — without this a fault-drill run would recover
            # before the end-of-run verdict ever looked
            if (cfg.health_every and cycle % cfg.health_every == 0
                    and self.scheduler.telemetry is not None):
                self.scheduler.telemetry.health()
            # 3d. metrics-history tick on the virtual clock (the long-run
            # analog of the service's history sampler)
            if history is not None and cycle % cfg.history_every == 0:
                history.sample_once()
            # 4. advance virtual time
            self.now_ms += cfg.cycle_ms
            # stop when all work is done
            if submitted == len(self.trace_jobs):
                all_done = all(
                    self.store.jobs[j.uuid].state.value == "completed"
                    for j in self.trace_jobs
                )
                if all_done:
                    break
        # final flush so trailing completions land in the trace
        self.cluster.advance_to(self.now_ms)
        recorder = self.scheduler.recorder
        led_h2d1, led_d2h1 = _dp.LEDGER.byte_totals()
        records = (recorder.records_json(limit=recorder.capacity)
                   if recorder is not None else [])
        rebuilds = [r["rebuild_fraction"] for r in records
                    if r.get("rebuild_fraction") is not None]
        wastes = [r["padding_waste"] for r in records
                  if r.get("padding_waste") is not None]
        # device-residency attribution off the same records: how many
        # match cycles rode O(delta) updates vs full rebuilds, and the
        # rows scattered — the after picture next to rebuild_fraction
        ds_records = [r["device_state"] for r in records
                      if r.get("device_state")]
        data_plane_summary = {
            "h2d_bytes": led_h2d1 - led_h2d0,
            "d2h_bytes": led_d2h1 - led_d2h0,
            "mean_rebuild_fraction": (sum(rebuilds) / len(rebuilds)
                                      if rebuilds else None),
            "mean_padding_waste": (sum(wastes) / len(wastes)
                                   if wastes else None),
            "device_state": {
                "cycles": len(ds_records),
                "rebuilds": sum(1 for d in ds_records if d.get("rebuild")),
                "delta_cycles": sum(1 for d in ds_records
                                    if not d.get("rebuild")),
                "delta_rows": sum(d.get("delta_rows", 0)
                                  for d in ds_records
                                  if not d.get("rebuild")),
                "resident_bytes": (ds_records[-1].get("resident_bytes", 0)
                                   if ds_records else 0),
            },
        }
        return SimResult(
            rows=self._collect_rows(),
            cycles=cycle,
            virtual_ms=self.now_ms,
            phase_wall_s=phase_wall,
            cycle_wall_s=cycle_wall,
            cycle_records=records,
            health=(self.scheduler.telemetry.health()
                    if self.scheduler.telemetry is not None else {}),
            elastic_plans=(
                self.scheduler.elastic.recorder.records_json(limit=10_000)
                if self.scheduler.elastic is not None else []),
            capacity_ledger=self.store.encoded_capacity_ledger(),
            incidents=self.scheduler.incidents.dump(),
            data_plane=data_plane_summary,
            metrics_history=(
                {"raw": history.query("*"), "10m": history.query(
                    "*", step="10m")} if history is not None else {}),
            fairness=self.scheduler.fairness.snapshot(),
        )

    def _collect_rows(self) -> list[dict]:
        rows = []
        for tj in self.trace_jobs:
            job = self.store.jobs[tj.uuid]
            insts = self.store.job_instances(tj.uuid)
            if not insts:
                rows.append(self._row(tj, None))
            for inst in insts:
                rows.append(self._row(tj, inst))
        return rows

    def _row(self, tj: TraceJob, inst) -> dict:
        return {
            "job_uuid": tj.uuid,
            "task_id": inst.task_id if inst else "",
            "user": tj.user,
            "mem": tj.mem,
            "cpus": tj.cpus,
            "gpus": tj.gpus,
            "submit_ms": tj.submit_time_ms,
            "start_ms": inst.start_time_ms if inst else None,
            "end_ms": inst.end_time_ms if inst else None,
            "host": inst.hostname if inst else "",
            "status": inst.status.value if inst else "unscheduled",
        }


def load_trace(path: str) -> tuple[list[TraceJob], list[TraceHost]]:
    with open(path) as f:
        data = json.load(f)
    return (
        [TraceJob.from_dict(d) for d in data["jobs"]],
        [TraceHost.from_dict(d) for d in data["hosts"]],
    )


def synth_trace(
    n_jobs: int,
    n_hosts: int,
    *,
    n_users: int = 10,
    seed: int = 0,
    mean_runtime_ms: int = 120_000,
    submit_span_ms: int = 300_000,
    host_mem: float = 64_000.0,
    host_cpus: float = 32.0,
    pool: str = "default",
) -> tuple[list[TraceJob], list[TraceHost]]:
    """Deterministic synthetic workload with a skewed user mix (the shape of
    the reference benchmark's 50k-job generator, benchmark.clj:37-77)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    user_weights = rng.zipf(1.5, size=n_users).astype(float)
    user_weights /= user_weights.sum()
    jobs = []
    for i in range(n_jobs):
        user = int(rng.choice(n_users, p=user_weights))
        jobs.append(
            TraceJob(
                uuid=f"job-{i:07d}",
                user=f"user{user}",
                submit_time_ms=int(rng.integers(0, submit_span_ms)),
                runtime_ms=int(rng.exponential(mean_runtime_ms)) + 1000,
                mem=float(rng.choice([512, 1024, 2048, 4096, 8192])),
                cpus=float(rng.choice([0.5, 1, 2, 4])),
                priority=int(rng.choice([25, 50, 75])),
                pool=pool,
            )
        )
    hosts = [
        TraceHost(
            node_id=f"node-{i:05d}",
            hostname=f"host-{i:05d}",
            mem=host_mem,
            cpus=host_cpus,
            pool=pool,
        )
        for i in range(n_hosts)
    ]
    return jobs, hosts
