"""Elastic flight recorder: bounded ring of capacity-plan decisions.

The match-cycle flight recorder (scheduler/flight_recorder.py) answers
"why did this cycle decide that"; this ring answers the same question
for the capacity plane: every planner solve — interval plans and
reclaim-on-demand — lands here with its demand/supply evidence, the
moves it committed, the txn id that made them durable, and the solve's
device identity (padded shape / backend / compiled).  Served at
`GET /debug/elastic`; `CycleRecord.elastic_plan` carries the plan id a
match cycle ran under, so `/debug/cycles` joins against this ring.
"""
from __future__ import annotations

import collections
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class PlanRecord:
    """One capacity-plane decision (interval plan or on-demand reclaim)."""

    plan_id: int
    kind: str                     # "interval" | "reclaim-on-demand"
    t_ms: int                     # store clock at plan time
    wall_time: float
    pools: list[str] = field(default_factory=list)
    demand: dict = field(default_factory=dict)   # pool -> {mem,cpus,gpus}
    supply: dict = field(default_factory=dict)
    moves: list[dict] = field(default_factory=list)
    unmet: dict = field(default_factory=dict)    # post-plan shortage
    solve_shape: str = ""
    backend: str = ""
    compiled: bool = False
    duration_s: float = 0.0
    txn_id: str = ""              # "" = nothing committed (no-op plan)

    def to_json(self) -> dict:
        return {
            "plan": self.plan_id,
            "kind": self.kind,
            "t_ms": self.t_ms,
            "wall_time": self.wall_time,
            "pools": list(self.pools),
            "demand": dict(self.demand),
            "supply": dict(self.supply),
            "moves": list(self.moves),
            "unmet": dict(self.unmet),
            "solve_shape": self.solve_shape,
            "backend": self.backend,
            "compiled": self.compiled,
            "duration_s": self.duration_s,
            "txn_id": self.txn_id,
        }


class ElasticRecorder:
    """Bounded ring of PlanRecords (the /debug/elastic substrate)."""

    def __init__(self, capacity: int = 256):
        self._ring: collections.deque[PlanRecord] = collections.deque(
            maxlen=capacity)
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    @property
    def capacity(self) -> int:
        return self._ring.maxlen

    def next_id(self) -> int:
        with self._lock:
            return next(self._ids)

    def add(self, record: PlanRecord) -> PlanRecord:
        if record.wall_time == 0.0:
            record.wall_time = time.time()
        with self._lock:
            self._ring.append(record)
        return record

    def records_json(self, limit: int = 50,
                     kind: Optional[str] = None) -> list[dict]:
        with self._lock:
            out = [r for r in self._ring if kind is None or r.kind == kind]
            return [r.to_json() for r in out[-limit:]]

    def last_plan_id(self) -> int:
        with self._lock:
            return self._ring[-1].plan_id if self._ring else 0
