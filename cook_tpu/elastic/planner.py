"""CapacityPlanner: the elastic capacity plane's control loop.

Cook's pools statically partition the fleet; this planner un-partitions
it on demand (the Aryl capacity-loaning design, arXiv:2202.07896):

  1. each planning interval it assembles per-pool DEMAND tensors (the
     DRU-ranked pending queues from scheduler/ranking.py, rank-weighted
     so the queue head dominates) and SUPPLY tensors (offered spare
     capacity per pool across every compute cluster);
  2. solves the loan/reclaim assignment as ONE bucket-padded batched
     tensor problem (`ops/elastic.py`; CPU parity in
     `ops/cpu_reference.py`), reporting the solve to the
     CompileObservatory like every other device solve;
  3. commits the resulting pool-capacity deltas through the txn
     pipeline as a durable `pool/capacity-delta` op — the LEDGER is the
     source of truth, durable before any cluster is touched — then
     converges every cluster's elastic capacity to the ledger-derived
     net per pool via the `ComputeCluster.scale` hook;
  4. records every decision in the ElasticRecorder ring
     (`GET /debug/elastic`) and exports the loaned-capacity gauge and
     reclaim-latency histogram at `/metrics`.

Reclaim-on-demand (`reclaim_for`) is the reversibility half: the
rebalancer's victim search calls it BEFORE choosing preemption victims,
so a lender pool whose demand returns gets its loaned capacity back —
non-disruptively — before any task is killed for it.  Failover safety:
a promoted leader calls `reconcile()` and every cluster converges to
the replayed ledger, no matter where the old leader died between
commit and resize (scale() is declarative, hence idempotent).
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

import jax.numpy as jnp

from cook_tpu.elastic.recorder import ElasticRecorder, PlanRecord
from cook_tpu.models.entities import Job, Resources
from cook_tpu.obs import data_plane
from cook_tpu.models.store import JobStore
from cook_tpu.ops.common import bucket_size, fetch_result
from cook_tpu.ops.elastic import (
    ELASTIC_RESOURCE_DIMS,
    ElasticProblem,
    solve_capacity_plan,
    weighted_demand,
)
from cook_tpu.utils.metrics import global_registry

# a move dimension below its threshold is tensor dust, not capacity
MIN_MOVE = {"mem": 1.0, "cpus": 0.01, "gpus": 0.01}


@dataclass
class ElasticParams:
    """Knobs of the capacity plane (SchedulerConfig.elastic)."""

    enabled: bool = False
    # fraction of a lender's surplus kept home (never strip a pool bare)
    headroom: float = 0.1
    # queue position where rank-weighted demand discounts to half
    rank_half_life: int = 64
    # pending jobs counted toward reclaim-on-demand unmet demand
    reclaim_window: int = 100
    # ring capacity of /debug/elastic
    recorder_capacity: int = 256
    # block-shaped headroom: a waiting gang of k is unmet demand unless
    # some single topology block has k member-sized hosts free — scalar
    # spare can look sufficient while every block is fragmented, and a
    # loaned-out pool would never call its capacity home for the gang
    count_block_headroom: bool = True
    # topology block width for that check (0 = choose_nodes_per_block)
    gang_block_hosts: int = 0
    # serve the per-interval demand/capacity tensors from a
    # device-resident keyed-row mirror (device_state.ResidentRows):
    # pools whose queues did not change move zero encode bytes.
    # Config key: [scheduler] resident_elastic
    resident: bool = False


class CapacityPlanner:
    """One leader's capacity plane (owned by the Scheduler)."""

    def __init__(self, store: JobStore, clusters: Sequence, txn,
                 params: Optional[ElasticParams] = None,
                 telemetry=None):
        self.store = store
        # shared reference (the Scheduler's own list): dynamically added
        # compute clusters join the capacity plane automatically
        self.clusters = clusters
        self.txn = txn
        self.params = params or ElasticParams()
        self.telemetry = telemetry
        self.recorder = ElasticRecorder(
            capacity=self.params.recorder_capacity)
        self._loaned_gauge = global_registry.gauge(
            "elastic.loaned",
            "capacity currently on loan per (lender, borrower, resource)")
        self._plan_counter = global_registry.counter(
            "elastic.plans", "capacity-plan solves per kind")
        self._move_counter = global_registry.counter(
            "elastic.moves", "committed capacity moves per kind")
        self._reclaim_hist = global_registry.histogram(
            "elastic.reclaim.seconds",
            "reclaim-on-demand latency: unmet demand detected -> loaned "
            "capacity back in the lender pool's offers")
        self._unmet_gauge = global_registry.gauge(
            "elastic.unmet_shortage",
            "post-plan unmet shortage per pool/resource")
        self._gauge_keys: set[tuple] = set()
        self._resident = None
        if self.params.resident:
            from cook_tpu.scheduler.device_state import ResidentRows

            self._resident = ResidentRows(
                "elastic",
                observatory=getattr(telemetry, "observatory", None),
                family=data_plane.FAM_ELASTIC)

    # ------------------------------------------------------- tensor builds

    def _active_pools(self) -> list[str]:
        return sorted(p.name for p in self.store.pools.values()
                      if p.schedules_jobs)

    def _supply(self, pools: list[str], p_pad: int) -> np.ndarray:
        from cook_tpu.cluster.base import scan_pool_offers

        supply = np.zeros((p_pad, 3), dtype=np.float32)
        for i, pool in enumerate(pools):
            for _cluster, offer in scan_pool_offers(self.clusters, pool):
                supply[i, 0] += max(offer.mem, 0.0)
                supply[i, 1] += max(offer.cpus, 0.0)
                supply[i, 2] += max(offer.gpus, 0.0)
        return supply

    def _demand_inputs(self, pools: list[str], queues: dict,
                       p_pad: int) -> tuple[np.ndarray, np.ndarray, int]:
        longest = 1
        for pool in pools:
            queue = queues.get(pool)
            if queue is not None:
                longest = max(longest, len(queue.jobs))
        j_pad = bucket_size(longest)
        res = np.zeros((p_pad, j_pad, 3), dtype=np.float32)
        valid = np.zeros((p_pad, j_pad), dtype=bool)
        for i, pool in enumerate(pools):
            queue = queues.get(pool)
            if queue is None:
                continue
            for k, job in enumerate(queue.jobs[:j_pad]):
                r = job.resources
                res[i, k] = (r.mem, r.cpus, r.gpus)
                valid[i, k] = True
        return res, valid, j_pad

    def _outstanding(self, pools: list[str], p_pad: int) -> np.ndarray:
        idx = {pool: i for i, pool in enumerate(pools)}
        out = np.zeros((p_pad, p_pad, 3), dtype=np.float32)
        for row in self.store.encoded_capacity_ledger():
            li, bi = idx.get(row["from"]), idx.get(row["to"])
            if li is None or bi is None:
                continue
            out[li, bi] = (row["mem"], row["cpus"], row["gpus"])
        return out

    # ------------------------------------------------------- interval plan

    def plan_cycle(self, queues: dict) -> Optional[PlanRecord]:
        """One planning interval: solve, commit deltas, converge
        clusters, record.  Returns the PlanRecord (None with < 2 active
        pools — there is no one to loan to)."""
        pools = self._active_pools()
        if len(pools) < 2:
            return None
        p_pad = bucket_size(len(pools), minimum=8)
        res, valid, j_pad = self._demand_inputs(pools, queues, p_pad)
        supply = self._supply(pools, p_pad)
        outstanding = self._outstanding(pools, p_pad)
        pool_valid = np.arange(p_pad) < len(pools)

        t0 = time.perf_counter()
        if self._resident is not None:
            # keyed-row mirror: one [j_pad, 3] demand row per pool,
            # keyed by pool NAME — a pool whose pending queue did not
            # change since the last interval ships zero encode bytes.
            # j_pad growth flips the row width -> width-changed rebuild.
            cols, _stats = self._resident.build(
                pools,
                {"res": res[:len(pools)], "valid": valid[:len(pools)]},
                out_len=p_pad,
            )
            res_dev, valid_dev = cols["res"], cols["valid"]
            supply_dev = self._resident.whole_array("supply", supply)
            outstanding_dev = self._resident.whole_array(
                "outstanding", outstanding)
            pool_valid_dev = self._resident.whole_array(
                "pool_valid", pool_valid)
        else:
            with data_plane.family(data_plane.FAM_ELASTIC):
                res_dev = data_plane.h2d(res)
                valid_dev = data_plane.h2d(valid)
                supply_dev = data_plane.h2d(supply)
                outstanding_dev = data_plane.h2d(outstanding)
                pool_valid_dev = data_plane.h2d(pool_valid)
        demand_dev = weighted_demand(
            res_dev, valid_dev, jnp.float32(self.params.rank_half_life))
        plan = solve_capacity_plan(
            ElasticProblem(
                demand=demand_dev,
                supply=supply_dev,
                outstanding=outstanding_dev,
                pool_valid=pool_valid_dev,
            ),
            jnp.float32(self.params.headroom),
        )
        demand, reclaim, loan, unmet = fetch_result(
            (demand_dev, plan.reclaim, plan.loan, plan.shortage))
        seconds = time.perf_counter() - t0

        compiled = False
        if self.telemetry is not None:
            compiled = self.telemetry.record_solve(
                "elastic_plan", (p_pad, j_pad), "xla", seconds)

        moves = (self._extract_moves(pools, reclaim, kind="reclaim")
                 + self._extract_moves(pools, loan, kind="loan"))
        txn_id = self._commit(moves)
        record = PlanRecord(
            plan_id=self.recorder.next_id(),
            kind="interval",
            t_ms=self.store.clock(),
            wall_time=time.time(),
            pools=pools,
            demand=self._per_pool(pools, demand),
            supply=self._per_pool(pools, supply),
            moves=moves,
            unmet=self._per_pool(pools, unmet, skip_zero=True),
            solve_shape=f"{p_pad}x{j_pad}",
            backend="xla",
            compiled=compiled,
            duration_s=seconds,
            txn_id=txn_id,
        )
        self.recorder.add(record)
        self._plan_counter.inc(labels={"kind": "interval"})
        for i, pool in enumerate(pools):
            for d, dim in enumerate(ELASTIC_RESOURCE_DIMS):
                self._unmet_gauge.set(float(unmet[i, d]),
                                      {"pool": pool, "resource": dim})
        return record

    def _extract_moves(self, pools: list[str], matrix: np.ndarray,
                       *, kind: str) -> list[dict]:
        moves = []
        for li, lender in enumerate(pools):
            for bi, borrower in enumerate(pools):
                if li == bi:
                    continue
                amounts = {
                    dim: float(matrix[li, bi, d])
                    for d, dim in enumerate(ELASTIC_RESOURCE_DIMS)
                }
                amounts = {dim: (v if v >= MIN_MOVE[dim] else 0.0)
                           for dim, v in amounts.items()}
                if any(v > 0 for v in amounts.values()):
                    moves.append({"kind": kind, "from": lender,
                                  "to": borrower, **amounts})
        return moves

    @staticmethod
    def _per_pool(pools: list[str], tensor: np.ndarray,
                  *, skip_zero: bool = False) -> dict:
        out = {}
        for i, pool in enumerate(pools):
            row = {dim: float(tensor[i, d])
                   for d, dim in enumerate(ELASTIC_RESOURCE_DIMS)}
            if skip_zero and not any(v > 1e-9 for v in row.values()):
                continue
            out[pool] = row
        return out

    # -------------------------------------------------- commit + converge

    def _commit(self, moves: list[dict]) -> str:
        """Ledger first (durable), clusters second (convergent)."""
        txn_id = ""
        if moves:
            outcome = self.txn.commit("pool/capacity-delta",
                                      {"moves": moves})
            txn_id = outcome.txn_id
            for move in moves:
                self._move_counter.inc(labels={"kind": move["kind"]})
        self.reconcile()
        return txn_id

    def reconcile(self) -> None:
        """Converge every cluster's elastic capacity to the ledger:
        called after each commit AND at promotion (components.py) — a
        leader that died between commit and resize leaves a ledger the
        next leader replays into the same scale() targets."""
        for pool in list(self.store.pools):
            net = self.store.net_capacity_adjustment(pool)
            cluster = self._scale_target(pool)
            if cluster is not None:
                cluster.scale(pool, net)
        self._export_ledger_gauges()

    def _scale_target(self, pool: str):
        """The cluster whose node-pool backs this pool (single-scalable-
        cluster deployments; with several, the one already offering in
        the pool wins)."""
        from cook_tpu.cluster.base import safe_pool_offers

        scalable = [c for c in self.clusters if c.supports_scale()]
        for cluster in scalable:
            # guarded scan: reconcile_clusters runs after every commit,
            # so a flapping offers RPC must skip the cluster, not crash
            # the commit path (safe_pool_offers returns None on error)
            if safe_pool_offers(cluster, pool):
                return cluster
        return scalable[0] if scalable else None

    def _export_ledger_gauges(self) -> None:
        live: set[tuple] = set()
        for row in self.store.encoded_capacity_ledger():
            for dim in ELASTIC_RESOURCE_DIMS:
                key = (row["from"], row["to"], dim)
                live.add(key)
                self._loaned_gauge.set(
                    row[dim], {"from": key[0], "to": key[1],
                               "resource": dim})
        for key in self._gauge_keys - live:
            self._loaned_gauge.set(
                0.0, {"from": key[0], "to": key[1], "resource": key[2]})
        self._gauge_keys = live

    # --------------------------------------------------- reclaim-on-demand

    def reclaim_for(self, pool: str, pending: Sequence[Job],
                    host_spare: dict) -> Optional[dict]:
        """The rebalancer's pre-preemption hook: if `pool` has capacity
        on loan and its head-of-queue demand exceeds current spare,
        reclaim the shortfall (clamped at what is outstanding), commit
        it durably, converge clusters, and return the pool's REFRESHED
        host-spare map so the victim search runs against the returned
        capacity — preempting nobody the reclaim already satisfied.
        Returns None when nothing was reclaimed."""
        outstanding = self.store.outstanding_loans_from(pool)
        if not outstanding:
            return None
        need = {dim: 0.0 for dim in ELASTIC_RESOURCE_DIMS}
        for job in list(pending)[: self.params.reclaim_window]:
            need["mem"] += job.resources.mem
            need["cpus"] += job.resources.cpus
            need["gpus"] += job.resources.gpus
        for res in host_spare.values():
            need["mem"] -= res.mem
            need["cpus"] -= res.cpus
            need["gpus"] -= res.gpus
        unmet = {dim: max(v, 0.0) for dim, v in need.items()}
        starved = {dim for dim, v in unmet.items() if v >= MIN_MOVE[dim]}
        reclaim_kind = "reclaim-on-demand"
        if not starved and self.params.count_block_headroom:
            # scalar spare covers the queue, but does any single block
            # hold a waiting gang?  If not, the loan still starves us —
            # block-shaped headroom is the capacity that matters to gangs
            short = self._gang_block_shortfall(pending, host_spare)
            if short is not None:
                starved = {
                    dim for dim in short["dims"]
                    if any(outstanding[b].get(dim, 0.0) >= MIN_MOVE[dim]
                           for b in outstanding)}
                reclaim_kind = "reclaim-block-headroom"
        if not starved:
            return None
        t0 = time.perf_counter()
        # a starved dimension calls its WHOLE loan home (Aryl semantics:
        # lender demand returns, the loan returns).  Reclaiming only the
        # unmet amount under-delivers whenever the lender's spare map
        # already hides withheld-but-consumed capacity — the spare gain
        # from reclaiming X is min(X, physical free), so partial reclaim
        # can leave the victim search short and preempting anyway.
        moves = []
        for borrower in sorted(outstanding):
            amounts = {}
            for dim in ELASTIC_RESOURCE_DIMS:
                owed = outstanding[borrower][dim]
                amounts[dim] = (owed if dim in starved
                                and owed >= MIN_MOVE[dim] else 0.0)
            if any(v > 0 for v in amounts.values()):
                moves.append({"kind": "reclaim", "from": pool,
                              "to": borrower, **amounts})
        if not moves:
            return None
        txn_id = self._commit(moves)
        refreshed = self._pool_spare(pool)
        self._reclaim_hist.observe(time.perf_counter() - t0,
                                   {"pool": pool})
        self._plan_counter.inc(labels={"kind": reclaim_kind})
        self.recorder.add(PlanRecord(
            plan_id=self.recorder.next_id(),
            kind=reclaim_kind,
            t_ms=self.store.clock(),
            wall_time=time.time(),
            pools=[pool] + sorted(outstanding),
            moves=moves,
            duration_s=time.perf_counter() - t0,
            txn_id=txn_id,
        ))
        return refreshed

    def _gang_block_shortfall(self, pending: Sequence[Job],
                              host_spare: dict) -> Optional[dict]:
        """First waiting gang no topology block can hold: {group,
        gang_size, best_block, dims} or None.  Blocks are contiguous
        runs of the sorted host list, matching the planner's reading of
        the fleet (scheduler/gang.py)."""
        from cook_tpu.ops.hierarchical import choose_nodes_per_block
        from cook_tpu.scheduler.gang import waiting_gangs

        gangs = waiting_gangs(list(pending)[: self.params.reclaim_window])
        if not gangs or not host_spare:
            return None
        hostnames = sorted(host_spare)
        npb = (self.params.gang_block_hosts
               or choose_nodes_per_block(len(hostnames)))
        for group, jobs_g in gangs:
            k = max(j.gang_size for j in jobs_g)
            mem = max(j.resources.mem for j in jobs_g)
            cpus = max(j.resources.cpus for j in jobs_g)
            gpus = max(j.resources.gpus for j in jobs_g)
            best = 0
            for b in range(0, len(hostnames), npb):
                free = 0
                for h in hostnames[b:b + npb]:
                    r = host_spare[h]
                    if r.mem >= mem and r.cpus >= cpus and r.gpus >= gpus:
                        free += 1
                best = max(best, free)
            if best < k:
                dims = {d for d, v in (("mem", mem), ("cpus", cpus),
                                       ("gpus", gpus)) if v > 0}
                return {"group": group, "gang_size": k,
                        "best_block": best, "dims": dims}
        return None

    def _pool_spare(self, pool: str) -> dict:
        from cook_tpu.cluster.base import scan_pool_offers

        spare: dict[str, Resources] = {}
        for _cluster, offer in scan_pool_offers(self.clusters, pool):
            spare[offer.hostname] = Resources(
                mem=offer.mem, cpus=offer.cpus, gpus=offer.gpus,
                disk=offer.disk)
        return spare
