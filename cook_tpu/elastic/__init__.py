"""Elastic capacity plane: TPU-solved pool loaning and cluster autoscaling.

Pools partition the fleet statically; this subsystem loans idle capacity
between them (Aryl's elastic-scheduler design, arXiv:2202.07896) with
durable, failover-safe deltas (cook_tpu/txn), observable decisions
(`GET /debug/elastic`), and a non-disruptive reclaim path that runs
BEFORE in-pool preemption.  See docs/elastic.md.
"""
from cook_tpu.elastic.planner import CapacityPlanner, ElasticParams
from cook_tpu.elastic.recorder import ElasticRecorder, PlanRecord

__all__ = [
    "CapacityPlanner",
    "ElasticParams",
    "ElasticRecorder",
    "PlanRecord",
]
