"""Task executor (reference: executor/)."""
from cook_tpu.executor.runner import (  # noqa: F401
    ExecutorConfig,
    HeartbeatSender,
    RestUpdateSink,
    TaskRunner,
    TaskUpdate,
)
