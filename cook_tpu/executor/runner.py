"""Task executor: runs a job's command and feeds the scheduler.

Reference: executor/ (/root/reference/executor/cook/executor.py —
`CookExecutor` + `manage_task`): launch the command in a sandbox, scrape
progress updates from its output (configurable regex), publish the exit
code and sandbox location, honor kills with a grace period, and send
status transitions.  Here the backend transport is a callable feed rather
than Mesos framework messages; the k8s deployment runs this as the pod's
main process with the sidecar (cook_tpu.sidecar) for file serving.
"""
from __future__ import annotations

import os
import re
import signal
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

# default progress regex, same shape the reference scrapes:
#   "progress: 25 doing the thing" -> (25, "doing the thing")
DEFAULT_PROGRESS_REGEX = r"progress:?\s+([0-9]*\.?[0-9]+)($|\s+.*)"


@dataclass
class ExecutorConfig:
    sandbox_dir: str = "."
    progress_regex: str = DEFAULT_PROGRESS_REGEX
    progress_sample_interval_s: float = 1.0
    shutdown_grace_s: float = 2.0
    stdout_file: str = "stdout"
    stderr_file: str = "stderr"


@dataclass
class TaskUpdate:
    task_id: str
    kind: str                 # "status" | "progress" | "exit-code" | "sandbox"
    status: Optional[str] = None
    progress: int = 0
    progress_message: str = ""
    exit_code: Optional[int] = None
    sandbox: str = ""


UpdateSink = Callable[[TaskUpdate], None]


class TaskRunner:
    """Runs one task; the executor process hosts one of these per task."""

    def __init__(self, task_id: str, command: str, sink: UpdateSink,
                 config: Optional[ExecutorConfig] = None,
                 env: Optional[dict] = None):
        self.task_id = task_id
        self.command = command
        self.sink = sink
        self.config = config or ExecutorConfig()
        self.env = env or {}
        self.proc: Optional[subprocess.Popen] = None
        self._progress_re = re.compile(self.config.progress_regex)
        self._last_progress = -1
        self._last_progress_sent = 0.0
        self._killed = threading.Event()

    def run(self) -> int:
        cfg = self.config
        os.makedirs(cfg.sandbox_dir, exist_ok=True)
        self.sink(TaskUpdate(self.task_id, "sandbox",
                             sandbox=os.path.abspath(cfg.sandbox_dir)))
        stdout_path = os.path.join(cfg.sandbox_dir, cfg.stdout_file)
        stderr_path = os.path.join(cfg.sandbox_dir, cfg.stderr_file)
        env = {**os.environ, **self.env,
               "COOK_TASK_ID": self.task_id,
               "COOK_WORKDIR": os.path.abspath(cfg.sandbox_dir)}
        with open(stdout_path, "wb") as out, open(stderr_path, "wb") as err:
            self.proc = subprocess.Popen(
                ["/bin/sh", "-c", self.command],
                stdout=subprocess.PIPE,
                stderr=err,
                cwd=cfg.sandbox_dir,
                env=env,
                start_new_session=True,  # kill the whole process group
            )
            self.sink(TaskUpdate(self.task_id, "status", status="running"))
            # tee stdout to the sandbox file while scraping progress
            assert self.proc.stdout is not None
            for raw in self.proc.stdout:
                out.write(raw)
                out.flush()
                self._scrape_progress(raw)
            self.proc.stdout.close()
            code = self.proc.wait()
        self._flush_progress(force=True)
        self.sink(TaskUpdate(self.task_id, "exit-code", exit_code=code))
        status = "success" if code == 0 and not self._killed.is_set() \
            else "failed"
        self.sink(TaskUpdate(self.task_id, "status", status=status))
        return code

    def _scrape_progress(self, raw: bytes) -> None:
        try:
            line = raw.decode(errors="replace").strip()
        except Exception:
            return
        match = self._progress_re.search(line)
        if not match:
            return
        pct = int(float(match.group(1)))
        message = (match.group(2) or "").strip()
        if pct > self._last_progress:
            self._last_progress = pct
            self._progress_message = message
            self._flush_progress()

    def _flush_progress(self, force: bool = False) -> None:
        """Sampled publication (the reference throttles progress sends)."""
        now = time.monotonic()
        if self._last_progress < 0:
            return
        if not force and now - self._last_progress_sent \
                < self.config.progress_sample_interval_s:
            return
        self._last_progress_sent = now
        self.sink(TaskUpdate(
            self.task_id, "progress",
            progress=min(self._last_progress, 100),
            progress_message=getattr(self, "_progress_message", ""),
        ))

    def kill(self) -> None:
        """Graceful shutdown: SIGTERM, grace period, SIGKILL (reference:
        executor gracefully_shutdown)."""
        self._killed.set()
        if self.proc is None or self.proc.poll() is not None:
            return
        try:
            os.killpg(self.proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            return
        deadline = time.monotonic() + self.config.shutdown_grace_s
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                return
            time.sleep(0.05)
        try:
            os.killpg(self.proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass


def _executor_session(session=None):
    """requests.Session carrying the shared executor secret
    (COOK_EXECUTOR_TOKEN) so heartbeat/progress posts stay spoof-proof
    under strict auth."""
    import requests

    session = session or requests.Session()
    token = os.environ.get("COOK_EXECUTOR_TOKEN", "")
    if token:
        session.headers["X-Cook-Executor-Token"] = token
    return session


class RestUpdateSink:
    """Publishes executor updates to the scheduler's REST API (the k8s-mode
    transport; the sidecar progress reporter does the same,
    sidecar/progress.py)."""

    def __init__(self, base_url: str, session=None):
        self.base_url = base_url.rstrip("/")
        self.session = _executor_session(session)

    def __call__(self, update: TaskUpdate) -> None:
        if update.kind == "progress":
            try:
                self.session.post(
                    f"{self.base_url}/progress/{update.task_id}",
                    json={"progress_percent": update.progress,
                          "progress_message": update.progress_message},
                    timeout=10,
                )
            except Exception:  # noqa: BLE001 — progress is best-effort
                pass


class HeartbeatSender:
    """Background liveness beats to the scheduler while a task runs
    (reference: the executor's heartbeat framework messages)."""

    def __init__(self, base_url: str, task_id: str, *,
                 interval_s: float = 30.0, session=None):
        self.url = f"{base_url.rstrip('/')}/heartbeat/{task_id}"
        self.interval_s = interval_s
        self.session = _executor_session(session)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HeartbeatSender":
        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.session.post(self.url, timeout=10)
                except Exception:  # noqa: BLE001 — best-effort
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="heartbeat")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
