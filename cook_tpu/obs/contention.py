"""Control-plane contention observatory: where the write path's time goes.

ROADMAP item 2 (sharded control plane) starts from a measurement gap:
every mutation serializes through one store lock, one journal fsync
pipeline, one replication stream, and one REST process — but nothing
measured which of those saturates first, so the sharding refactor would
fly blind.  This module instruments every serialization point and
serves it live:

  * `ProfiledRLock` / `LockProfiler` — per-call-site wait and hold
    histograms for the store lock, current-holder + longest-waiter
    gauges, and a windowed contention ratio (`models/store.py` wraps
    its RLock in one; every `with store._lock:` site in the tree gets
    labeled by its calling function automatically).
  * `JournalTelemetry` — append volume/bytes, pending-fsync depth,
    group-fsync batch sizes, and the fsync stall histogram
    (`models/persistence.JournalWriter` reports into the module
    singleton).
  * `EndpointTelemetry` — per-route REST latency / RPS / in-flight /
    error-rate (fed by `rest/api.py`'s outermost middleware).
  * `SloBurnTracker` — fast/slow-window SLO burn-rate evaluation over
    the commit-ack latency stream (`scheduler/monitor.observe_commit_ack`
    feeds the module singleton alongside the lifecycle histogram).
  * `ContentionObservatory` — the aggregator: the `GET /debug/contention`
    snapshot, plus the control-plane health degradations folded into
    `GET /debug/health`: `store-lock-saturation`, `fsync-stall`,
    `replication-lag`, `commit-ack-slo-burn`, `job-starvation`.

Import discipline: this module imports ONLY `utils.metrics` — the store
and the journal writer import it at module level, and those must stay
cheap and jax-free (`cook_tpu/obs/__init__` is lazy for the same
reason).
"""
from __future__ import annotations

import collections
import os
import statistics
import sys
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from cook_tpu.utils.metrics import global_registry

# ------------------------------------------------------- degradation reasons

STORE_LOCK_SATURATION = "store-lock-saturation"
FSYNC_STALL = "fsync-stall"
REPLICATION_LAG = "replication-lag"
COMMIT_ACK_SLO_BURN = "commit-ack-slo-burn"
JOB_STARVATION = "job-starvation"
# the journal's degrade-to-async fsync policy is in effect: an fsync
# FAILED (not merely stalled) and commits are proceeding without the
# disk barrier (models/persistence.JournalWriter, docs/resilience.md)
JOURNAL_FSYNC_DEGRADED = "journal-fsync-degraded"

CONTENTION_REASONS = (STORE_LOCK_SATURATION, FSYNC_STALL, REPLICATION_LAG,
                      COMMIT_ACK_SLO_BURN, JOB_STARVATION,
                      JOURNAL_FSYNC_DEGRADED)

# lock waits/holds live in the microsecond-to-millisecond range; the
# default request-scale buckets would collapse everything into the
# first bucket
LOCK_BUCKETS = (1e-6, 5e-6, 1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 0.01,
                0.05, 0.1, 0.5, 1.0, 5.0, float("inf"))
FSYNC_BUCKETS = (1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.025, 0.05,
                 0.1, 0.25, 0.5, 1.0, 5.0, float("inf"))
BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 1024, float("inf"))


def _site_of(code) -> str:
    module = os.path.basename(code.co_filename)
    if module.endswith(".py"):
        module = module[:-3]
    return f"{module}.{code.co_name}"


# code object -> "module.function": call sites are code, not workload,
# so this is bounded; caching skips the per-acquisition path/string work
_SITE_CACHE: dict = {}


def _caller_site(depth: int) -> str:
    """`module.function` of the frame `depth` levels up — the per-call-
    site label for lock profiling."""
    try:
        code = sys._getframe(depth).f_code
    except ValueError:
        return "unknown"
    site = _SITE_CACHE.get(code)
    if site is None:
        site = _SITE_CACHE[code] = _site_of(code)
    return site


# ------------------------------------------------------------ lock profiling


class LockProfiler:
    """Aggregation target for one named lock: per-site wait/hold stats,
    the current holder, the longest live waiter, and a count-windowed
    contention ratio (fraction of the last `window` outermost
    acquisitions that found the lock held)."""

    def __init__(self, name: str = "store", window: int = 512):
        self.name = name
        self.window = window
        self._lock = threading.Lock()
        self._sites: dict[str, dict] = {}
        # recent outermost acquisitions: True where the acquirer waited
        self._recent: collections.deque[bool] = collections.deque(
            maxlen=window)
        self._holder: Optional[dict] = None
        self._waiters: dict[int, dict] = {}
        self.acquisitions = 0
        self.contended = 0
        self.wait_seconds_total = 0.0
        self.hold_seconds_total = 0.0
        labels = {"lock": name}
        self._labels = labels
        # per-site label-bound metric handles: the store lock is hot
        # enough (tens of thousands of acquisitions per match cycle)
        # that re-sorting a label dict per observation is real probe
        # overhead; bound once per call site instead
        self._instruments: dict[str, tuple] = {}
        self._wait_hist = global_registry.histogram(
            "store.lock.wait_seconds",
            "seconds spent waiting for the store lock per call site",
            buckets=LOCK_BUCKETS)
        self._hold_hist = global_registry.histogram(
            "store.lock.hold_seconds",
            "seconds the store lock was held per call site",
            buckets=LOCK_BUCKETS)
        self._acq_counter = global_registry.counter(
            "store.lock.acquisitions",
            "outermost store-lock acquisitions per call site")
        self._contended_counter = global_registry.counter(
            "store.lock.contended",
            "outermost store-lock acquisitions that found the lock held")
        self._waiters_gauge = global_registry.gauge(
            "store.lock.waiters", "threads currently waiting for the lock")
        self._ratio_gauge = global_registry.gauge(
            "store.lock.contention_ratio",
            "contended fraction of recent outermost lock acquisitions")
        self._longest_gauge = global_registry.gauge(
            "store.lock.longest_wait_seconds",
            "age of the longest currently-parked lock waiter")
        self._bound_waiters = self._waiters_gauge.bind(labels)

    def _site_instruments(self, site: str) -> tuple:
        """(wait_hist, hold_hist, acq_counter, contended_counter) bound
        to this site's labels; caller holds self._lock."""
        inst = self._instruments.get(site)
        if inst is None:
            labels = {"lock": self.name, "site": site}
            inst = self._instruments[site] = (
                self._wait_hist.bind(labels), self._hold_hist.bind(labels),
                self._acq_counter.bind(labels),
                self._contended_counter.bind(labels))
        return inst

    # --- called from ProfiledRLock (hot path: keep it lean)

    def note_waiting(self, site: str, t0: float) -> None:
        with self._lock:
            self._waiters[threading.get_ident()] = {"site": site, "t0": t0}
            self._bound_waiters.set(len(self._waiters))

    def unnote_waiting(self) -> None:
        with self._lock:
            self._waiters.pop(threading.get_ident(), None)
            self._bound_waiters.set(len(self._waiters))

    def note_acquired(self, site: str, wait_s: float, waited: bool) -> None:
        with self._lock:
            self.acquisitions += 1
            self.wait_seconds_total += wait_s
            self.contended += waited
            self._recent.append(waited)
            entry = self._sites.get(site)
            if entry is None:
                entry = self._sites[site] = {
                    "acquisitions": 0, "contended": 0, "wait_s": 0.0,
                    "hold_s": 0.0, "max_wait_s": 0.0, "max_hold_s": 0.0}
            entry["acquisitions"] += 1
            entry["contended"] += waited
            entry["wait_s"] += wait_s
            entry["max_wait_s"] = max(entry["max_wait_s"], wait_s)
            self._holder = {"site": site, "since": time.monotonic(),
                            "thread": threading.get_ident()}
            wait_h, _, acq_c, cont_c = self._site_instruments(site)
        wait_h.observe(wait_s)
        acq_c.inc()
        if waited:
            cont_c.inc()

    def note_released(self, site: str, hold_s: float) -> None:
        with self._lock:
            self.hold_seconds_total += hold_s
            entry = self._sites.get(site)
            if entry is not None:
                entry["hold_s"] += hold_s
                entry["max_hold_s"] = max(entry["max_hold_s"], hold_s)
            if self._holder is not None and \
                    self._holder["thread"] == threading.get_ident():
                self._holder = None
            hold_h = self._site_instruments(site)[1]
        hold_h.observe(hold_s)

    # --- reads

    def contention_ratio(self) -> float:
        with self._lock:
            if not self._recent:
                return 0.0
            return sum(self._recent) / len(self._recent)

    def recent_samples(self) -> int:
        with self._lock:
            return len(self._recent)

    def snapshot(self, top: int = 20) -> dict:
        now = time.monotonic()
        with self._lock:
            holder = None
            if self._holder is not None:
                holder = {"site": self._holder["site"],
                          "held_s": now - self._holder["since"]}
            longest = None
            for waiter in self._waiters.values():
                waited_s = now - waiter["t0"]
                if longest is None or waited_s > longest["waited_s"]:
                    longest = {"site": waiter["site"], "waited_s": waited_s}
            sites = sorted(self._sites.items(),
                           key=lambda kv: kv[1]["wait_s"], reverse=True)
            ratio = (sum(self._recent) / len(self._recent)
                     if self._recent else 0.0)
            body = {
                "lock": self.name,
                "acquisitions": self.acquisitions,
                "contended": self.contended,
                "contention_ratio": ratio,
                "recent_window": len(self._recent),
                "wait_seconds_total": self.wait_seconds_total,
                "hold_seconds_total": self.hold_seconds_total,
                "holder": holder,
                "longest_waiter": longest,
                "waiters": len(self._waiters),
                "sites": {site: dict(entry) for site, entry in sites[:top]},
            }
        self._ratio_gauge.set(ratio, self._labels)
        self._longest_gauge.set(longest["waited_s"] if longest else 0.0,
                                self._labels)
        return body


class ProfiledRLock:
    """Drop-in RLock that reports outermost acquisitions to a
    LockProfiler.  Re-entrant acquisitions (the store's query helpers
    called under a held write transaction) are passed straight through —
    their wait is zero by construction and their hold belongs to the
    outermost owner."""

    def __init__(self, profiler: LockProfiler):
        self._lock = threading.RLock()
        self.profiler = profiler
        self._local = threading.local()

    def acquire(self, blocking: bool = True, timeout: float = -1,
                *, _site: Optional[str] = None) -> bool:
        depth = getattr(self._local, "depth", 0)
        if depth:
            ok = self._lock.acquire(blocking, timeout)
            if ok:
                self._local.depth = depth + 1
            return ok
        site = _site if _site is not None else _caller_site(2)
        t0 = time.perf_counter()
        waited = False
        if not self._lock.acquire(False):
            waited = True
            self.profiler.note_waiting(site, time.monotonic())
            try:
                if not self._lock.acquire(blocking, timeout):
                    return False
            finally:
                self.profiler.unnote_waiting()
        wait_s = time.perf_counter() - t0
        self._local.depth = 1
        self._local.site = site
        self._local.acquired = time.perf_counter()
        self.profiler.note_acquired(site, wait_s, waited)
        return True

    def release(self) -> None:
        depth = getattr(self._local, "depth", 0)
        if depth == 1:
            hold_s = time.perf_counter() - self._local.acquired
            self.profiler.note_released(self._local.site, hold_s)
        self._local.depth = max(depth - 1, 0)
        self._lock.release()

    def __enter__(self) -> "ProfiledRLock":
        self.acquire(_site=_caller_site(2))
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def profiled_store_lock(name: str = "store") -> ProfiledRLock:
    """The store's lock constructor (models/store.py).  One profiler per
    STORE (not per process): a production node runs one store, and in
    tests a fresh store must not inherit another suite's contention
    window — the Prometheus metrics underneath are process-global
    regardless (same names, shared registry)."""
    return ProfiledRLock(LockProfiler(name))


# --------------------------------------------------------- journal pipeline


class JournalTelemetry:
    """The txn journal's write-path telemetry: append volume and bytes,
    pending-fsync depth (events flushed to the OS but not yet on disk —
    the append "queue" a crash-consistency bound cares about), group-
    fsync batch sizes, and the fsync stall histogram.  One instance per
    JournalWriter (`writer.telemetry`) — the observatory reads ITS
    store's journal, so another process-resident journal's disk stalls
    (tests spin up many) can't flip this node's verdict.  The Prometheus
    metrics underneath are process-global regardless."""

    def __init__(self, recent_fsyncs: int = 64):
        self._lock = threading.Lock()
        self._recent_fsyncs: collections.deque[float] = collections.deque(
            maxlen=recent_fsyncs)
        self.appends = 0
        self.bytes_written = 0
        self.fsyncs = 0
        self.fsync_errors = 0
        self.degraded = False
        self.fsync_seconds_total = 0.0
        self.max_fsync_s = 0.0
        self.last_batch = 0
        self._append_counter = global_registry.counter(
            "journal.appends", "journal lines appended")
        self._bytes_counter = global_registry.counter(
            "journal.bytes_written", "journal bytes appended")
        self._pending_gauge = global_registry.gauge(
            "journal.pending_fsync",
            "events flushed to the OS but not yet fsynced")
        self._fsync_hist = global_registry.histogram(
            "journal.fsync_seconds", "journal fsync stall seconds",
            buckets=FSYNC_BUCKETS)
        self._batch_hist = global_registry.histogram(
            "journal.fsync_batch_events",
            "events covered by one group fsync", buckets=BATCH_BUCKETS)
        self._error_counter = global_registry.counter(
            "journal.fsync_errors", "journal fsyncs that FAILED (raised)")
        self._degraded_gauge = global_registry.gauge(
            "journal.degraded",
            "1 while the journal runs in degraded async mode (fsync "
            "failed under the degrade-to-async policy)")

    def note_append(self, n_bytes: int, pending: int) -> None:
        with self._lock:
            self.appends += 1
            self.bytes_written += n_bytes
        self._append_counter.inc()
        self._bytes_counter.inc(n_bytes)
        self._pending_gauge.set(pending)

    def note_fsync(self, batch_events: int, seconds: float) -> None:
        with self._lock:
            self.fsyncs += 1
            self.fsync_seconds_total += seconds
            self.max_fsync_s = max(self.max_fsync_s, seconds)
            self.last_batch = batch_events
            self._recent_fsyncs.append(seconds)
        self._fsync_hist.observe(seconds)
        self._batch_hist.observe(float(batch_events))
        self._pending_gauge.set(0)

    def note_fsync_error(self) -> None:
        with self._lock:
            self.fsync_errors += 1
        self._error_counter.inc()

    def set_degraded(self, degraded: bool) -> None:
        with self._lock:
            self.degraded = degraded
        self._degraded_gauge.set(1.0 if degraded else 0.0)

    def is_degraded(self) -> bool:
        with self._lock:
            return self.degraded

    def note_rotate(self) -> None:
        """Journal rotation dropped the unfsynced tail with the old
        file — nothing is pending against the fresh one."""
        self._pending_gauge.set(0)

    def recent_fsync_max(self) -> float:
        with self._lock:
            return max(self._recent_fsyncs, default=0.0)

    def snapshot(self) -> dict:
        with self._lock:
            recent = list(self._recent_fsyncs)
            return {
                "appends": self.appends,
                "bytes_written": self.bytes_written,
                "fsyncs": self.fsyncs,
                "fsync_errors": self.fsync_errors,
                "degraded": self.degraded,
                "fsync_seconds_total": self.fsync_seconds_total,
                "fsync_max_s": self.max_fsync_s,
                "recent_fsync_max_s": max(recent, default=0.0),
                "recent_fsync_p50_ms": (
                    statistics.median(recent) * 1000 if recent else 0.0),
                "last_batch_events": self.last_batch,
                "mean_batch_events": (self.appends / self.fsyncs
                                      if self.fsyncs else 0.0),
            }


# ------------------------------------------------------------ REST endpoints


class EndpointTelemetry:
    """Per-route REST telemetry: latency histogram + request counter at
    /metrics, and an in-object sliding sample window per (route, method)
    for the live RPS / p50 / p99 / error-rate table /debug/contention
    serves.  Route labels are matched route templates (bounded by the
    route table, not the workload)."""

    def __init__(self, samples_per_route: int = 512):
        self._lock = threading.Lock()
        self._routes: dict[tuple[str, str], dict] = {}
        self._samples = samples_per_route
        self._hist = global_registry.histogram(
            "rest.request_seconds",
            "REST request wall seconds per route/method")
        self._counter = global_registry.counter(
            "rest.requests", "REST requests per route/method/status class")
        self._in_flight_gauge = global_registry.gauge(
            "rest.in_flight", "REST requests currently being served")

    def _entry(self, route: str, method: str) -> dict:
        key = (route, method)
        entry = self._routes.get(key)
        if entry is None:
            entry = self._routes[key] = {
                "count": 0, "errors": 0, "in_flight": 0,
                "recent": collections.deque(maxlen=self._samples),
            }
        return entry

    def begin(self, route: str, method: str) -> None:
        with self._lock:
            entry = self._entry(route, method)
            entry["in_flight"] += 1
            total = sum(e["in_flight"] for e in self._routes.values())
        self._in_flight_gauge.set(total)

    def done(self, route: str, method: str, status: int,
             seconds: float) -> None:
        error = status >= 500
        with self._lock:
            entry = self._entry(route, method)
            entry["in_flight"] = max(entry["in_flight"] - 1, 0)
            entry["count"] += 1
            entry["errors"] += error
            entry["recent"].append((time.monotonic(), seconds, error))
            total = sum(e["in_flight"] for e in self._routes.values())
        self._in_flight_gauge.set(total)
        labels = {"route": route, "method": method,
                  "status": f"{status // 100}xx"}
        self._counter.inc(1, labels)
        self._hist.observe(seconds, {"route": route, "method": method})

    def snapshot(self, window_s: float = 60.0) -> dict:
        now = time.monotonic()
        out = {}
        with self._lock:
            items = [(key, dict(entry), list(entry["recent"]))
                     for key, entry in self._routes.items()]
        for (route, method), entry, recent in items:
            in_window = [(t, s, e) for t, s, e in recent
                         if now - t <= window_s]
            durations = sorted(s for _, s, _ in in_window)
            # a full deque may retain less than window_s of history (a
            # busy route evicts old samples); dividing by the nominal
            # window would cap reported RPS at maxlen/window_s
            effective_s = window_s
            if recent and len(recent) == self._samples:
                effective_s = min(window_s, max(now - recent[0][0], 1e-9))
            row = {
                "count": entry["count"],
                "errors": entry["errors"],
                "in_flight": entry["in_flight"],
                "window_s": effective_s,
                "rps": len(in_window) / effective_s,
                "error_rate": (sum(e for _, _, e in in_window)
                               / len(in_window)) if in_window else 0.0,
            }
            if durations:
                row["p50_ms"] = _percentile(durations, 50) * 1000
                row["p99_ms"] = _percentile(durations, 99) * 1000
            out[f"{method} {route}"] = row
        return out


def _percentile(sorted_values: list[float], q: float) -> float:
    if not sorted_values:
        return 0.0
    idx = min(len(sorted_values) - 1,
              max(0, round(q / 100 * (len(sorted_values) - 1))))
    return sorted_values[idx]


# ------------------------------------------------------------- SLO burn rate


# latency bin bounds for burn-rate bucketing: evaluation is EXACT when
# the SLO threshold is one of these (a sample counts as violating iff
# its bin lies strictly above the threshold's bin); an off-grid
# threshold effectively rounds up to its bin's upper bound
_SLO_BOUNDS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
               10.0, 30.0, float("inf"))


class SloBurnTracker:
    """Fast/slow-window SLO burn-rate evaluation (the multi-window SRE
    pattern: page only when BOTH a fast and a slow window burn error
    budget faster than allowed — a blip trips neither, a sustained burn
    trips both).

    Window counts come from time-bucketed latency histograms
    (`bucket_s`-wide buckets retained `retention_s` back), so the slow
    window stays honest at ANY commit rate — a count-bounded ring would
    silently shrink the hour window to seconds at high RPS, collapsing
    both windows onto the same samples and paging on exactly the blip
    this pattern exists to suppress.  A bounded sample ring rides along
    for the reported percentiles only."""

    def __init__(self, capacity: int = 4096, bucket_s: float = 10.0,
                 retention_s: float = 3660.0 * 2):
        self._lock = threading.Lock()
        # recent raw samples: percentile estimates, not burn counts
        self._ring: collections.deque[tuple[float, float]] = \
            collections.deque(maxlen=capacity)
        self._bucket_s = bucket_s
        self._retention_s = retention_s
        # bucket start -> per-latency-bin counts (_SLO_BOUNDS)
        self._buckets: dict[float, list[int]] = {}
        self._newest_t = 0.0

    def observe(self, seconds: float, t: Optional[float] = None) -> None:
        import bisect

        t = time.time() if t is None else t
        start = t - (t % self._bucket_s)
        bin_i = bisect.bisect_left(_SLO_BOUNDS, seconds)
        with self._lock:
            self._ring.append((t, seconds))
            counts = self._buckets.get(start)
            if counts is None:
                counts = self._buckets[start] = [0] * len(_SLO_BOUNDS)
                self._newest_t = max(self._newest_t, t)
                cutoff = self._newest_t - self._retention_s
                for old in [s for s in self._buckets if s < cutoff]:
                    del self._buckets[old]
            counts[bin_i] += 1

    def stats(self, *, threshold_s: float, budget: float, fast_s: float,
              slow_s: float, now: Optional[float] = None) -> dict:
        """Burn rate per window = (violating fraction) / (error budget).
        >1.0 means the window is consuming budget faster than allowed."""
        import bisect

        now = time.time() if now is None else now
        with self._lock:
            buckets = [(s, list(c)) for s, c in self._buckets.items()]
            ring = list(self._ring)
        thr_bin = bisect.bisect_left(_SLO_BOUNDS, threshold_s)

        def window(window_s: float) -> tuple[float, int, int]:
            total = over = 0
            for start, counts in buckets:
                # whole-bucket granularity: a bucket counts if any of
                # it overlaps [now - window_s, now]
                if start + self._bucket_s > now - window_s and start <= now:
                    total += sum(counts)
                    over += sum(counts[thr_bin + 1:])
            if not total:
                return 0.0, 0, 0
            return (over / total) / max(budget, 1e-9), over, total

        fast_burn, fast_over, fast_n = window(fast_s)
        slow_burn, slow_over, slow_n = window(slow_s)
        durations = sorted(s for t, s in ring if now - t <= slow_s)
        return {
            "threshold_s": threshold_s,
            "budget": budget,
            "fast_window_s": fast_s,
            "slow_window_s": slow_s,
            "fast_burn": fast_burn,
            "slow_burn": slow_burn,
            "fast_samples": fast_n,
            "fast_over": fast_over,
            "slow_samples": slow_n,
            "slow_over": slow_over,
            "p50_ms": _percentile(durations, 50) * 1000,
            "p99_ms": _percentile(durations, 99) * 1000,
        }


# --------------------------------------------------------------- aggregator


@dataclass
class ContentionParams:
    """Thresholds for the control-plane degradation checks."""

    # store-lock-saturation: contended fraction of the recent
    # acquisition window, with a floor on how many samples judge it
    lock_contention_ratio: float = 0.5
    lock_min_acquisitions: int = 64
    # fsync-stall: any fsync in the recent window slower than this
    fsync_stall_s: float = 0.25
    # replication-lag: a follower this many events behind, or behind at
    # all and silent this long
    replication_lag_events: int = 1000
    replication_ack_age_s: float = 15.0
    # commit-ack SLO: latency bound, violating budget, burn windows
    commit_ack_slo_s: float = 1.0
    commit_ack_budget: float = 0.01
    burn_fast_s: float = 300.0
    burn_slow_s: float = 3600.0
    burn_threshold: float = 1.0
    # job-starvation: oldest queued job older than this
    starvation_age_s: float = 1800.0


class ContentionObservatory:
    """Aggregates every write-path instrument into one live surface.

    `snapshot()` is the GET /debug/contention body; `evaluate()` returns
    (degradations, checks) that rest/api.py folds into the
    GET /debug/health verdict next to the device-telemetry checks."""

    def __init__(self, store, *, params: Optional[ContentionParams] = None,
                 endpoints: Optional[EndpointTelemetry] = None,
                 journal_fn: Optional[
                     Callable[[], Optional[JournalTelemetry]]] = None,
                 commit_ack: Optional[SloBurnTracker] = None,
                 replication_meta_fn: Optional[Callable[[], dict]] = None,
                 starvation_fn: Optional[Callable[[], dict]] = None,
                 shards_fn: Optional[Callable[[], list]] = None):
        self.store = store
        self.params = params or ContentionParams()
        self.endpoints = endpoints
        # resolves to THIS node's journal writer telemetry (rest/api.py
        # passes the transaction log's journal); the empty fallback
        # renders zeros on journal-less deployments
        self.journal_fn = journal_fn
        self._journal_fallback = JournalTelemetry()
        # per-observatory: burn-rate windows must not inherit another
        # api instance's samples (the owning CookApi feeds this from its
        # commit path, next to the lifecycle histogram)
        self.commit_ack = commit_ack or SloBurnTracker()
        # leader view: follower -> {seq, durable, time(monotonic), ...}
        # (rest/api.py replication_ack_meta)
        self.replication_meta_fn = replication_meta_fn or (lambda: {})
        # pool -> starvation stats (scheduler/monitor.starvation_stats)
        self.starvation_fn = starvation_fn or (lambda: {})
        # sharded control plane (cook_tpu/shard/): per-shard rows — each
        # shard's lock profiler, journal-segment telemetry, and commit
        # service-time window (ShardedTransactionLog.shard_view); None on
        # single-shard deployments.  rest/api.py wires this after
        # construction (the txn log is built before the observatory).
        self.shards_fn = shards_fn
        self._lag_gauge = global_registry.gauge(
            "replication.follower_lag_events",
            "events the follower's last ack trails the leader by")
        self._ack_age_gauge = global_registry.gauge(
            "replication.follower_ack_age_seconds",
            "seconds since the follower's last replication ack")
        self._reason_gauge = global_registry.gauge(
            "obs.health.reason_active",
            "1 while the labeled degradation reason is active")

    # ------------------------------------------------------------- views

    def _lock_profiler(self) -> Optional[LockProfiler]:
        lock = getattr(self.store, "_lock", None)
        return getattr(lock, "profiler", None)

    def _journal(self) -> JournalTelemetry:
        journal = self.journal_fn() if self.journal_fn is not None else None
        return journal if journal is not None else self._journal_fallback

    def replication_view(self) -> list[dict]:
        """Per-follower ack lag, computed leader-side: event delta vs
        the store head, seconds since the last ack, durable split.  On a
        sharded store each ack names its shard and lags against THAT
        shard's head (sequence numbers are per-shard)."""
        shards = getattr(self.store, "shards", None)
        last_seq = self.store.last_seq()
        now = time.monotonic()
        out = []
        for follower, meta in sorted(self.replication_meta_fn().items()):
            shard = int(meta.get("shard", 0))
            if shards is not None and 0 <= shard < len(shards):
                head = shards[shard].last_seq()
            else:
                head = last_seq
            lag_events = max(0, head - int(meta.get("seq", 0)))
            ack_age_s = now - meta.get("time", now)
            out.append({
                "follower": follower,
                "shard": shard,
                "acked_seq": int(meta.get("seq", 0)),
                "leader_seq": head,
                "lag_events": lag_events,
                "ack_age_s": ack_age_s,
                "durable": bool(meta.get("durable", False)),
                "last_txn_id": meta.get("last_txn_id", ""),
            })
            self._lag_gauge.set(lag_events, {"follower": follower})
            self._ack_age_gauge.set(ack_age_s, {"follower": follower})
        return out

    def commit_ack_stats(self) -> dict:
        p = self.params
        return self.commit_ack.stats(
            threshold_s=p.commit_ack_slo_s, budget=p.commit_ack_budget,
            fast_s=p.burn_fast_s, slow_s=p.burn_slow_s)

    def snapshot(self) -> dict:
        profiler = self._lock_profiler()
        body = {
            "store_lock": (profiler.snapshot() if profiler is not None
                           else {"profiled": False}),
            "journal": self._journal().snapshot(),
            "replication": self.replication_view(),
            "endpoints": (self.endpoints.snapshot()
                          if self.endpoints is not None else {}),
            "commit_ack": self.commit_ack_stats(),
            "starvation": self.starvation_fn(),
            "wall_time": time.time(),
        }
        if self.shards_fn is not None:
            # per-shard attribution (cook_tpu/shard/): each shard's lock,
            # journal segment, and commit service-time window — the
            # hottest-shard answer tools/loadtest.py scrapes
            body["shards"] = self.shards_fn()
        return body

    # ------------------------------------------------------------- health

    def evaluate(self) -> tuple[list[dict], dict]:
        """(degradations, checks) for the /debug/health merge.  Every
        check contributes evidence even when green; each reason has an
        inducing test in tests/test_contention.py."""
        p = self.params
        degradations: list[dict] = []
        checks: dict = {}

        profiler = self._lock_profiler()
        if profiler is not None:
            ratio = profiler.contention_ratio()
            samples = profiler.recent_samples()
            checks["store_lock"] = {
                "contention_ratio": ratio, "recent_window": samples,
                "threshold": p.lock_contention_ratio}
            if samples >= p.lock_min_acquisitions and \
                    ratio >= p.lock_contention_ratio:
                degradations.append({
                    "reason": STORE_LOCK_SATURATION,
                    "detail": (
                        f"{ratio:.0%} of the last {samples} store-lock "
                        f"acquisitions waited (threshold "
                        f"{p.lock_contention_ratio:.0%}) — the single "
                        f"store lock is the bottleneck; see "
                        f"/debug/contention for the per-site split"),
                    "contention_ratio": ratio,
                    "recent_window": samples,
                })

        journal = self._journal()
        stall = journal.recent_fsync_max()
        checks["journal"] = {"recent_fsync_max_s": stall,
                             "threshold_s": p.fsync_stall_s,
                             "degraded": journal.is_degraded(),
                             "fsync_errors": journal.fsync_errors}
        if journal.is_degraded():
            degradations.append({
                "reason": JOURNAL_FSYNC_DEGRADED,
                "detail": (
                    "journal fsync FAILED and the degrade-to-async "
                    "policy is in effect: commits proceed without the "
                    "disk barrier (an OS crash may lose the unfsynced "
                    "tail) until a disk probe succeeds — check the "
                    "volume; see docs/resilience.md"),
                "fsync_errors": journal.fsync_errors,
            })
        if stall >= p.fsync_stall_s:
            degradations.append({
                "reason": FSYNC_STALL,
                "detail": (
                    f"journal fsync stalled {stall * 1000:.0f} ms in the "
                    f"recent window (threshold "
                    f"{p.fsync_stall_s * 1000:.0f} ms) — every commit ack "
                    f"waits on this disk barrier"),
                "recent_fsync_max_s": stall,
            })

        if self.shards_fn is not None:
            # per-shard fsync health: a wedged SEGMENT degrades with its
            # shard id attached, so the chaos wedged-shard drill (and an
            # operator) can see exactly which shard's keys are affected
            shard_checks = {}
            for row in self.shards_fn():
                shard = row.get("shard")
                jstats = row.get("journal") or {}
                stall_s = float(jstats.get("recent_fsync_max_s", 0.0))
                shard_checks[str(shard)] = {
                    "recent_fsync_max_s": stall_s,
                    "degraded": bool(jstats.get("degraded")),
                    "commit_p99_ms": (row.get("commit_ack") or {}).get(
                        "p99_ms", 0.0),
                }
                if jstats.get("degraded"):
                    degradations.append({
                        "reason": JOURNAL_FSYNC_DEGRADED,
                        "shard": shard,
                        "detail": (
                            f"shard {shard}'s journal segment is running "
                            f"degraded-async after an fsync FAILURE — "
                            f"only this shard's keys ride the page cache; "
                            f"see docs/operations.md (diagnosing a hot "
                            f"shard)"),
                        "fsync_errors": jstats.get("fsync_errors", 0),
                    })
                if stall_s >= p.fsync_stall_s:
                    degradations.append({
                        "reason": FSYNC_STALL,
                        "shard": shard,
                        "detail": (
                            f"shard {shard}'s journal segment fsync "
                            f"stalled {stall_s * 1000:.0f} ms (threshold "
                            f"{p.fsync_stall_s * 1000:.0f} ms) — commits "
                            f"ROUTED TO THIS SHARD wait on it; other "
                            f"shards' segments are unaffected"),
                        "recent_fsync_max_s": stall_s,
                    })
            checks["shards"] = shard_checks

        followers = self.replication_view()
        checks["replication"] = {"followers": followers}
        for f in followers:
            behind = f["lag_events"] >= p.replication_lag_events
            silent = (f["lag_events"] > 0
                      and f["ack_age_s"] >= p.replication_ack_age_s)
            if behind or silent:
                degradations.append({
                    "reason": REPLICATION_LAG,
                    "follower": f["follower"],
                    "detail": (
                        f"follower {f['follower']} is {f['lag_events']} "
                        f"events behind (last ack "
                        f"{f['ack_age_s']:.1f} s ago, durable="
                        f"{f['durable']}) — sync-ack commits are waiting "
                        f"on it"),
                    **{k: f[k] for k in ("lag_events", "ack_age_s",
                                         "durable")},
                })

        ack = self.commit_ack_stats()
        checks["commit_ack"] = ack
        if ack["fast_burn"] > p.burn_threshold \
                and ack["slow_burn"] > p.burn_threshold:
            degradations.append({
                "reason": COMMIT_ACK_SLO_BURN,
                "detail": (
                    f"commit-ack latency is burning its "
                    f"{p.commit_ack_slo_s:.1f} s SLO budget at "
                    f"{ack['fast_burn']:.1f}x (fast window) / "
                    f"{ack['slow_burn']:.1f}x (slow window) the allowed "
                    f"rate — correlate with store-lock / fsync / "
                    f"replication attribution at /debug/contention"),
                "fast_burn": ack["fast_burn"],
                "slow_burn": ack["slow_burn"],
            })

        starvation = self.starvation_fn()
        checks["starvation"] = {"pools": starvation,
                                "threshold_s": p.starvation_age_s}
        for pool, stats in sorted(starvation.items()):
            if stats.get("oldest_age_s", 0.0) >= p.starvation_age_s:
                degradations.append({
                    "reason": JOB_STARVATION,
                    "pool": pool,
                    "detail": (
                        f"pool {pool}'s oldest queued job has waited "
                        f"{stats['oldest_age_s']:.0f} s (threshold "
                        f"{p.starvation_age_s:.0f} s); worst user "
                        f"{stats.get('worst_user', '?')} at "
                        f"{stats.get('worst_user_wait_s', 0.0):.0f} s"),
                    **{k: stats[k] for k in ("oldest_age_s", "oldest_job",
                                             "worst_user",
                                             "worst_user_wait_s")
                       if k in stats},
                })

        active = {d["reason"] for d in degradations}
        for reason in CONTENTION_REASONS:
            self._reason_gauge.set(1.0 if reason in active else 0.0,
                                   {"reason": reason})
        return degradations, checks
