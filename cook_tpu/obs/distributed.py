"""Cross-process request-flow observability for the mp runtime.

Everything PR 8/15 built — span rings, chrome-trace export, incident
bundles, fleet polls — is process-local.  The mp runtime
(cook_tpu/mp/) spreads one request across a front end, a coordinator
decision write, and N shard-group workers; this module is the glue
that makes that flow readable as ONE artifact:

  * the **header contract** — the front end stamps every forward and
    every `/rpc/*` call with `X-Cook-Txn-Id` (correlation, already the
    idempotency key) plus `X-Cook-Parent-Span` (the causal parent's
    span name), and workers answer with `X-Cook-Hop-Walls` carrying
    their server-side phase walls (`server`, `apply`, `fsync`,
    `replication_ack` seconds);
  * **merged traces** — `merge_process_traces` dedupes the per-process
    ring slices (workers answer `GET /debug/trace?txn_id=`) and
    `merged_chrome_trace` renders them with one pid track per process:
    front end = pid 0, the coordinator's 2PC decision lane = pid 1,
    worker group g = pid g + 2 — so Perfetto shows the true
    cross-process critical path;
  * **per-hop attribution** — `HopAttribution` folds the forward
    round-trip into front-end queue / RPC transport / worker apply /
    fsync / replication-ack reservoirs per group, exported as
    `mp.hop_seconds{hop,group}` and the `/debug/frontend` hop rows;
  * **federated incidents** — `add_mp_collectors` teaches the front
    end's IncidentRecorder to embed the 2PC decision-log tail, breaker
    states, and the route map, so a failover bundle answers "which
    hop, which group, which decision" from one artifact.

Spans carry a ring-only `process` tag (tracing._RING_ONLY_TAGS)
identifying the recording fleet member — in the in-process harness
(MpRuntime(inprocess=True)) every "process" shares one module-global
ring, so the tag, not the ring identity, is what routes a span to its
pid track.  That makes the merge identical for real multi-process and
in-process runs.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Callable, Optional

from cook_tpu.utils.metrics import global_registry

# ------------------------------------------------------- header contract

TXN_HEADER = "X-Cook-Txn-Id"
PARENT_SPAN_HEADER = "X-Cook-Parent-Span"
HOP_WALLS_HEADER = "X-Cook-Hop-Walls"

# process labels -> merged-trace pid tracks
PROCESS_FRONTEND = "frontend"
PROCESS_COORDINATOR = "coordinator"
PID_FRONTEND = 0
PID_COORDINATOR = 1
_PID_WORKER_BASE = 2


def worker_process_label(group: int) -> str:
    return f"worker-g{group}"


def pid_for_process(label: Optional[str]) -> Optional[int]:
    """front end = 0, coordinator decision lane = 1, worker group g =
    g + 2; None for labels the merge must assign dynamically."""
    if label == PROCESS_FRONTEND:
        return PID_FRONTEND
    if label == PROCESS_COORDINATOR:
        return PID_COORDINATOR
    if label and label.startswith("worker-g"):
        try:
            return int(label[len("worker-g"):]) + _PID_WORKER_BASE
        except ValueError:
            return None
    return None


def encode_hop_walls(walls: dict) -> str:
    """`{"apply": 0.0012, ...}` -> `apply=0.001200;...` — one flat
    header value (floats in seconds, 6 decimals keeps microseconds)."""
    return ";".join(f"{k}={float(v):.6f}" for k, v in sorted(walls.items()))


def parse_hop_walls(value: Optional[str]) -> dict[str, float]:
    """Tolerant inverse of `encode_hop_walls` — an unparseable pair is
    dropped, not raised: a malformed header must not fail a forward."""
    walls: dict[str, float] = {}
    for pair in (value or "").split(";"):
        name, sep, raw = pair.partition("=")
        if not sep:
            continue
        try:
            walls[name.strip()] = float(raw)
        except ValueError:
            continue
    return walls


# --------------------------------------------------- per-hop attribution

# the forward hops, in causal order; queue and transport are measured by
# the front end, the rest arrive in the worker's X-Cook-Hop-Walls header
HOPS = ("queue", "transport", "apply", "fsync", "replication_ack")

# sub-ms transport on loopback up to seconds under fsync stalls
_HOP_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0, float("inf"))

_RESERVOIR_CAP = 2048


class _HopReservoir:
    """Bounded sample ring with quantile reads (the front end's
    per-group latency reservoir pattern, kept local to avoid an
    obs -> mp import cycle)."""

    def __init__(self, cap: int = _RESERVOIR_CAP):
        self._samples: list[float] = []
        self._cap = cap
        self._next = 0
        self.count = 0

    def add(self, value: float) -> None:
        if len(self._samples) < self._cap:
            self._samples.append(value)
        else:
            self._samples[self._next] = value
            self._next = (self._next + 1) % self._cap
        self.count += 1

    def quantile(self, q: float) -> float:
        if not self._samples:
            return 0.0
        ordered = sorted(self._samples)
        idx = min(len(ordered) - 1, int(q * len(ordered)))
        return ordered[idx]


class HopAttribution:
    """Folds forward round-trips into per-(group, hop) reservoirs and
    the `mp.hop_seconds{hop,group}` histogram feeding tsdb history."""

    def __init__(self):
        self._lock = threading.Lock()
        self._reservoirs: dict[tuple[int, str], _HopReservoir] = {}
        self._hop_seconds = global_registry.histogram(
            "mp.hop_seconds",
            "per-hop split of front-end forward time (front-end queue, "
            "RPC transport, worker apply, fsync, replication-ack), "
            "labeled hop + shard group", buckets=_HOP_BUCKETS)

    def observe(self, group: int, hop: str, seconds: float) -> None:
        seconds = max(0.0, float(seconds))
        with self._lock:
            res = self._reservoirs.get((group, hop))
            if res is None:
                res = self._reservoirs[(group, hop)] = _HopReservoir()
            res.add(seconds)
        self._hop_seconds.observe(
            seconds, {"hop": hop, "group": str(group)})

    def attribute(self, group: int, *, total_s: float, queue_s: float,
                  walls: dict[str, float]) -> None:
        """One forward's split: `total_s` is the front end's round-trip
        wall, `queue_s` the arrival-to-forward-start wait, `walls` the
        worker's decoded X-Cook-Hop-Walls.  transport = round-trip
        minus the worker's total server wall (clamped at 0 — clock
        reads race by design, attribution must never go negative)."""
        self.observe(group, "queue", queue_s)
        server = walls.get("server")
        if server is not None:
            self.observe(group, "transport", max(0.0, total_s - server))
        for hop in ("apply", "fsync", "replication_ack"):
            if hop in walls:
                self.observe(group, hop, walls[hop])

    def snapshot(self, group: int) -> dict:
        """{hop: {p50_ms, p99_ms, count}} for one group's
        /debug/frontend row (only hops that have samples)."""
        with self._lock:
            pairs = [(hop, res) for (g, hop), res
                     in self._reservoirs.items() if g == group]
        return {hop: {"p50_ms": res.quantile(0.5) * 1000.0,
                      "p99_ms": res.quantile(0.99) * 1000.0,
                      "count": res.count}
                for hop, res in pairs}


# ------------------------------------------------------------ trace merge

_collections = global_registry.counter(
    "trace.federated_collections",
    "federated GET /debug/trace?txn_id= merges at the front end, per "
    "outcome (merged = every live group answered, partial = some "
    "group's slice was unreachable, empty = no spans matched)")


def merge_process_traces(sources: list[dict]) -> list[dict]:
    """Merge per-process ring slices into one span list.

    `sources` is `[{"process": label, "spans": [ring entries]}, ...]`.
    Each span's own ring-only `process` tag wins over the source label
    (the in-process harness shares ONE ring across every "process", so
    identical slices come back from every worker and only the tag says
    who recorded what); spans are deduped on (name, t, tid, duration)
    and returned oldest-first with a resolved top-level "process"."""
    seen: set[tuple] = set()
    merged: list[dict] = []
    for source in sources:
        label = source.get("process")
        for entry in source.get("spans") or []:
            tags = entry.get("tags") or {}
            key = (entry.get("name"), entry.get("t"), entry.get("tid"),
                   entry.get("duration_s"))
            if key in seen:
                continue
            seen.add(key)
            resolved = dict(entry)
            resolved["process"] = tags.get("process") or label or "?"
            merged.append(resolved)
    merged.sort(key=lambda e: (e.get("t", 0.0) - e.get("duration_s", 0.0)))
    return merged


def merged_chrome_trace(spans: list[dict]) -> dict:
    """Chrome Trace Event Format over merged spans: one pid per process
    (`pid_for_process`; labels the contract doesn't name get the next
    free pid), one tid lane per source thread inside each process."""
    events: list[dict] = []
    pids: dict[str, int] = {}
    used: set[int] = set()
    track_tids: dict[tuple, int] = {}

    def pid_of(label: str) -> int:
        pid = pids.get(label)
        if pid is None:
            pid = pid_for_process(label)
            if pid is None or pid in used:
                pid = max(used, default=_PID_WORKER_BASE) + 1
            pids[label] = pid
            used.add(pid)
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0, "args": {"name": label}})
        return pid

    def track(pid: int, name: str) -> int:
        key = (pid, name)
        tid = track_tids.get(key)
        if tid is None:
            tid = sum(1 for (p, _n) in track_tids if p == pid) + 1
            track_tids[key] = tid
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": name}})
        return tid

    for entry in spans:
        label = entry.get("process") or "?"
        pid = pid_of(label)
        tags = entry.get("tags") or {}
        args = {k: v for k, v in tags.items() if k != "process"}
        if entry.get("parent"):
            args["parent"] = entry["parent"]
        duration_us = entry.get("duration_s", 0.0) * 1e6
        start_us = entry.get("t", 0.0) * 1e6 - duration_us
        thread = entry.get("thread") or f"thread-{entry.get('tid', 0)}"
        base = {"name": entry.get("name", "?"), "cat": "span",
                "ts": start_us, "args": args, "pid": pid,
                "tid": track(pid, thread)}
        if duration_us > 0:
            base.update({"ph": "X", "dur": duration_us})
        else:
            base.update({"ph": "i", "s": "t"})
        events.append(base)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def note_collection(outcome: str) -> None:
    _collections.inc(1, {"outcome": outcome})


# -------------------------------------------------- federated mp incidents

def decision_log_tail(path: Optional[str], limit: int = 64) -> dict:
    """The newest `limit` 2PC decision records plus which txns are
    committed-but-not-done — the slice a federated incident bundle
    embeds so an abort storm or a mid-commit failover is legible
    without shelling into the coordinator's data dir."""
    records: list[dict] = []
    open_txns: dict[str, float] = {}
    if path and os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                except ValueError:
                    break  # torn tail — same rule as DecisionLog
                records.append(record)
                txn_id = record.get("txn_id")
                if record.get("decision") == "commit":
                    open_txns[txn_id] = record.get("t", 0.0)
                elif record.get("decision") == "done":
                    open_txns.pop(txn_id, None)
    return {"path": path, "records": records[-limit:],
            "outstanding": sorted(open_txns)}


def add_mp_collectors(recorder, *, decision_log_path: Optional[str],
                      breakers_fn: Callable[[], dict],
                      route_map_fn: Callable[[], dict]):
    """Register the mp-runtime evidence sources on an IncidentRecorder:
    the decision-log tail, breaker states, and the route map at capture
    time.  One registration site (router.py and debug_smoke both call
    it) so the federated bundle schema cannot drift."""
    recorder.add_collector(
        "decision_log", lambda: decision_log_tail(decision_log_path))
    recorder.add_collector("breakers", breakers_fn)
    recorder.add_collector("route_map", route_map_fn)
    return recorder


# -------------------------------------------------------- timeline stitch

def stitch_twopc_events(timeline: dict, record: dict,
                        done_t: Optional[float]) -> dict:
    """Fold a 2PC commit decision into a worker-rendered job timeline:
    the cross-group hop the owning worker cannot see.  Events are
    re-sorted by t_ms (stable — the worker's causal tie-breaks
    survive); the raw decision summary also lands under "twopc"."""
    groups = sorted(int(g) for g in (record.get("groups") or {}))
    txn_id = record.get("txn_id")
    decided_t = record.get("t")
    events = list(timeline.get("events") or [])
    prepare_s = record.get("prepare_s") or {}
    if decided_t is not None:
        events.append({
            "t_ms": int(decided_t * 1000),
            "kind": "2pc-commit-decision", "txn_id": txn_id,
            "groups": groups,
            "prepare_ms": {g: round(float(s) * 1000.0, 3)
                           for g, s in prepare_s.items()}})
    if done_t is not None:
        events.append({"t_ms": int(done_t * 1000), "kind": "2pc-done",
                       "txn_id": txn_id, "groups": groups})
    events.sort(key=lambda e: e.get("t_ms", 0))
    stitched = dict(timeline)
    stitched["events"] = events
    stitched["twopc"] = {
        "txn_id": txn_id, "groups": groups, "op": record.get("op"),
        "decided_t": decided_t, "done_t": done_t,
        "prepare_s": prepare_s}
    return stitched


__all__ = [
    "TXN_HEADER", "PARENT_SPAN_HEADER", "HOP_WALLS_HEADER",
    "PROCESS_FRONTEND", "PROCESS_COORDINATOR", "PID_FRONTEND",
    "PID_COORDINATOR", "worker_process_label", "pid_for_process",
    "encode_hop_walls", "parse_hop_walls", "HOPS", "HopAttribution",
    "merge_process_traces", "merged_chrome_trace", "note_collection",
    "decision_log_tail", "add_mp_collectors", "stitch_twopc_events",
]
