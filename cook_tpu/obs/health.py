"""Health verdict: fold device telemetry into one machine-readable answer.

`GET /debug/health` serves this verdict.  Four degradation reasons, each
backed by its own detector:

  * `recompile-storm`        — CompileObservatory sliding-window flag
                               (padding-bucket churn is recompiling the
                               solver faster than the jit cache amortizes).
  * `quality-drift`          — QualityMonitor rolling-baseline anomaly or
                               parity-floor breach on sampled CPU shadow
                               solves.
  * `solve-latency-regression` — per-pool match-solve seconds risen out of
                               the rolling median/MAD band.
  * `device-oom-risk`        — device allocator utilization above the
                               risk threshold (unobservable on CPU; the
                               verdict says so instead of guessing).

The verdict is advisory — the scheduler keeps scheduling — but it is the
machine-readable hook for operators and autoscalers: production DL-cluster
schedulers treat exactly this telemetry as load-bearing for capacity and
preemption decisions (Aryl, arXiv:2202.07896; topology-aware preemptive
scheduling, arXiv:2411.11560)."""
from __future__ import annotations

import time
from typing import Callable, Optional

from cook_tpu.obs.device_monitor import device_memory_stats
from cook_tpu.utils.metrics import global_registry

RECOMPILE_STORM = "recompile-storm"
QUALITY_DRIFT = "quality-drift"
SOLVE_LATENCY_REGRESSION = "solve-latency-regression"
DEVICE_OOM_RISK = "device-oom-risk"
# the matcher degraded the pool to the CPU reference solver after a
# device solve error or a latency-guard breach (scheduler/matcher device
# fallback — docs/resilience.md reaction (c)); clears when the periodic
# device probe succeeds
DEVICE_DEGRADED = "device-degraded"

DEGRADATION_REASONS = (RECOMPILE_STORM, QUALITY_DRIFT,
                       SOLVE_LATENCY_REGRESSION, DEVICE_OOM_RISK,
                       DEVICE_DEGRADED)


class HealthMonitor:
    """Stateless folder over the telemetry components (they own the
    rolling state); one instance per DeviceTelemetry."""

    def __init__(self, telemetry, oom_threshold: float = 0.9,
                 memory_stats_fn: Optional[Callable] = None):
        self.telemetry = telemetry
        self.oom_threshold = oom_threshold
        self.memory_stats_fn = memory_stats_fn or device_memory_stats
        self._degraded_gauge = global_registry.gauge(
            "obs.health.degraded",
            "1 while /debug/health reports any degradation reason")
        self._reason_gauge = global_registry.gauge(
            "obs.health.reason_active",
            "1 while the labeled degradation reason is active")

    def verdict(self) -> dict:
        degradations: list[dict] = []

        storms = self.telemetry.observatory.storming_ops()
        for op, evidence in sorted(storms.items()):
            degradations.append({
                "reason": RECOMPILE_STORM, "op": op,
                "detail": (
                    f"{evidence['compiles_in_window']} new XLA programs in "
                    f"the last {evidence['window']} {op} solves "
                    f"(threshold {evidence['threshold']}) — padded-shape "
                    f"churn; check bucket sizing"),
                **evidence,
            })

        drifting = self.telemetry.quality.drifting_pools()
        for pool, evidence in sorted(drifting.items()):
            degradations.append({
                "reason": QUALITY_DRIFT, "pool": pool,
                "detail": (
                    f"pool {pool} packing efficiency "
                    f"{evidence['efficiency']:.4f} vs CPU reference "
                    f"({evidence['kind']}) — re-run tools/tpu_sweep.py or "
                    f"lower chunk"),
                **evidence,
            })

        latency = self.telemetry.latency_regressions()
        for pool, evidence in sorted(latency.items()):
            degradations.append({
                "reason": SOLVE_LATENCY_REGRESSION, "pool": pool,
                "detail": (
                    f"pool {pool} match-solve recent median "
                    f"{evidence['recent'] * 1000:.1f} ms vs baseline "
                    f"{evidence['baseline'] * 1000:.1f} ms"),
                **evidence,
            })

        fallbacks = getattr(self.telemetry, "device_fallbacks",
                            lambda: {})()
        for pool, evidence in sorted(fallbacks.items()):
            degradations.append({
                "reason": DEVICE_DEGRADED, "pool": pool,
                "detail": (
                    f"pool {pool} match solves degraded to the CPU "
                    f"reference ({evidence.get('cause', '?')}, "
                    f"{evidence.get('cycles', 0)} cycles so far, "
                    f"{evidence.get('cycles_left', 0)} before the next "
                    f"device probe) — placements continue; investigate "
                    f"the device"),
                **evidence,
            })

        memory = self.memory_stats_fn()
        if memory is not None and memory["utilization"] >= self.oom_threshold:
            degradations.append({
                "reason": DEVICE_OOM_RISK,
                "detail": (
                    f"device memory {memory['utilization']:.0%} of "
                    f"{memory['bytes_limit'] / 2**30:.1f} GiB "
                    f"(threshold {self.oom_threshold:.0%})"),
                **memory,
            })

        healthy = not degradations
        self._degraded_gauge.set(0.0 if healthy else 1.0)
        active = {d["reason"] for d in degradations}
        for reason in DEGRADATION_REASONS:
            self._reason_gauge.set(1.0 if reason in active else 0.0,
                                   {"reason": reason})
        return {
            "healthy": healthy,
            "status": "ok" if healthy else "degraded",
            "degradations": degradations,
            "reasons": sorted(active),
            "checks": {
                "compile": self.telemetry.observatory.stats(),
                "quality": self.telemetry.quality.stats(),
                "solve_latency": self.telemetry.latency_stats(),
                "device_fallback": fallbacks,
                "device_memory": (memory if memory is not None
                                  else {"observable": False}),
            },
            "wall_time": time.time(),
        }
