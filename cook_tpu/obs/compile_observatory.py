"""Compile observatory: JIT-compilation accounting per (op, shape, backend).

XLA compiles one program per (shapes, static-arguments) combination, so
every padded problem shape the scheduler hands a kernel is a potential
multi-second compile.  `ops/common.bucket_size` exists to bound that set —
but nothing VERIFIED it: a queue oscillating across a bucket boundary, a
sweep-promoted chunk that no longer divides the padded size, or a pool
whose node count grows through fresh power-of-two buckets all show up
only as mysterious slow cycles.

The observatory mirrors the jit-cache keying host-side: every device
solve reports `(op, shape_signature, backend)`; a first-seen key is a
compilation (the process-lifetime jit cache holds every program it ever
built, exactly like this set).  Compile counts are exported per
(op, shape, backend) at `/metrics`, and a sliding window per op flags a
**recompile storm** — `storm_threshold`+ new programs within the last
`window` solves — the signature of padding-bucket churn.

Label cardinality: shapes are padded-bucket strings ("131072x16384"), so
the label set is bounded by the bucket lattice, not the workload.
"""
from __future__ import annotations

import collections
import threading
from typing import Iterable, Optional

from cook_tpu.utils.metrics import global_registry


def shape_signature(dims: Iterable) -> str:
    """Canonical shape-signature string for a padded solve, e.g. a
    131072-job x 16384-node match renders "131072x16384"."""
    return "x".join(str(int(d)) for d in dims)


class CompileObservatory:
    """Process-lifetime compile accounting + sliding-window storm flag.

    Thread-safe: match cycles, rank triggers, and the rebalancer all
    report solves, potentially from different threads.
    """

    def __init__(self, window: int = 32, storm_threshold: int = 4,
                 warmup_solves: Optional[int] = None):
        # a storm = >= storm_threshold first-seen (shape, backend) keys
        # within the op's last `window` solves.  The op's first
        # `warmup_solves` solves (default: one full window) never feed
        # the storm trigger: a fresh process compiles every shape once
        # by construction, and paging "recompile-storm" on every deploy/
        # failover would train operators to ignore the real signal.
        # Compile COUNTS still include warmup (the accounting is honest);
        # only the storm edge gets the grace.
        self.window = window
        self.storm_threshold = storm_threshold
        self.warmup_solves = window if warmup_solves is None else \
            warmup_solves
        self._seen: set[tuple[str, str, str]] = set()
        self._recent: dict[str, collections.deque] = {}
        self._solve_totals: dict[str, int] = {}
        self._storming: dict[str, bool] = {}
        # roofline attribution (obs/data_plane.py): per-program FLOPs +
        # bytes accessed from compiled.cost_analysis(), plus the last
        # observed non-overlapped solve wall — together they turn the
        # CPU-vs-device gap into a number per program
        self._costs: dict[tuple[str, str, str], dict] = {}
        self._last_seconds: dict[tuple[str, str, str], float] = {}
        self._lock = threading.Lock()
        self._compile_counter = global_registry.counter(
            "obs.compile.count",
            "JIT compilations (first-seen solve keys) per op/shape/backend")
        self._solve_counter = global_registry.counter(
            "obs.solve.count", "device solves observed per op/backend")
        self._storm_counter = global_registry.counter(
            "obs.compile.storms",
            "recompile-storm onsets (window compile count crossed the "
            "threshold) per op")
        self._storm_gauge = global_registry.gauge(
            "obs.compile.storm_active",
            "1 while the op's recent-solve window holds a recompile storm")
        self._programs_gauge = global_registry.gauge(
            "obs.compile.programs",
            "distinct compiled programs (op-wide jit cache size)")

    def observe_solve(self, op: str, shape, backend: str, *,
                      seconds: float = None) -> bool:
        """Report one device solve; returns True when this (op, shape,
        backend) key was first seen — i.e. the solve paid a compile.
        `seconds` (optional, warm non-overlapped walls only) feeds the
        roofline join: cost_stats() divides the program's FLOPs by the
        last observed wall to report achieved throughput."""
        sig = shape if isinstance(shape, str) else shape_signature(shape)
        key = (op, sig, backend)
        with self._lock:
            compiled = key not in self._seen
            if compiled:
                self._seen.add(key)
            elif seconds is not None and seconds > 0:
                # warm walls only: a compile-paying run's wall is XLA
                # time, not execution — it would poison the achieved-
                # throughput join exactly like the latency baseline
                self._last_seconds[key] = seconds
            total = self._solve_totals.get(op, 0) + 1
            self._solve_totals[op] = total
            recent = self._recent.setdefault(
                op, collections.deque(maxlen=self.window))
            # warmup compiles are expected and excluded from the storm
            # window (they still hit the compile counters below)
            recent.append(compiled and total > self.warmup_solves)
            storming = sum(recent) >= self.storm_threshold
            storm_onset = storming and not self._storming.get(op, False)
            self._storming[op] = storming
            programs = sum(1 for k in self._seen if k[0] == op)
        self._solve_counter.inc(labels={"op": op, "backend": backend})
        if compiled:
            self._compile_counter.inc(
                labels={"op": op, "shape": sig, "backend": backend})
        if storm_onset:
            self._storm_counter.inc(labels={"op": op})
        self._storm_gauge.set(1.0 if storming else 0.0, {"op": op})
        self._programs_gauge.set(programs, {"op": op})
        return compiled

    def storming_ops(self) -> dict[str, dict]:
        """Ops whose recent-solve window currently holds a storm, with
        the window evidence (for the health verdict's detail)."""
        with self._lock:
            out = {}
            for op, storming in self._storming.items():
                if not storming:
                    continue
                recent = self._recent.get(op, ())
                out[op] = {
                    "window": len(recent),
                    "compiles_in_window": sum(recent),
                    "threshold": self.storm_threshold,
                }
            return out

    # ------------------------------------------------- roofline cost cache

    def observe_cost(self, op: str, shape, backend: str,
                     cost: dict) -> None:
        """Cache one program's cost_analysis() result ({"flops",
        "bytes_accessed"}), keyed exactly like the compile accounting."""
        sig = shape if isinstance(shape, str) else shape_signature(shape)
        with self._lock:
            self._costs[(op, sig, backend)] = dict(cost)

    def cost(self, op: str, shape, backend: str):
        sig = shape if isinstance(shape, str) else shape_signature(shape)
        with self._lock:
            return self._costs.get((op, sig, backend))

    def cost_stats(self) -> list[dict]:
        """Roofline rows for `/debug/device`: per-program FLOPs, bytes
        accessed, arithmetic intensity, and — when a warm solve wall has
        been observed — achieved GFLOP/s, so the CPU-vs-device gap is a
        number per program."""
        with self._lock:
            rows = []
            for (op, sig, backend), cost in sorted(self._costs.items()):
                if cost.get("unavailable"):
                    # negative-cache sentinel (the backend reported no
                    # cost table) — cached so probes don't re-lower, but
                    # not a roofline row
                    continue
                flops = cost.get("flops", 0.0)
                nbytes = cost.get("bytes_accessed", 0.0)
                row = {
                    "op": op, "shape": sig, "backend": backend,
                    "flops": flops, "bytes_accessed": nbytes,
                    "arithmetic_intensity": (flops / nbytes
                                             if nbytes > 0 else None),
                }
                seconds = self._last_seconds.get((op, sig, backend))
                if seconds:
                    row["last_solve_s"] = seconds
                    row["achieved_gflops"] = flops / seconds / 1e9
                rows.append(row)
            return rows

    def stats(self) -> dict:
        """Snapshot for the health verdict: per-op program counts and
        window compile pressure."""
        with self._lock:
            per_op: dict[str, dict] = {}
            for op, recent in self._recent.items():
                per_op[op] = {
                    "programs": sum(1 for k in self._seen if k[0] == op),
                    "solves_in_window": len(recent),
                    "compiles_in_window": sum(recent),
                    "storming": self._storming.get(op, False),
                }
            return per_op
