"""Fairness observatory: per-user DRU trajectories, preemption ledger,
wasted-work accounting.

Cook's reason to exist is DRU fair-share ranking plus rebalancer
preemption, and until now the fairness engine was the one subsystem
without an observatory: no share-vs-usage view per user, no record of
who preempted whom, no measure of work destroyed by a kill.  This
module closes that gap with three instruments:

  * a **DRU trajectory** sampler — `observe_rank()` runs at rank-cycle
    time with the `RankedQueue` in hand and records, per (pool, user):
    share, quota, running dominant-resource usage, running DRU score
    (dominant usage over share), best queued DRU, and queued depth.
    The headline numbers are exported as `fairness.user.*` gauges so
    the PR 15 tsdb samples them into durable history (`cs history
    fairness.user.dru` sparklines a user's drift); label churn is
    bounded both here (top-`max_users_per_pool` by DRU, departed users
    retracted) and in the tsdb (series TTL pruning).

  * a **preemption ledger** — `record_decisions()` is fed by the
    scheduler for every rebalancer decision it transacts: preemptor
    job/user, per-victim task/user/DRU-at-decision, resources freed,
    and **wasted-work seconds** (the victim instance's runtime at
    kill).  Entries live in a bounded ring; rollups accumulate per
    pool and per user.  Wasted work is split `fairness` (rebalancer
    preemptions — deliberate, fair-share-driven) vs `mea-culpa`
    (other scheduler-fault kills, e.g. container-preempted, reported
    through `note_kill()`).  The per-pool **fragmentation** stat is
    block-aware: each ledger entry carries the topology block of the
    host it freed (stamped by the scheduler from the same block
    decomposition the hierarchical matcher solves), `contiguous_share`
    is the largest single BLOCK's freed total over everything freed in
    the ledger window, and `fragmentation` is its complement — freeing
    three hosts in one block beats freeing three across the fleet,
    because only the former admits a gang.  Topology-aware victim
    selection (scheduler/gang.py) pushes it down.

  * **Jain fairness index** + drift detection — each rank cycle folds
    per-user running DRU into Jain's index `(Σx)²/(n·Σx²)` and feeds a
    `RollingBaseline` per pool; a sustained drop (recent median below
    the MAD band) raises the `fairness-drift` health reason, which the
    REST health verdict merges and the incident recorder snapshots
    (the `fairness` collector lands trajectories + ledger in every
    bundle).

Thread-safety: rank/rebalance cycles run on the scheduler thread but
REST snapshots arrive from aiohttp executors, so all mutation and
reads go through one lock (same discipline as ContentionObservatory).
"""
from __future__ import annotations

import collections
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..utils.metrics import global_registry

# Health reason raised on a sustained Jain-index drop.  Deliberately NOT
# in health.DEGRADATION_REASONS: HealthMonitor.verdict() zeroes the
# reason_active gauge for every reason in that tuple on each device-side
# verdict, and fairness is evaluated on a different path (the REST
# health merge) — the observatory owns its own gauge lifecycle.
FAIRNESS_DRIFT = "fairness-drift"

_INF = float("inf")


@dataclass
class FairnessConfig:
    """Bounds and drift knobs for the observatory."""

    ledger_capacity: int = 512       # preemption-ledger ring size
    max_users_per_pool: int = 64     # trajectory gauge/label cap per pool
    max_rollup_users: int = 256      # per-user rollup cap per pool
    # Jain-index drift baseline (RollingBaseline knobs).  A healthy
    # pool's index hovers near a stable level; sustained relative drops
    # past 10% of baseline flag drift.
    baseline_window: int = 64
    baseline_recent: int = 8
    baseline_min_samples: int = 12
    baseline_k_mad: float = 6.0
    baseline_rel_floor: float = 0.10


def jain_index(values) -> float:
    """Jain's fairness index (Σx)²/(n·Σx²) over non-negative samples.

    1.0 = perfectly even allocation, →1/n as one user dominates.  An
    empty or all-zero population is vacuously fair (1.0).
    """
    xs = [float(v) for v in values if v > 0.0]
    if not xs:
        return 1.0
    total = sum(xs)
    sq = sum(x * x for x in xs)
    return (total * total) / (len(xs) * sq)


def _res_dict(mem: float = 0.0, cpus: float = 0.0, gpus: float = 0.0) -> dict:
    return {"mem": round(float(mem), 3), "cpus": round(float(cpus), 3),
            "gpus": round(float(gpus), 3)}


def _finite(v: float) -> Optional[float]:
    return None if v == _INF else v


@dataclass
class _PoolRollup:
    """Accumulated preemption accounting for one pool."""

    preemptions: int = 0          # rebalancer decisions transacted
    tasks_preempted: int = 0
    wasted_fairness_s: float = 0.0
    wasted_mea_culpa_s: float = 0.0
    freed_mem: float = 0.0
    freed_cpus: float = 0.0
    freed_gpus: float = 0.0
    # user -> {"victim_tasks", "victim_wasted_s", "preemptions_initiated"}
    by_user: dict = field(default_factory=dict)
    users_truncated: int = 0

    def user_slot(self, user: str, cap: int) -> dict:
        slot = self.by_user.get(user)
        if slot is None:
            if len(self.by_user) >= cap:
                self.users_truncated += 1
                user = "(other)"
                slot = self.by_user.get(user)
                if slot is not None:
                    return slot
            slot = {"victim_tasks": 0, "victim_wasted_s": 0.0,
                    "preemptions_initiated": 0}
            self.by_user[user] = slot
        return slot

    def to_json(self) -> dict:
        return {
            "preemptions": self.preemptions,
            "tasks_preempted": self.tasks_preempted,
            "wasted_s": {
                "fairness": round(self.wasted_fairness_s, 3),
                "mea_culpa": round(self.wasted_mea_culpa_s, 3),
            },
            "freed": _res_dict(self.freed_mem, self.freed_cpus,
                               self.freed_gpus),
            "by_user": {u: dict(v) for u, v in self.by_user.items()},
            "users_truncated": self.users_truncated,
        }


class FairnessObservatory:
    """Per-user DRU trajectories + preemption ledger + drift detection.

    Owned by the Scheduler (one per process); scheduler-less REST nodes
    (mp shard-group workers) stand up their own so `/debug/fairness`
    scatter-merges cleanly across the fleet.
    """

    def __init__(self, config: Optional[FairnessConfig] = None,
                 clock: Optional[Callable[[], float]] = None):
        from .baseline import RollingBaseline

        self.config = config or FairnessConfig()
        self.clock = clock or (lambda: 0.0)
        self._lock = threading.Lock()
        self._ledger: collections.deque = collections.deque(
            maxlen=self.config.ledger_capacity)
        self._rollups: dict[str, _PoolRollup] = {}
        # pool -> {user: trajectory point}; refreshed whole each rank
        self._trajectories: dict[str, dict[str, dict]] = {}
        self._traj_truncated: dict[str, int] = {}
        self._jain: dict[str, float] = {}
        self._baseline_cls = RollingBaseline
        self._baselines: dict[str, "RollingBaseline"] = {}
        # pool -> set of users with exported per-user gauges (retraction
        # bookkeeping, same idiom as monitor._exported_user_waits)
        self._exported_users: dict[str, set] = {}
        self._drift_active: bool = False

    # ------------------------------------------------------- trajectories

    def observe_rank(self, pool: str, queue, store) -> None:
        """Sample per-user DRU trajectories from one pool's rank cycle.

        `queue` is the RankedQueue just produced (jobs in fair-share
        order + per-job queue DRU); `store` supplies shares, quotas and
        running usage.  Runs on the scheduler thread once per rank
        cycle — cheap enough to always be on.
        """
        cfg = self.config
        usage = store.user_usage(pool)
        queued: dict[str, int] = {}
        queue_dru: dict[str, float] = {}
        for job in queue.jobs:
            queued[job.user] = queued.get(job.user, 0) + 1
            d = queue.dru.get(job.uuid)
            if d is not None:
                prev = queue_dru.get(job.user)
                if prev is None or d < prev:
                    queue_dru[job.user] = float(d)

        users = set(usage) | set(queued)
        points: dict[str, dict] = {}
        for user in users:
            share = store.get_share(user, pool)
            quota = store.get_quota(user, pool)
            used = usage.get(user)
            dru = 0.0
            if used is not None:
                dru = max(
                    used.mem / share.mem if share.mem > 0 else 0.0,
                    used.cpus / share.cpus if share.cpus > 0 else 0.0,
                    used.gpus / share.gpus if share.gpus > 0 else 0.0,
                )
            points[user] = {
                "share": {"mem": _finite(share.mem),
                          "cpus": _finite(share.cpus),
                          "gpus": _finite(share.gpus)},
                "quota": {"mem": _finite(quota.resources.mem),
                          "cpus": _finite(quota.resources.cpus),
                          "count": (quota.count if quota.count < 2**31
                                    else None)},
                "usage": (_res_dict(used.mem, used.cpus, used.gpus)
                          if used is not None else _res_dict()),
                "dru": round(dru, 6),
                "queue_dru": (round(queue_dru[user], 6)
                              if user in queue_dru else None),
                "queued": queued.get(user, 0),
            }

        # Bound the kept set: top users by (running DRU, queued depth).
        truncated = 0
        if len(points) > cfg.max_users_per_pool:
            keep = sorted(points,
                          key=lambda u: (points[u]["dru"],
                                         points[u]["queued"]),
                          reverse=True)[:cfg.max_users_per_pool]
            truncated = len(points) - len(keep)
            points = {u: points[u] for u in keep}

        jain = jain_index(p["dru"] for p in points.values())

        dru_gauge = global_registry.gauge(
            "fairness.user.dru",
            "per-user running dominant-resource usage over share")
        queued_gauge = global_registry.gauge(
            "fairness.user.queued",
            "per-user pending jobs in the ranked queue")
        with self._lock:
            for user, point in points.items():
                labels = {"pool": pool, "user": user}
                dru_gauge.set(point["dru"], labels)
                queued_gauge.set(float(point["queued"]), labels)
            for user in self._exported_users.get(pool, set()) - set(points):
                dru_gauge.remove({"pool": pool, "user": user})
                queued_gauge.remove({"pool": pool, "user": user})
            self._exported_users[pool] = set(points)
            self._trajectories[pool] = points
            self._traj_truncated[pool] = truncated
            self._jain[pool] = jain
            baseline = self._baselines.get(pool)
            if baseline is None:
                baseline = self._baseline_cls(
                    window=cfg.baseline_window, recent=cfg.baseline_recent,
                    min_samples=cfg.baseline_min_samples,
                    k_mad=cfg.baseline_k_mad,
                    rel_floor=cfg.baseline_rel_floor)
                self._baselines[pool] = baseline
            baseline.add(jain)
        global_registry.gauge(
            "fairness.jain_index",
            "Jain fairness index over per-user running DRU").set(
                jain, {"pool": pool})

    # ------------------------------------------------------------- ledger

    def record_decisions(self, pool: str, entries: list[dict]) -> dict:
        """Append transacted rebalancer decisions to the ledger.

        Each entry: {t_ms, preemptor_job, preemptor_user, hostname,
        min_preempted_dru, victims: [{task_id, user, dru, wasted_s,
        mem, cpus, gpus}], freed: {mem, cpus, gpus}, wasted_s}.
        Returns this cycle's rollup (for CycleRecord.fairness).
        """
        cap = self.config.max_rollup_users
        cycle_tasks = 0
        cycle_wasted = 0.0
        with self._lock:
            rollup = self._rollups.setdefault(pool, _PoolRollup())
            for entry in entries:
                victims = entry.get("victims", [])
                wasted = sum(v.get("wasted_s", 0.0) for v in victims)
                entry = dict(entry, pool=pool, kind="fairness",
                             wasted_s=round(wasted, 3))
                self._ledger.append(entry)
                rollup.preemptions += 1
                rollup.tasks_preempted += len(victims)
                rollup.wasted_fairness_s += wasted
                freed = entry.get("freed", {})
                rollup.freed_mem += freed.get("mem", 0.0)
                rollup.freed_cpus += freed.get("cpus", 0.0)
                rollup.freed_gpus += freed.get("gpus", 0.0)
                slot = rollup.user_slot(entry.get("preemptor_user", ""), cap)
                slot["preemptions_initiated"] += 1
                for victim in victims:
                    vslot = rollup.user_slot(victim.get("user", ""), cap)
                    vslot["victim_tasks"] += 1
                    vslot["victim_wasted_s"] = round(
                        vslot["victim_wasted_s"] + victim.get("wasted_s", 0.0),
                        3)
                cycle_tasks += len(victims)
                cycle_wasted += wasted
            jain = self._jain.get(pool)
        if entries:
            global_registry.counter(
                "fairness.preemptions",
                "rebalancer preemption decisions transacted").inc(
                    len(entries), {"pool": pool})
            global_registry.counter(
                "fairness.preempted_tasks",
                "victim tasks killed by rebalancer preemption").inc(
                    cycle_tasks, {"pool": pool})
            global_registry.counter(
                "fairness.wasted_work_seconds",
                "victim instance runtime destroyed at kill, by kind").inc(
                    cycle_wasted, {"pool": pool, "kind": "fairness"})
            frag = self._fragmentation(pool)
            global_registry.gauge(
                "fairness.fragmentation",
                "1 - largest within-one-topology-block freed capacity "
                "over total freed (ledger window)").set(
                    frag["fragmentation"], {"pool": pool})
        return {
            "preemptions": len(entries),
            "tasks_preempted": cycle_tasks,
            "wasted_s": round(cycle_wasted, 3),
            "jain_index": jain,
        }

    def note_kill(self, pool: str, user: str, task_id: str,
                  wasted_s: float, reason: str = "") -> None:
        """Account a non-rebalancer mea-culpa kill (e.g. the backing
        cluster preempted the container).  The runtime destroyed lands
        in the `mea_culpa` wasted-work bucket; no ledger entry — there
        is no preemptor, and the instance event stream already records
        the kill itself.
        """
        with self._lock:
            rollup = self._rollups.setdefault(pool, _PoolRollup())
            rollup.wasted_mea_culpa_s += wasted_s
            slot = rollup.user_slot(user, self.config.max_rollup_users)
            slot["victim_wasted_s"] = round(
                slot["victim_wasted_s"] + wasted_s, 3)
        global_registry.counter(
            "fairness.wasted_work_seconds",
            "victim instance runtime destroyed at kill, by kind").inc(
                wasted_s, {"pool": pool, "kind": "mea-culpa"})

    def victim_detail(self, task_id: str) -> Optional[dict]:
        """Ledger lookup for one victim task (newest entry wins) — the
        timeline's preemption-detail source."""
        with self._lock:
            for entry in reversed(self._ledger):
                for victim in entry.get("victims", ()):
                    if victim.get("task_id") == task_id:
                        return {
                            "preemptor_user": entry.get("preemptor_user", ""),
                            "preemptor_job": entry.get("preemptor_job", ""),
                            "dru_at_decision": victim.get("dru"),
                            "runtime_lost_s": victim.get("wasted_s"),
                            "t_ms": entry.get("t_ms"),
                        }
        return None

    def _fragmentation(self, pool: str) -> dict:
        """Block-aware contiguous-capacity share of freed memory over the
        ledger window: decisions carry the topology block their host
        belongs to (stamped by Scheduler.rebalance_cycle), freed memory
        accumulates per block, and `contiguous_share` is the LARGEST
        single block's freed total over everything freed — capacity
        returned scattered across blocks scores fragmented even when each
        individual kill freed a big host, because no gang can use it
        whole.  Entries without a block stamp (older ledgers, recovery)
        fall back to per-decision chunks.  Caller holds no lock (reads
        the deque snapshot-style; appends are the only mutation and
        deques are safe to iterate under the GIL via list())."""
        per_block: dict = {}
        total = 0.0
        n = 0
        for entry in list(self._ledger):
            if entry.get("pool") != pool or entry.get("kind") != "fairness":
                continue
            freed = entry.get("freed", {}).get("mem", 0.0)
            total += freed
            n += 1
            block = entry.get("block")
            key = (("block", block) if isinstance(block, int) and block >= 0
                   else ("entry", n))
            per_block[key] = per_block.get(key, 0.0) + freed
        best = max(per_block.values(), default=0.0)
        share = best / total if total > 0 else 1.0
        return {"contiguous_share": round(share, 4),
                "fragmentation": round(1.0 - share, 4),
                "decisions": n,
                "blocks": sum(1 for k in per_block if k[0] == "block")}

    # ----------------------------------------------------------- recovery

    def recover(self, store) -> int:
        """Rebuild wasted-work rollups from the store after failover.

        The ledger itself is in-memory state lost with the leader, but
        terminal instances carry reason codes, so the durable journal is
        enough to restore the rollup totals (preemptor attribution is
        gone — recovered entries count victims only).  Returns the
        number of preempted instances replayed.
        """
        from ..models.reasons import REASONS_BY_CODE

        replayed = 0
        try:
            jobs = list(store.jobs.values())
        except AttributeError:
            return 0
        cap = self.config.max_rollup_users
        with self._lock:
            for job in jobs:
                for inst in store.job_instances(job.uuid):
                    if not inst.status.terminal or inst.reason_code is None:
                        continue
                    reason = REASONS_BY_CODE.get(inst.reason_code)
                    if reason is None or not reason.mea_culpa:
                        continue
                    wasted = 0.0
                    # start_time_ms is clock-stamped at create (0 is a
                    # real start under a virtual clock); end guards the
                    # never-terminal edge only
                    if inst.end_time_ms:
                        wasted = max(
                            0.0,
                            (inst.end_time_ms - inst.start_time_ms) / 1000.0)
                    rollup = self._rollups.setdefault(job.pool, _PoolRollup())
                    if reason.name == "preempted-by-rebalancer":
                        rollup.tasks_preempted += 1
                        rollup.wasted_fairness_s += wasted
                    else:
                        rollup.wasted_mea_culpa_s += wasted
                    slot = rollup.user_slot(job.user, cap)
                    slot["victim_tasks"] += 1
                    slot["victim_wasted_s"] = round(
                        slot["victim_wasted_s"] + wasted, 3)
                    replayed += 1
        return replayed

    # -------------------------------------------------------------- drift

    def health_degradations(self) -> list[dict]:
        """Per-pool `fairness-drift` degradations (sustained Jain-index
        drop below the rolling baseline band).  Also owns the
        `obs.health.reason_active{reason="fairness-drift"}` gauge.
        """
        out = []
        with self._lock:
            baselines = dict(self._baselines)
        for pool, baseline in sorted(baselines.items()):
            snap = baseline.anomaly_low()
            if snap is not None:
                out.append({
                    "reason": FAIRNESS_DRIFT,
                    "pool": pool,
                    "detail": (
                        f"jain index {snap['recent']:.3f} sustained below "
                        f"baseline {snap['baseline']:.3f} "
                        f"(band {snap['band']:.3f})"),
                    **{k: snap[k] for k in
                       ("baseline", "recent", "deviation", "n")},
                })
        active = bool(out)
        if active or self._drift_active:
            global_registry.gauge(
                "obs.health.reason_active",
                "1 while a degradation reason is firing").set(
                    1.0 if active else 0.0, {"reason": FAIRNESS_DRIFT})
        self._drift_active = active
        return out

    def health_checks(self) -> dict:
        """Per-pool Jain index + baseline snapshot for the health
        verdict's `checks.fairness` block."""
        with self._lock:
            jain = dict(self._jain)
            baselines = dict(self._baselines)
        return {
            pool: {
                "jain_index": round(jain.get(pool, 1.0), 4),
                "baseline": baselines[pool].snapshot()
                if pool in baselines else None,
            }
            for pool in sorted(set(jain) | set(baselines))
        }

    # ----------------------------------------------------------- surfaces

    def snapshot(self, pool: Optional[str] = None,
                 ledger_limit: int = 50) -> dict:
        """The `/debug/fairness` body.  Shape is mp-scatter-merge-safe:
        everything lives under per-pool keys (pools are group-owned and
        disjoint across shard groups, so the front end's dict-union
        merge composes bodies without summing anything)."""
        with self._lock:
            pools = sorted(set(self._trajectories) | set(self._rollups)
                           | set(self._jain))
            if pool is not None:
                pools = [p for p in pools if p == pool]
            ledger = list(self._ledger)
            body_pools = {}
            for p in pools:
                traj = dict(self._trajectories.get(p, {}))
                truncated = self._traj_truncated.get(p, 0)
                rollup = self._rollups.get(p)
                baseline = self._baselines.get(p)
                pool_ledger = [e for e in ledger if e.get("pool") == p]
                body_pools[p] = {
                    "jain_index": round(self._jain.get(p, 1.0), 4),
                    "jain_baseline": baseline.snapshot()
                    if baseline is not None else None,
                    "trajectories": traj,
                    "trajectories_truncated": truncated,
                    "rollups": rollup.to_json() if rollup is not None
                    else _PoolRollup().to_json(),
                    "fragmentation": self._fragmentation(p),
                    "ledger": pool_ledger[-ledger_limit:],
                }
        return {"enabled": True, "pools": body_pools}

    def collector(self) -> dict:
        """Incident-bundle evidence: bounded snapshot."""
        return self.snapshot(ledger_limit=20)
