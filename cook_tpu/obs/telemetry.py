"""DeviceTelemetry: the facade the scheduler owns.

One instance per Scheduler.  Every device solve — match (per-pool and
pool-batched), rank, rebalance — reports through `record_solve`, which
feeds the compile observatory, the per-pool solve-latency baselines, the
device-memory gauges, and the per-pool "last solve" snapshot that
`/unscheduled_jobs` and `/debug/cycles` surface so operators can
correlate reason codes with compile behavior."""
from __future__ import annotations

import threading
from typing import Optional

from cook_tpu.obs.baseline import RollingBaseline
from cook_tpu.obs.compile_observatory import (CompileObservatory,
                                              shape_signature)
from cook_tpu.obs.device_monitor import update_device_memory_gauges
from cook_tpu.obs.health import HealthMonitor
from cook_tpu.obs.quality_monitor import QualityMonitor
from cook_tpu.utils.metrics import global_registry

# wide buckets: a padded-bucket compile can cost tens of seconds while a
# warm smoke-size solve is sub-millisecond
SOLVE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
                 float("inf"))


class DeviceTelemetry:
    def __init__(self, *, storm_window: int = 32, storm_threshold: int = 4,
                 storm_warmup: Optional[int] = None,
                 quality_sample_every: int = 25,
                 latency_window: int = 64, latency_recent: int = 8,
                 latency_min_samples: int = 12,
                 latency_rel_floor: Optional[float] = None,
                 oom_threshold: float = 0.9,
                 memory_stats_fn=None,
                 update_memory_gauges: bool = True):
        self.observatory = CompileObservatory(window=storm_window,
                                              storm_threshold=storm_threshold,
                                              warmup_solves=storm_warmup)
        self.quality = QualityMonitor(sample_every=quality_sample_every)
        self.health_monitor = HealthMonitor(self, oom_threshold=oom_threshold,
                                            memory_stats_fn=memory_stats_fn)
        self._latency_args = dict(window=latency_window,
                                  recent=latency_recent,
                                  min_samples=latency_min_samples)
        if latency_rel_floor is not None:
            # widen the anomaly band's relative floor (tests / noisy
            # hosts: a short `recent` window on millisecond solves can
            # trip on scheduler jitter alone)
            self._latency_args["rel_floor"] = latency_rel_floor
        self._latency: dict[str, RollingBaseline] = {}
        self._last_solve: dict[str, dict] = {}
        # pools currently degraded to the CPU reference solver
        # (scheduler/matcher device fallback): pool -> evidence for the
        # `device-degraded` health reason
        self._fallbacks: dict[str, dict] = {}
        self._lock = threading.Lock()
        # incident hook (obs/incident.IncidentRecorder.observe): every
        # health() verdict reports through it so ok->degraded transitions
        # capture evidence bundles even when no REST probe is watching
        self.health_observer = None
        self._fallback_gauge = global_registry.gauge(
            "obs.device_fallback_active",
            "1 while the pool's match solve is degraded to the CPU "
            "reference solver")
        self._update_memory_gauges = update_memory_gauges
        self._memory_stats_fn = memory_stats_fn
        self._solve_hist = global_registry.histogram(
            "obs.solve.seconds",
            "device solve wall seconds (dispatch + execute + D2H fetch) "
            "per op/backend", buckets=SOLVE_BUCKETS)

    # ------------------------------------------------------------- recording

    def record_solve(self, op: str, shape, backend: str,
                     seconds: Optional[float] = None,
                     pool: Optional[str] = None) -> bool:
        """Report one device solve; returns True when it paid a compile
        (first-seen (op, shape, backend) key).  `seconds` feeds the
        latency histogram; match solves additionally feed the per-pool
        regression baseline via `record_match_solve`."""
        compiled = self.observatory.observe_solve(op, shape, backend,
                                                  seconds=seconds)
        if seconds is not None:
            self._solve_hist.observe(seconds, {"op": op, "backend": backend})
        if pool is not None:
            sig = shape if isinstance(shape, str) else shape_signature(shape)
            with self._lock:
                self._last_solve[pool] = {
                    "op": op, "shape": sig, "backend": backend,
                    "compiled": compiled,
                    **({"seconds": seconds} if seconds is not None else {}),
                }
        return compiled

    def record_match_solve(self, pool: str, shape, backend: str,
                           seconds: float,
                           overlapped: bool = False) -> bool:
        """The per-pool match path's entry point: compile accounting +
        per-pool latency baseline + device-memory gauge refresh.
        `overlapped=True` (the pipelined cycle) keeps the wall out of
        EVERY latency surface — regression baseline, solve histogram,
        and the per-pool last-solve snapshot: the pipelined solve wall
        (dispatch -> fetch) deliberately spans neighbor pools' host
        work, so there is no honest device-latency scalar to export —
        publishing the inflated one would fire phantom regressions the
        moment the pipeline is enabled.  Compile accounting still runs
        (it is shape-keyed, not time-keyed)."""
        compiled = self.record_solve(
            "match", shape, backend,
            None if overlapped else seconds, pool=pool)
        if not overlapped:
            self._observe_latency(pool, seconds, compiled)
        self._refresh_memory_gauges()
        return compiled

    def record_batched_match_solve(self, pools: list, shape, backend: str,
                                   seconds: float) -> bool:
        """The pool-batched path: ONE stacked program solved every pool,
        so the observatory sees one solve, while each participating
        pool's latency baseline observes the shared batch wall time (no
        pool's cycle can finish sooner than the batch)."""
        compiled = self.observatory.observe_solve("match_batched", shape,
                                                  backend, seconds=seconds)
        self._solve_hist.observe(seconds,
                                 {"op": "match_batched", "backend": backend})
        sig = shape if isinstance(shape, str) else shape_signature(shape)
        for pool in pools:
            with self._lock:
                self._last_solve[pool] = {
                    "op": "match_batched", "shape": sig, "backend": backend,
                    "compiled": compiled, "seconds": seconds,
                }
            self._observe_latency(pool, seconds, compiled)
        self._refresh_memory_gauges()
        return compiled

    def _observe_latency(self, pool: str, seconds: float,
                         compiled: bool) -> None:
        with self._lock:
            baseline = self._latency.get(pool)
            if baseline is None:
                baseline = RollingBaseline(**self._latency_args)
                self._latency[pool] = baseline
            # a compile-paying solve is not a latency sample: the first
            # run of a new program costs seconds of XLA time by design,
            # and feeding it would poison the baseline (or mask a real
            # regression behind a giant MAD band)
            if not compiled:
                baseline.add(seconds)

    def _refresh_memory_gauges(self) -> None:
        if not self._update_memory_gauges:
            return
        if self._memory_stats_fn is not None:
            update_device_memory_gauges(self._memory_stats_fn)
        else:
            update_device_memory_gauges()

    # ------------------------------------------------------ device fallback

    def note_device_fallback(self, pool: str, reason: str, *,
                             cycles_left: int = 0) -> None:
        """The matcher solved this pool on the CPU reference this cycle
        (scheduler/matcher.record_fallback_outcome)."""
        import time as _time

        with self._lock:
            entry = self._fallbacks.get(pool)
            if entry is None:
                # key is "cause", NOT "reason": the dict is spread into
                # the health degradation entry, whose "reason" key is the
                # verdict constant (device-degraded)
                entry = self._fallbacks[pool] = {
                    "cause": reason, "since": _time.time(), "cycles": 0}
            entry["cause"] = reason
            entry["cycles"] += 1
            entry["cycles_left"] = cycles_left
        self._fallback_gauge.set(1.0, {"pool": pool})

    def clear_device_fallback(self, pool: str) -> None:
        """The device probe succeeded; the pool is healthy again."""
        with self._lock:
            self._fallbacks.pop(pool, None)
        self._fallback_gauge.set(0.0, {"pool": pool})

    def device_fallbacks(self) -> dict[str, dict]:
        with self._lock:
            return {pool: dict(e) for pool, e in self._fallbacks.items()}

    # ---------------------------------------------------------------- reads

    def solve_info(self, pool: str) -> Optional[dict]:
        """The pool's last device solve: padded shape, backend, whether
        it compiled — the `/unscheduled_jobs` correlation fields."""
        with self._lock:
            info = self._last_solve.get(pool)
            return dict(info) if info is not None else None

    def latency_regressions(self) -> dict[str, dict]:
        # snapshot under the owning lock: the REST thread reads while
        # the scheduler thread appends, and iterating a deque mid-append
        # raises RuntimeError
        with self._lock:
            out = {}
            for pool, baseline in self._latency.items():
                anomaly = baseline.anomaly_high()
                if anomaly is not None:
                    out[pool] = anomaly
            return out

    def latency_stats(self) -> dict:
        with self._lock:
            return {pool: (b.snapshot() or {"n": len(b)})
                    for pool, b in self._latency.items()}

    def health(self, observe: bool = True) -> dict:
        """The device-side verdict.  `observe=False` is for callers that
        MERGE this verdict with other degradation sources before
        reporting (rest/api.get_debug_health) — observing both the
        partial and the merged verdict would read a contention-only
        degradation as an ok->degraded flap on every probe."""
        verdict = self.health_monitor.verdict()
        if observe and self.health_observer is not None:
            self.health_observer(verdict)
        return verdict
