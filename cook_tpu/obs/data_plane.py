"""Device data-plane observatory: transfer accounting, residency ledger,
padding-waste and roofline attribution.

ROADMAP item 2(a) ("stop rebuilding the world per cycle") promises to
keep encode tensors device-resident and apply O(delta) updates — but
nothing measured the thing it would eliminate.  This module is that
measurement, four instruments sharing one ledger:

  * **TransferLedger** — every host↔device crossing the scheduler owns
    (`ops/common.fetch_result`, the `jnp.asarray` conversions in the
    tensor builds, the `jax.device_put` sites in `parallel/mesh.py`)
    reports LOGICAL bytes per tensor family (node-encode /
    job-feasibility / dru-columns / hier-coarse / hier-fine /
    mesh-shard / fallback).  Logical bytes — the nbytes of the host
    array being put or fetched — are backend-stable: a CPU-fallback
    round and a real-TPU round move the same bytes, so byte counts are
    the one bench column `tools/bench_gate.py` can diff across
    backends.  The matcher's quality-audit `device_put` buckets under
    the distinct `fallback` family so CPU-reference re-solves never
    inflate device transfer numbers.

  * **residency ledger** — joins the encode-cache delta stats
    (scheduler/encode_cache.py) to report `rebuild_fraction`: the
    fraction of this cycle's per-job encode-row bytes that were freshly
    (re)computed.  A cold pool reports ~1.0; an unchanged pool served
    entirely from the host cache reports ~0.0 — yet its tensors were
    STILL re-transferred, and `(1 - rebuild_fraction)` of the encode
    traffic is exactly the waste item 2(a)'s device-resident cache
    removes.

  * **padding-waste accounting** — valid-cell fraction per padded
    bucket per op (`bucket_size` rounds everything to power-of-two
    buckets; the dead lanes still cross the bus and burn FLOPs).

  * **roofline attribution** — `compiled.cost_analysis()` (FLOPs +
    bytes accessed per (op, shape-signature, backend) program), cached
    in the CompileObservatory and joined with observed solve walls so
    the CPU-vs-device gap becomes a number per program.

Attribution is ambient: the match paths activate a per-(pool, cycle)
`CycleDataPlane` scope on the driving thread (the pipelined engine
re-activates the right pool's scope around each stage, so overlapping
solves report disjoint per-cycle counts), and instrumented sites credit
the innermost active scope plus the process-global ledger.  Sites on
threads with no active scope (the background quality audit, speculative
dispatch, bench kernels) still land in the ledger totals.

No jax at import time: `models/store.py`-adjacent modules import the
instrumented call sites, and this module must stay as cheap as
`utils/metrics` (the same lazy-import discipline as `cook_tpu/obs`).
"""
from __future__ import annotations

import collections
import os
import threading
from contextlib import contextmanager
from typing import Optional

from cook_tpu.utils.metrics import global_registry

# ------------------------------------------------------- tensor families
# Bounded label set: one family per logical tensor kind the scheduler
# moves, NOT per pool/shape (those live on the cycle records).

FAM_NODE_ENCODE = "node-encode"      # demands/avail/totals/valid tensors
FAM_FEASIBILITY = "job-feasibility"  # the [J, N] constraint mask
FAM_DRU = "dru-columns"              # DRU rank task columns + divisors
FAM_HIER_COARSE = "hier-coarse"      # hierarchical coarse pass traffic
FAM_HIER_FINE = "hier-fine"          # hierarchical fine batch traffic
FAM_MESH = "mesh-shard"              # parallel/mesh.py device_put sites
FAM_SOLVE = "solve-results"          # assignment fetches (D2H)
FAM_FALLBACK = "fallback"            # CPU-fallback / quality-audit puts
FAM_REBALANCE = "rebalance-state"    # rebalancer victim/spare tensors
FAM_ELASTIC = "elastic-plan"         # elastic demand/capacity tensors
FAM_OTHER = "other"                  # unattributed crossings

FAMILIES = (FAM_NODE_ENCODE, FAM_FEASIBILITY, FAM_DRU, FAM_HIER_COARSE,
            FAM_HIER_FINE, FAM_MESH, FAM_SOLVE, FAM_FALLBACK,
            FAM_REBALANCE, FAM_ELASTIC, FAM_OTHER)

# unpadded per-node byte width of the node encode tensors (avail [4]f32 +
# totals [2]f32 + node_valid bool) — the residency ledger's weight for
# the fingerprint-governed node encoding
NODE_ROW_BYTES = 4 * 4 + 2 * 4 + 1

_tls = threading.local()


def _stack() -> list:
    stack = getattr(_tls, "scopes", None)
    if stack is None:
        stack = _tls.scopes = []
    return stack


def _families() -> list:
    fams = getattr(_tls, "families", None)
    if fams is None:
        fams = _tls.families = []
    return fams


# sentinel pushed by detached(): masks any enclosing cycle scope so
# audit/sampling transfers never land on the driving cycle's record
_DETACHED = object()


def active_scope() -> Optional["CycleDataPlane"]:
    stack = _stack()
    if not stack:
        return None
    top = stack[-1]
    return None if top is _DETACHED else top


def current_family() -> Optional[str]:
    fams = _families()
    return fams[-1] if fams else None


@contextmanager
def activate(scope: Optional["CycleDataPlane"]):
    """Make `scope` the innermost attribution target on this thread.
    Re-entrant (the serial cycle wraps the whole pass, the matcher wraps
    its sections again) and None-tolerant (NullCycle carries no scope)."""
    if scope is None:
        yield None
        return
    stack = _stack()
    stack.append(scope)
    try:
        yield scope
    finally:
        stack.pop()


@contextmanager
def detached():
    """Mask the enclosing cycle scope: audit/shadow sections run inside
    an activated cycle (e.g. the quality monitor's shadow solve on a
    speculation commit) but their transfers are sampling overhead, not
    the cycle's data plane — they go to the ledger only."""
    stack = _stack()
    stack.append(_DETACHED)
    try:
        yield
    finally:
        stack.pop()


@contextmanager
def family(name: str):
    """Ambient family for crossings whose call site can't know the
    tensor kind (fetch_result, the mesh device_puts): the innermost
    family() context labels them."""
    fams = _families()
    fams.append(name)
    try:
        yield
    finally:
        fams.pop()


def tree_nbytes(tree) -> int:
    """Total nbytes of every array leaf in a pytree (host numpy or
    device arrays — both carry .nbytes); non-array leaves count zero."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


class CycleDataPlane:
    """Per-(pool, cycle) data-plane accumulator.  Written only by the
    cycle's driving thread (the same single-writer contract as
    CycleBuilder); read after the cycle commits."""

    __slots__ = ("pool", "cycle_id", "h2d", "d2h",
                 "rows_fresh_bytes", "rows_cached_bytes",
                 "nodes_fresh_bytes", "nodes_cached_bytes", "padding")

    def __init__(self, pool: str = "", cycle_id: int = 0):
        self.pool = pool
        self.cycle_id = cycle_id
        # family -> [bytes, calls]
        self.h2d: dict[str, list] = {}
        self.d2h: dict[str, list] = {}
        # residency: per-job encode-row bytes governed by the encode
        # cache (fresh = recomputed this cycle, cached = unchanged rows
        # that were still re-transferred), plus the node-encoding split
        self.rows_fresh_bytes = 0
        self.rows_cached_bytes = 0
        self.nodes_fresh_bytes = 0
        self.nodes_cached_bytes = 0
        # op -> [valid_cells, padded_cells]
        self.padding: dict[str, list] = {}

    # ------------------------------------------------------------ writes

    def note_h2d(self, nbytes: int, fam: str) -> None:
        slot = self.h2d.setdefault(fam, [0, 0])
        slot[0] += int(nbytes)
        slot[1] += 1

    def note_d2h(self, nbytes: int, fam: str) -> None:
        slot = self.d2h.setdefault(fam, [0, 0])
        slot[0] += int(nbytes)
        slot[1] += 1

    def note_residency(self, fresh_bytes: int, cached_bytes: int,
                       kind: str = "rows") -> None:
        if kind == "nodes":
            self.nodes_fresh_bytes += int(fresh_bytes)
            self.nodes_cached_bytes += int(cached_bytes)
        else:
            self.rows_fresh_bytes += int(fresh_bytes)
            self.rows_cached_bytes += int(cached_bytes)

    def note_padding(self, op: str, valid_cells: int,
                     padded_cells: int) -> None:
        slot = self.padding.setdefault(op, [0, 0])
        slot[0] += int(valid_cells)
        slot[1] += int(padded_cells)

    # ------------------------------------------------------------- reads

    @property
    def h2d_bytes(self) -> int:
        return sum(slot[0] for slot in self.h2d.values())

    @property
    def d2h_bytes(self) -> int:
        return sum(slot[0] for slot in self.d2h.values())

    @property
    def rebuild_fraction(self) -> Optional[float]:
        """Fraction of this cycle's encode-ROW bytes freshly recomputed
        (1 - this) × the encode H2D traffic is the device-residency
        waste.  None when the cycle encoded nothing."""
        total = self.rows_fresh_bytes + self.rows_cached_bytes
        if total <= 0:
            return None
        return self.rows_fresh_bytes / total

    @property
    def padding_waste(self) -> Optional[float]:
        """1 - valid/padded cells across every padded bucket the cycle
        built; None when nothing padded was built."""
        valid = sum(slot[0] for slot in self.padding.values())
        padded = sum(slot[1] for slot in self.padding.values())
        if padded <= 0:
            return None
        return 1.0 - valid / padded

    def families_json(self) -> dict:
        return {
            fam: {"h2d_bytes": self.h2d.get(fam, [0, 0])[0],
                  "h2d_calls": self.h2d.get(fam, [0, 0])[1],
                  "d2h_bytes": self.d2h.get(fam, [0, 0])[0],
                  "d2h_calls": self.d2h.get(fam, [0, 0])[1]}
            for fam in sorted(set(self.h2d) | set(self.d2h))
        }

    def to_json(self) -> dict:
        return {
            "pool": self.pool,
            "cycle": self.cycle_id,
            "h2d_bytes": self.h2d_bytes,
            "d2h_bytes": self.d2h_bytes,
            "rebuild_fraction": self.rebuild_fraction,
            "padding_waste": self.padding_waste,
            "residency": {
                "rows_fresh_bytes": self.rows_fresh_bytes,
                "rows_cached_bytes": self.rows_cached_bytes,
                "nodes_fresh_bytes": self.nodes_fresh_bytes,
                "nodes_cached_bytes": self.nodes_cached_bytes,
            },
            "families": self.families_json(),
            "padding": {op: {"valid_cells": slot[0],
                             "padded_cells": slot[1],
                             "waste": (1.0 - slot[0] / slot[1]
                                       if slot[1] else 0.0)}
                        for op, slot in sorted(self.padding.items())},
        }


class TransferLedger:
    """Process-lifetime transfer accounting + a bounded ring of finished
    cycle scopes — the `GET /debug/device` substrate."""

    def __init__(self, cycle_ring: int = 256):
        self._lock = threading.Lock()
        # family -> [h2d_bytes, h2d_calls, d2h_bytes, d2h_calls]
        self._families: dict[str, list] = {}
        # (op) -> {shape_sig: [valid_cells, padded_cells]}
        self._padding: dict[str, dict[str, list]] = {}
        # pool -> last finished cycle's residency summary
        self._residency: dict[str, dict] = {}
        self._cycles: collections.deque[dict] = collections.deque(
            maxlen=cycle_ring)
        self._h2d_bytes = global_registry.counter(
            "data_plane.h2d_bytes",
            "host->device bytes transferred, per tensor family")
        self._h2d_calls = global_registry.counter(
            "data_plane.h2d_calls",
            "host->device transfer calls, per tensor family")
        self._d2h_bytes = global_registry.counter(
            "data_plane.d2h_bytes",
            "device->host bytes fetched, per tensor family")
        self._d2h_calls = global_registry.counter(
            "data_plane.d2h_calls",
            "device->host fetch calls, per tensor family")
        self._rebuild_gauge = global_registry.gauge(
            "data_plane.rebuild_fraction",
            "fraction of the last cycle's encode-row bytes freshly "
            "recomputed (1 - this = re-transferred unchanged)")
        self._padding_gauge = global_registry.gauge(
            "data_plane.padding_waste",
            "1 - valid/padded cell fraction of the last padded problem "
            "built, per op")

    # ------------------------------------------------------------ writes

    def note_h2d(self, nbytes: int, fam: str, scope=None) -> None:
        nbytes = int(nbytes)
        with self._lock:
            slot = self._families.setdefault(fam, [0, 0, 0, 0])
            slot[0] += nbytes
            slot[1] += 1
        self._h2d_bytes.inc(nbytes, {"family": fam})
        self._h2d_calls.inc(1, {"family": fam})
        if scope is not None:
            scope.note_h2d(nbytes, fam)

    def note_d2h(self, nbytes: int, fam: str, scope=None) -> None:
        nbytes = int(nbytes)
        with self._lock:
            slot = self._families.setdefault(fam, [0, 0, 0, 0])
            slot[2] += nbytes
            slot[3] += 1
        self._d2h_bytes.inc(nbytes, {"family": fam})
        self._d2h_calls.inc(1, {"family": fam})
        if scope is not None:
            scope.note_d2h(nbytes, fam)

    def note_padding(self, op: str, shape_sig: str, valid_cells: int,
                     padded_cells: int, scope=None) -> None:
        with self._lock:
            buckets = self._padding.setdefault(op, {})
            slot = buckets.setdefault(shape_sig, [0, 0])
            slot[0] += int(valid_cells)
            slot[1] += int(padded_cells)
        if padded_cells > 0:
            self._padding_gauge.set(1.0 - valid_cells / padded_cells,
                                    {"op": op})
        if scope is not None:
            scope.note_padding(op, valid_cells, padded_cells)

    def finish_cycle(self, scope: CycleDataPlane) -> None:
        """Fold a finished cycle scope into the ring + the per-pool
        residency surface (empty scopes — idle pools — are skipped so
        the ring holds signal, not heartbeats)."""
        fraction = scope.rebuild_fraction
        if fraction is not None:
            self._rebuild_gauge.set(fraction, {"pool": scope.pool})
        if (scope.h2d_bytes == 0 and scope.d2h_bytes == 0
                and fraction is None):
            return
        summary = scope.to_json()
        with self._lock:
            self._cycles.append(summary)
            if fraction is not None:
                self._residency[scope.pool] = summary["residency"] | {
                    "rebuild_fraction": fraction,
                    "cycle": scope.cycle_id,
                }

    # ------------------------------------------------------------- reads

    def byte_totals(self) -> tuple[int, int]:
        """(h2d_bytes, d2h_bytes) across every family — the cheap delta
        anchor bench phases stamp around their solves."""
        with self._lock:
            h2d = sum(slot[0] for slot in self._families.values())
            d2h = sum(slot[2] for slot in self._families.values())
        return h2d, d2h

    def family_totals(self) -> dict[str, dict]:
        with self._lock:
            return {
                fam: {"h2d_bytes": slot[0], "h2d_calls": slot[1],
                      "d2h_bytes": slot[2], "d2h_calls": slot[3]}
                for fam, slot in sorted(self._families.items())
            }

    def snapshot(self, cycles: int = 32) -> dict:
        """The `/debug/device` body (roofline rows are joined in by the
        handler from the CompileObservatory)."""
        families = self.family_totals()
        with self._lock:
            # NOT `[-cycles:]`: list[-0:] is the WHOLE list, and 0 must
            # mean "no cycle section", not the maximal payload
            recent = list(self._cycles)[-cycles:] if cycles > 0 else []
            residency = {pool: dict(r)
                         for pool, r in sorted(self._residency.items())}
            padding = {
                op: {sig: {"valid_cells": slot[0],
                           "padded_cells": slot[1],
                           "waste": (1.0 - slot[0] / slot[1]
                                     if slot[1] else 0.0)}
                     for sig, slot in sorted(buckets.items())}
                for op, buckets in sorted(self._padding.items())
            }
        return {
            "transfers": {
                "families": families,
                "h2d_bytes": sum(f["h2d_bytes"] for f in families.values()),
                "d2h_bytes": sum(f["d2h_bytes"] for f in families.values()),
            },
            "residency": residency,
            "padding": padding,
            "cycles": recent,
        }

    def reset(self) -> None:
        """Test hook: zero the ledger state (metric counters are
        monotonic and stay — tests diff, not read absolutes)."""
        with self._lock:
            self._families.clear()
            self._padding.clear()
            self._residency.clear()
            self._cycles.clear()


# the process singleton every instrumented site reports to (the same
# pattern as utils/metrics.global_registry)
LEDGER = TransferLedger()


# ----------------------------------------------------- module-level notes
# Instrumented sites call these; attribution = explicit family, else the
# innermost family() context, else "other"; the innermost active cycle
# scope (if any) is credited alongside the ledger.

def note_h2d(nbytes: int, family: Optional[str] = None) -> None:
    if nbytes <= 0:
        return
    fam = family or current_family() or FAM_OTHER
    LEDGER.note_h2d(nbytes, fam, scope=active_scope())


def note_d2h(nbytes: int, family: Optional[str] = None) -> None:
    if nbytes <= 0:
        return
    fam = family or current_family() or FAM_OTHER
    LEDGER.note_d2h(nbytes, fam, scope=active_scope())


def note_residency(fresh_bytes: int, cached_bytes: int,
                   kind: str = "rows") -> None:
    scope = active_scope()
    if scope is not None:
        scope.note_residency(fresh_bytes, cached_bytes, kind=kind)


def note_padding(op: str, shape, valid_cells: int,
                 padded_cells: int) -> None:
    from cook_tpu.obs.compile_observatory import shape_signature

    sig = shape if isinstance(shape, str) else shape_signature(shape)
    LEDGER.note_padding(op, sig, valid_cells, padded_cells,
                        scope=active_scope())


def h2d(array, family: Optional[str] = None):
    """`jnp.asarray` + ledger accounting — THE instrumented host->device
    put for tensor builds (logical bytes: what crosses is the padded
    host array, whatever the backend does with it)."""
    import jax.numpy as jnp

    out = jnp.asarray(array)
    note_h2d(int(out.nbytes), family=family)
    return out


def device_put(tree, sharding_or_device=None,
               family: Optional[str] = None):
    """`jax.device_put` + ledger accounting — the instrumented placement
    for pytrees (the `parallel/mesh.py` shard sites and the quality
    audit's CPU put).  The note lands AFTER the put succeeds: a raising
    put (host allocation failure on a giant problem) transferred
    nothing, and callers that swallow the error must not inherit
    phantom bytes."""
    import jax

    if sharding_or_device is None:
        out = jax.device_put(tree)
    else:
        out = jax.device_put(tree, sharding_or_device)
    note_h2d(tree_nbytes(tree), family=family)
    return out


# ------------------------------------------------------------- roofline

# programs above this padded-cell count are never re-lowered by the
# background probe (recompiling a giant program to read its cost table
# would cost as much as the original compile)
ROOFLINE_MAX_CELLS = int(os.environ.get("COOK_ROOFLINE_MAX_CELLS",
                                        str(1 << 22)))

_probe_lock = threading.Lock()  # single-flight across the process


def cost_analysis(fn, *args, **kwargs) -> Optional[dict]:
    """Lower + compile a jitted fn AOT and normalize its
    `compiled.cost_analysis()` into {"flops", "bytes_accessed"}.
    Returns None when the backend reports nothing (some plugin backends)
    or lowering fails — the roofline is attribution, never a gate."""
    try:
        compiled = fn.lower(*args, **kwargs).compile()
        analysis = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — never let attribution raise into
        # a match cycle or bench run
        return None
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    return {
        "flops": float(analysis.get("flops", 0.0)),
        "bytes_accessed": float(analysis.get("bytes accessed", 0.0)),
    }


def probe_roofline(observatory, op: str, shape, backend: str, fn, *args,
                   inline: bool = False, **kwargs) -> Optional[dict]:
    """Fill the observatory's cost cache for one (op, shape, backend)
    program.  `inline=True` (bench, tests) runs synchronously and
    returns the cost; the default schedules a single-flight daemon
    thread (a busy probe skips — the cost stays absent and the next
    solve retries) so the match path never waits on a re-lower."""
    from cook_tpu.obs.compile_observatory import shape_signature

    sig = shape if isinstance(shape, str) else shape_signature(shape)
    if observatory is None or observatory.cost(op, sig, backend) is not None:
        return None
    if inline:
        cost = cost_analysis(fn, *args, **kwargs)
        if cost is not None:
            observatory.observe_cost(op, sig, backend, cost)
        return cost

    if not _probe_lock.acquire(blocking=False):
        return None

    def run():
        try:
            cost = cost_analysis(fn, *args, **kwargs)
            # a failed analysis is cached as unavailable: retrying would
            # re-lower (= recompile) the program on EVERY solve of a
            # backend that never reports costs
            observatory.observe_cost(
                op, sig, backend, cost if cost is not None
                else {"unavailable": True})
        finally:
            _probe_lock.release()

    try:
        # non-daemon on purpose: a daemon thread inside an XLA compile at
        # interpreter shutdown aborts the process ("terminate called
        # without an active exception"); the size cap bounds how long a
        # clean exit can wait on the join
        threading.Thread(target=run, name=f"roofline-{op}",
                         daemon=False).start()
    except Exception:  # noqa: BLE001 — thread never started, run()
        # never runs: release here or the probe wedges forever
        _probe_lock.release()
    return None
