"""Durable multi-resolution metrics history: the "what did this gauge
look like before it broke" layer.

Every observability surface before this one (/debug/health,
/debug/contention, /metrics) is a snapshot-in-time view: by the time an
operator looks, the pre-incident shape of a gauge has been overwritten
by its current value.  Online scheduling and capacity decisions are
driven by exactly this kind of time-windowed telemetry
(prediction-assisted scheduling, arXiv:2501.05563; Aryl,
arXiv:2202.07896), and the two-speed streaming scheduler (ROADMAP item
4) cannot be tuned without retained submit-to-launch latency history.

`MetricsHistory` closes the gap:

  * a background sampler polls `utils/metrics.global_registry` every
    `sample_s` seconds and turns the registry into per-series POINTS —
    gauges sample their value, counters sample their per-second RATE
    over the tick, histograms sample windowed p50/p99 (bucket-edge
    estimate over the observations that landed in the tick);
  * points land in multi-resolution rings: the raw ring plus 1m and 10m
    rollup rings whose buckets carry min/max/mean/last/count — a week of
    10m buckets costs ~1000 points per series while the raw ring keeps
    the last hours at full resolution;
  * with a `dir`, every sample tick is appended to a bounded JSONL
    segment under `data_dir/metrics/` (rotated by line count, retention-
    capped by segment count, torn tails tolerated on recovery) and the
    rings are rebuilt from the segments on restart — history survives
    the process;
  * `query(metric, since, step)` serves `GET /debug/history`
    (rest/api.py) and the `cs history` sparkline renderer;
  * `incident_slice()` is registered as an incident-bundle collector
    (rest/api.py) so every bundle embeds the pre-incident window of the
    configured key series — a bundle answers "what changed before it
    broke" without a live node.

Import discipline: stdlib + utils.metrics only — the REST layer and
control-plane-only nodes import this module (same rule as
obs/contention.py).
"""
from __future__ import annotations

import collections
import json
import logging
import math
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from cook_tpu.utils.metrics import (Counter, Gauge, Histogram, Registry,
                                    global_registry)

log = logging.getLogger(__name__)

# rollup resolutions: (step name, bucket width seconds).  "raw" is the
# unbucketed sample stream; queries name one of these.
ROLLUPS = (("1m", 60.0), ("10m", 600.0))
STEPS = ("raw",) + tuple(name for name, _ in ROLLUPS)

# pre-incident series embedded in every incident bundle (prefix match —
# a `family.` entry covers the family).  Chosen to answer "what changed
# before it broke" for both halves of the health verdict: the verdict
# itself, the write path, replication, and the match plane.
DEFAULT_KEY_SERIES = (
    "obs.health.degraded",
    "obs.health.reason_active",
    "incident.open",
    "rest.in_flight",
    "store.lock.contention_ratio",
    "replication.follower_lag_events",
    "job.latency.submit_commit_ack.",
    "match.matched",
    "rank.queue_len",
    "fairness.",
)


@dataclass
class HistoryConfig:
    """Knobs for the sampler + retention (Settings.history_sample_s /
    Settings.history_retention; docs/configuration.md)."""

    sample_s: float = 10.0
    # per-series ring caps: points retained in memory per resolution
    raw_points: int = 4096
    rollup_points: int = 2048
    # on-disk segments: ticks per segment before rotation, and how many
    # rotated segments retention keeps
    segment_lines: int = 512
    max_segments: int = 64
    # incident-bundle slice: series prefixes + window
    key_series: tuple = DEFAULT_KEY_SERIES
    incident_window_s: float = 600.0
    # a series with no new point for this long is dropped outright
    # (rings + rollups + index row).  Churned label sets — per-user
    # monitor gauges, per-peer fleet gauges — are REMOVED from the
    # registry when their subject goes away; without an age-out their
    # history series would accumulate ring buffers forever on a
    # long-lived leader.  <= 0 disables.
    series_ttl_s: float = 86_400.0

    @classmethod
    def from_retention(cls, sample_s: float,
                       retention: Optional[dict] = None) -> "HistoryConfig":
        """Settings-shaped constructor: `history_retention` keys override
        the matching caps ({"raw_points": .., "rollup_points": ..,
        "segment_lines": .., "max_segments": .., "key_series": [..],
        "incident_window_s": ..})."""
        retention = dict(retention or {})
        kw = {"sample_s": sample_s}
        for key in ("raw_points", "rollup_points", "segment_lines",
                    "max_segments"):
            if key in retention:
                kw[key] = int(retention[key])
        if "incident_window_s" in retention:
            kw["incident_window_s"] = float(retention["incident_window_s"])
        if "series_ttl_s" in retention:
            kw["series_ttl_s"] = float(retention["series_ttl_s"])
        if "key_series" in retention:
            kw["key_series"] = tuple(retention["key_series"])
        return cls(**kw)


def _series_key(name: str, labels_key: tuple, suffix: str = "") -> str:
    base = name + suffix
    if not labels_key:
        return base
    inner = ",".join(f"{k}={v}" for k, v in labels_key)
    return f"{base}{{{inner}}}"


def series_base(key: str) -> str:
    """The series name with the label set stripped:
    `rank.queue_len{pool=default}` -> `rank.queue_len`."""
    brace = key.find("{")
    return key if brace < 0 else key[:brace]


def _histogram_quantile(buckets: tuple, counts: list[int],
                        q: float) -> Optional[float]:
    """Bucket-edge quantile estimate over one tick's observation deltas
    (the exposition-histogram estimate: the value is the upper edge of
    the bucket the target rank lands in; +Inf collapses to the last
    finite edge)."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0
    for edge, count in zip(buckets, counts):
        cum += count
        if cum >= rank:
            if edge == math.inf:
                finite = [e for e in buckets if e != math.inf]
                return finite[-1] if finite else None
            return edge
    return None


class _Rollup:
    """One series' rollup at one resolution: the finalized-bucket ring
    plus the open bucket raw points fold into."""

    __slots__ = ("width", "ring", "open")

    def __init__(self, width: float, cap: int):
        self.width = width
        self.ring: collections.deque = collections.deque(maxlen=cap)
        self.open: Optional[dict] = None

    def add(self, t: float, v: float) -> None:
        start = math.floor(t / self.width) * self.width
        bucket = self.open
        if bucket is not None and bucket["t"] != start:
            self.ring.append(bucket)
            bucket = None
        if bucket is None:
            self.open = {"t": start, "min": v, "max": v, "sum": v,
                         "count": 1, "last": v}
            return
        bucket["min"] = min(bucket["min"], v)
        bucket["max"] = max(bucket["max"], v)
        bucket["sum"] += v
        bucket["count"] += 1
        bucket["last"] = v

    def points(self, since: float) -> list[dict]:
        out = []
        for bucket in self.ring:
            if bucket["t"] + self.width <= since:
                continue
            out.append(self._render(bucket))
        if self.open is not None and self.open["t"] + self.width > since:
            out.append(self._render(self.open))
        return out

    @staticmethod
    def _render(bucket: dict) -> dict:
        return {"t": bucket["t"], "min": bucket["min"],
                "max": bucket["max"],
                "mean": bucket["sum"] / bucket["count"],
                "last": bucket["last"], "count": bucket["count"]}


class MetricsHistory:
    """Multi-resolution, optionally durable history over a metrics
    registry.  Thread-safe: the sampler thread writes, REST handlers and
    incident collectors read."""

    def __init__(self, registry: Optional[Registry] = None, *,
                 dir: Optional[str] = None,
                 config: Optional[HistoryConfig] = None,
                 clock: Callable[[], float] = time.time):
        self.registry = registry or global_registry
        self.dir = dir or None
        self.config = config or HistoryConfig()
        self.clock = clock
        self._lock = threading.Lock()
        self._raw: dict[str, collections.deque] = {}
        self._rollups: dict[str, dict[str, _Rollup]] = {}
        # previous cumulative values, for counter rates and histogram
        # window deltas — live state only, never recovered from disk
        # (the first tick after restart just emits no rate points)
        self._prev_counts: dict[str, float] = {}
        self._prev_hist: dict[str, list[int]] = {}
        self._prev_t: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # on-disk segment state
        self._segment_index = 0
        self._segment_lines = 0
        self._segment_file = None
        self._samples = global_registry.counter(
            "history.samples", "metrics-history sample ticks taken")
        self._points = global_registry.counter(
            "history.points", "metrics-history points recorded, all series")
        self._series_gauge = global_registry.gauge(
            "history.series", "series the metrics history is tracking")
        self._segments_gauge = global_registry.gauge(
            "history.segments", "on-disk metrics-history segments retained")
        self._recovered = global_registry.counter(
            "history.recovered_points",
            "points rebuilt from on-disk segments at startup")
        if self.dir:
            self._recover()

    # ------------------------------------------------------------ sampling

    def sample_once(self, now: Optional[float] = None) -> int:
        """Take one sample tick: registry -> points -> rings (+ the
        on-disk segment).  Returns the number of points recorded."""
        now = self.clock() if now is None else now
        points = self._collect(now)
        with self._lock:
            for key, value in points.items():
                self._append_locked(key, now, value)
            self._expire_series_locked(now)
            self._series_gauge.set(len(self._raw))
        if points:
            self._persist_tick(now, points)
        self._samples.inc()
        self._points.inc(len(points))
        return len(points)

    def _expire_series_locked(self, now: float) -> None:
        """Drop series that stopped producing points TTL ago — the
        subject behind a removed label set (a departed user, a
        decommissioned peer) must eventually leave the index too."""
        ttl = self.config.series_ttl_s
        if ttl <= 0:
            return
        for key in [k for k, ring in self._raw.items()
                    if ring and now - ring[-1][0] > ttl]:
            del self._raw[key]
            del self._rollups[key]

    def _collect(self, now: float) -> dict[str, float]:
        """One tick's points from the registry snapshot.  Counters and
        histograms need a previous tick to difference against, so their
        first observation primes state and emits nothing."""
        with self.registry._lock:
            metrics = list(self.registry._metrics.items())
        prev_t = self._prev_t
        self._prev_t = now
        dt = (now - prev_t) if prev_t is not None else None
        points: dict[str, float] = {}
        # prev-state keys still backed by a live registry label set; the
        # maps are pruned to this after the pass — a removed label set
        # (departed user, decommissioned peer) must not leave its
        # cumulative state behind forever
        seen_counts: set[str] = set()
        seen_hist: set[str] = set()
        for name, metric in metrics:
            if isinstance(metric, Gauge):
                with metric._lock:
                    values = list(metric._values.items())
                for labels_key, value in values:
                    points[_series_key(name, labels_key)] = float(value)
            elif isinstance(metric, Counter):
                with metric._lock:
                    values = list(metric._values.items())
                for labels_key, value in values:
                    key = _series_key(name, labels_key, ".rate")
                    seen_counts.add(key)
                    prev = self._prev_counts.get(key)
                    self._prev_counts[key] = value
                    if prev is None or dt is None or dt <= 0:
                        continue
                    # a counter can only move forward; a drop means the
                    # process restarted mid-window — treat as a fresh base
                    points[key] = max(0.0, value - prev) / dt
            elif isinstance(metric, Histogram):
                with metric._lock:
                    counts = [(k, list(c)) for k, c in
                              metric._counts.items()]
                for labels_key, cum in counts:
                    state_key = _series_key(name, labels_key)
                    seen_hist.add(state_key)
                    prev = self._prev_hist.get(state_key)
                    self._prev_hist[state_key] = cum
                    if prev is None or len(prev) != len(cum):
                        continue
                    delta = [max(0, c - p) for c, p in zip(cum, prev)]
                    for q, suffix in ((0.5, ".p50"), (0.99, ".p99")):
                        est = _histogram_quantile(metric.buckets, delta, q)
                        if est is not None:
                            points[_series_key(name, labels_key,
                                               suffix)] = est
        for gone in set(self._prev_counts) - seen_counts:
            del self._prev_counts[gone]
        for gone in set(self._prev_hist) - seen_hist:
            del self._prev_hist[gone]
        return points

    def _append_locked(self, key: str, t: float, v: float) -> None:
        raw = self._raw.get(key)
        if raw is None:
            raw = self._raw[key] = collections.deque(
                maxlen=self.config.raw_points)
            self._rollups[key] = {
                step: _Rollup(width, self.config.rollup_points)
                for step, width in ROLLUPS}
        raw.append((t, v))
        for rollup in self._rollups[key].values():
            rollup.add(t, v)

    # ---------------------------------------------------------- durability

    def _segment_path(self, index: int) -> str:
        return os.path.join(self.dir, f"segment-{index:06d}.jsonl")

    def _persist_tick(self, t: float, points: dict[str, float]) -> None:
        if not self.dir:
            return
        try:
            os.makedirs(self.dir, exist_ok=True)
            if self._segment_file is None:
                self._segment_file = open(
                    self._segment_path(self._segment_index), "a")
                self._segment_lines = 0
                # prune on OPEN, not just rotation: the retained set
                # (open segment included) never exceeds the cap, and
                # recovery reads exactly what retention kept
                self._prune_segments()
            line = json.dumps({"t": t, "p": points})
            self._segment_file.write(line + "\n")
            self._segment_file.flush()
            self._segment_lines += 1
            if self._segment_lines >= self.config.segment_lines:
                self._rotate_segment()
        except OSError as e:
            # disk trouble must not take the sampler down: the in-memory
            # rings keep serving, and the next tick retries the disk
            log.warning("metrics history tick not persisted to %s: %s",
                        self.dir, e)
            self._close_segment()

    def _rotate_segment(self) -> None:
        """Close the full segment and start the next numbered one (the
        open happens lazily on the next tick, which also prunes);
        retention drops the OLDEST segments beyond the cap — a point
        newer than the cap is never the one pruned."""
        self._close_segment()
        self._segment_index += 1

    def _close_segment(self) -> None:
        if self._segment_file is not None:
            try:
                self._segment_file.close()
            except OSError:
                pass
            self._segment_file = None

    def _prune_segments(self) -> None:
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.startswith("segment-")
                           and n.endswith(".jsonl"))
            for name in names[:-self.config.max_segments]:
                os.unlink(os.path.join(self.dir, name))
            self._segments_gauge.set(
                min(len(names), self.config.max_segments))
        except OSError:
            pass

    def _recover(self) -> None:
        """Rebuild the rings from the retained segments (newest
        `max_segments`, oldest first so rollup buckets re-fold in
        arrival order); numbering continues after the newest segment.
        A torn trailing line (crash mid-append) is skipped, not fatal."""
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.startswith("segment-")
                           and n.endswith(".jsonl"))
        except OSError:
            return
        recovered = 0
        for name in names[-self.config.max_segments:]:
            try:
                with open(os.path.join(self.dir, name)) as f:
                    for line in f:
                        try:
                            tick = json.loads(line)
                        except ValueError:
                            continue  # torn tail
                        t = float(tick.get("t", 0.0))
                        for key, value in (tick.get("p") or {}).items():
                            self._append_locked(key, t, float(value))
                            recovered += 1
            except OSError:
                continue
        if names:
            last = names[-1]
            self._segment_index = int(last[len("segment-"):-len(".jsonl")])
            # resume appending to the newest segment only while it has
            # line budget left; otherwise start the next one
            try:
                with open(os.path.join(self.dir, last)) as f:
                    lines = sum(1 for _ in f)
            except OSError:
                lines = self.config.segment_lines
            if lines >= self.config.segment_lines:
                self._segment_index += 1
            else:
                self._segment_lines = lines
                try:
                    self._segment_file = open(
                        os.path.join(self.dir, last), "a")
                except OSError:
                    self._segment_file = None
        self._series_gauge.set(len(self._raw))
        self._segments_gauge.set(len(names))
        if recovered:
            self._recovered.inc(recovered)
            log.info("metrics history recovered %d points / %d series "
                     "from %s", recovered, len(self._raw), self.dir)

    # -------------------------------------------------------------- reads

    def series_index(self) -> dict[str, dict]:
        """{series: {points, newest_t}} — the discovery surface
        `GET /debug/history` serves when no metric is named."""
        with self._lock:
            return {key: {"points": len(ring),
                          "newest_t": ring[-1][0] if ring else None}
                    for key, ring in sorted(self._raw.items())}

    def _match_keys(self, metric: str) -> list[str]:
        """Series selected by a query: the exact series key, every
        labeled series of a base name, or a trailing-`*` prefix."""
        if metric.endswith("*"):
            prefix = metric[:-1]
            return [k for k in self._raw if k.startswith(prefix)]
        return [k for k in self._raw
                if k == metric or series_base(k) == metric]

    def query(self, metric: str, since: float = 0.0,
              step: str = "raw") -> dict:
        """Points for every series `metric` selects, at one resolution.
        `since` <= 0 is relative to now (-600 = the last ten minutes);
        raw points render as [t, value] pairs, rollup points as
        {t, min, max, mean, last, count} buckets."""
        if step not in STEPS:
            raise ValueError(f"unknown step {step!r} "
                             f"(one of {', '.join(STEPS)})")
        if since <= 0.0:
            since = (self.clock() + since) if since < 0.0 else 0.0
        with self._lock:
            keys = sorted(self._match_keys(metric))
            series: dict[str, list] = {}
            for key in keys:
                if step == "raw":
                    series[key] = [[t, v] for t, v in self._raw[key]
                                   if t > since]
                else:
                    series[key] = self._rollups[key][step].points(since)
        return {"metric": metric, "step": step, "since": since,
                "series": series}

    def incident_slice(self) -> dict:
        """The pre-incident raw window of the configured key series —
        registered as an incident-bundle collector so a bundle carries
        "what changed before it broke" without a live node."""
        window = self.config.incident_window_s
        since = self.clock() - window
        with self._lock:
            series = {}
            for key in sorted(self._raw):
                base = series_base(key)
                if not any(base == p or base.startswith(p)
                           for p in self.config.key_series):
                    continue
                points = [[t, v] for t, v in self._raw[key] if t > since]
                if points:
                    series[key] = points
        return {"window_s": window, "series": series,
                "key_series": list(self.config.key_series)}

    # ------------------------------------------------------------ running

    def start(self) -> "MetricsHistory":
        """Start the background sampler (no-op when sample_s <= 0)."""
        if self.config.sample_s <= 0 or self._thread is not None:
            return self

        def loop():
            while not self._stop.wait(self.config.sample_s):
                try:
                    self.sample_once()
                except Exception:  # noqa: BLE001 — the sampler must
                    # survive any registry/disk hiccup
                    log.exception("metrics history sample failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="metrics-history")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.config.sample_s + 5)
            self._thread = None
        with self._lock:
            self._close_segment()
