"""Device profile capture: single-flight, duration-bounded, rate-limited.

`jax.profiler.start_trace`/`stop_trace` capture an XProf/TensorBoard-
viewable device profile, but raw access is operationally dangerous on a
live scheduler: two concurrent captures corrupt each other (the profiler
is process-global), an unmatched start leaks collection overhead
forever, and an automatic trigger that fires on every degraded health
probe would profile continuously exactly when the system is slowest.

`ProfileCapturer` makes capture safe to expose:

  * **single-flight** — at most one capture in flight per capturer;
    a second request is rejected with the active capture's identity
    instead of corrupting it;
  * **duration-bounded** — every capture stops itself on a daemon timer
    (clamped to `max_duration_s`), so an operator who fires
    `POST /debug/profile` and walks away cannot leave the profiler on;
  * **cooldown-rate-limited auto capture** — `maybe_capture_auto` fires
    only for latency-shaped health reasons (`AUTO_PROFILE_REASONS`) and
    at most once per `cooldown_s`, so a flapping verdict cannot turn the
    leader into a full-time profiler.

The start/stop functions are injectable so tests (and non-jax builds)
exercise the lifecycle without the real profiler.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Optional

from cook_tpu.utils.metrics import global_registry

# health reasons that mean "latency went somewhere the host cannot see":
# worth a device profile.  Deliberately DEVICE-shaped only —
# commit-ack-slo-burn is a control-plane overload where a device profile
# holds little of the answer (the bundle's contention snapshot does) and
# the capture's own overhead measurably worsens the burn on a saturated
# leader (verified: a 3 s auto capture during an SLO burn pushed sync-ack
# replication past its bound) — an incident tool must not amplify the
# incident it is documenting.
AUTO_PROFILE_REASONS = frozenset({
    "solve-latency-regression",
    "device-degraded",
})


def _jax_start(log_dir: str) -> None:
    import jax

    jax.profiler.start_trace(log_dir)


def _jax_stop() -> None:
    import jax

    jax.profiler.stop_trace()


class ProfileCapturer:
    def __init__(self, *, base_dir: Optional[str] = None,
                 default_duration_s: float = 3.0,
                 max_duration_s: float = 30.0,
                 cooldown_s: float = 300.0,
                 start_fn: Optional[Callable[[str], None]] = None,
                 stop_fn: Optional[Callable[[], None]] = None,
                 history: int = 8):
        import tempfile

        self.base_dir = base_dir or os.path.join(
            tempfile.gettempdir(), "cook-tpu-profiles")
        self.default_duration_s = default_duration_s
        self.max_duration_s = max_duration_s
        self.cooldown_s = cooldown_s
        self._start = start_fn or _jax_start
        self._stop = stop_fn or _jax_stop
        self._lock = threading.Lock()
        self._active: Optional[dict] = None
        self._last_auto: float = float("-inf")
        self._seq = 0
        self._history: deque = deque(maxlen=history)
        self._captures = global_registry.counter(
            "profile.captures",
            "device profile captures started, per trigger")
        self._rejected = global_registry.counter(
            "profile.rejected",
            "profile capture requests rejected, per cause "
            "(in-flight, cooldown, profiler-error)")
        self._active_gauge = global_registry.gauge(
            "profile.active", "1 while a device profile capture is open")

    # ------------------------------------------------------------- capture

    def capture(self, duration_s: Optional[float] = None, *,
                trigger: str = "manual") -> dict:
        """Start one bounded capture.  Returns the capture descriptor
        ({"started": True, "log_dir": ..., ...}) or a rejection
        ({"started": False, "reason": ...}) — never raises."""
        duration = min(float(duration_s or self.default_duration_s),
                       self.max_duration_s)
        if duration <= 0:
            return {"started": False, "reason": "non-positive duration"}
        # reserve the single-flight slot under the lock, but run the
        # (potentially slow) profiler start OUTSIDE it: GET /debug/profile
        # and concurrent capture attempts must not block on jax work
        with self._lock:
            if self._active is not None:
                self._rejected.inc(1, {"cause": "in-flight"})
                return {"started": False, "reason": "capture-in-flight",
                        "active": dict(self._active)}
            self._seq += 1
            log_dir = os.path.join(
                self.base_dir, f"profile-{self._seq:04d}")
            entry = {"seq": self._seq, "trigger": trigger,
                     "log_dir": log_dir, "duration_s": duration,
                     "wall_time": time.time(), "completed": False}
            self._active = entry
        try:
            os.makedirs(log_dir, exist_ok=True)
            self._start(log_dir)
        except Exception as e:  # noqa: BLE001 — a wedged profiler must
            # degrade to "no profile", never break the caller (the
            # health probe / incident capture path runs this)
            with self._lock:
                self._active = None
            self._rejected.inc(1, {"cause": "profiler-error"})
            return {"started": False, "reason": f"profiler-error: {e}"}
        self._active_gauge.set(1.0)
        self._captures.inc(1, {"trigger": trigger})
        timer = threading.Timer(duration, self._finish)
        timer.daemon = True
        timer.start()
        return {"started": True, **entry}

    def _finish(self) -> None:
        # stop BEFORE releasing the slot — if _active were cleared first,
        # a capture starting in the gap would have its fresh jax trace
        # killed by this (stale) timer — but run the (slow, profile-
        # serializing) stop outside the lock: the still-occupied slot is
        # what serializes the profiler, the lock only guards the fields
        with self._lock:
            entry = self._active
        if entry is None:
            return
        try:
            self._stop()
        except Exception:  # noqa: BLE001 — stop failing must not kill
            # the timer thread; the next start attempt will surface it
            self._rejected.inc(1, {"cause": "profiler-error"})
        with self._lock:
            entry["completed"] = True
            self._history.append(dict(entry))
            self._active = None
        self._active_gauge.set(0.0)

    def maybe_capture_auto(self, reasons) -> dict:
        """Automatic capture for a degraded health verdict: fires only on
        latency-shaped reasons, at most once per cooldown.  The cooldown
        is only committed when a capture actually STARTS — a rejection
        (slot in flight, profiler error) must not block the auto profile
        for the whole next window."""
        latency = sorted(set(reasons) & AUTO_PROFILE_REASONS)
        if not latency:
            return {"started": False, "reason": "no-latency-shaped-reason"}
        with self._lock:
            if time.monotonic() - self._last_auto < self.cooldown_s:
                self._rejected.inc(1, {"cause": "cooldown"})
                return {"started": False, "reason": "cooldown",
                        "cooldown_s": self.cooldown_s}
        result = self.capture(trigger="auto:" + ",".join(latency))
        if result.get("started"):
            with self._lock:
                self._last_auto = time.monotonic()
        return result

    # --------------------------------------------------------------- reads

    def status(self) -> dict:
        with self._lock:
            return {
                "active": dict(self._active) if self._active else None,
                "recent": [dict(e) for e in self._history],
                "base_dir": self.base_dir,
                "default_duration_s": self.default_duration_s,
                "max_duration_s": self.max_duration_s,
                "auto_cooldown_s": self.cooldown_s,
            }
