"""Online solution-quality monitor: sampled CPU shadow solves + drift.

The matcher's periodic exact-kernel audit (`matcher.audit_match_quality`)
guards one cycle's parity; this monitor guards the TREND.  Every
`sample_every`-th solvable cycle per pool it shadow-solves the SAME
problem with the reference-faithful numpy greedy
(`ops/cpu_reference.np_greedy_match` — identical decision semantics to
Fenzo's sequential scheduleOnce) and records the packing-efficiency
ratio (device-placed demand weight / reference-placed demand weight)
into a rolling baseline.  A recent-median drop out of the median/MAD
band — or below the absolute parity floor — is **quality drift**, one of
the four `/debug/health` degradation reasons.

Shadow solves run host-side on the unpadded problem (<= the pool's
considerable cap, ~1000 jobs by default), bounded by `max_shadow_jobs`
so a misconfigured pool can't stall a match cycle on an O(J·N) replay.
"""
from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from cook_tpu.obs.baseline import RollingBaseline
from cook_tpu.ops.common import fetch_result
from cook_tpu.utils.metrics import global_registry


class QualityMonitor:
    def __init__(self, sample_every: int = 25, floor: float = 0.97,
                 max_shadow_jobs: int = 4096, window: int = 32,
                 recent: int = 4, min_samples: int = 8,
                 rel_floor: float = 0.02):
        self.sample_every = sample_every  # <= 0 disables shadow sampling
        self.floor = floor
        self.max_shadow_jobs = max_shadow_jobs
        self._baseline_args = dict(window=window, recent=recent,
                                   min_samples=min_samples,
                                   rel_floor=rel_floor)
        self._cycles: dict[str, int] = {}
        self._baselines: dict[str, RollingBaseline] = {}
        self._last: dict[str, float] = {}
        self._in_drift: dict[str, bool] = {}
        # sample listeners (fn(pool, ratio)): the quantization parity
        # guard (scheduler/device_state.py) rides every shadow-solve
        # sample this way — ONE wiring site covers the serial, batched,
        # pipelined, and speculative paths
        self._listeners: list = []
        self._lock = threading.Lock()
        self._gauge = global_registry.gauge(
            "obs.quality.efficiency",
            "sampled packing efficiency: device solve vs CPU reference "
            "greedy (placed demand weight ratio)")
        self._drift_counter = global_registry.counter(
            "obs.quality.drift_events",
            "quality-drift onsets per pool (edge-triggered: one sustained "
            "episode counts once)")
        self._shadow_counter = global_registry.counter(
            "obs.quality.shadow_solves", "CPU shadow solves run per pool")

    def due(self, pool: str) -> bool:
        """Count one solvable cycle; True on the sampled ones."""
        if self.sample_every <= 0:
            return False
        with self._lock:
            n = self._cycles.get(pool, 0) + 1
            self._cycles[pool] = n
        return n % self.sample_every == 0

    def observe_cycle(self, prepared, assignment, pool: str,
                      ) -> Optional[float]:
        """Shadow-solve when due; returns the efficiency ratio when a
        shadow ran, else None.  `prepared` is the matcher's PreparedPool
        (problem + considerable); `assignment` the device decision for
        the unpadded jobs."""
        if prepared is None or getattr(prepared, "problem", None) is None:
            return None
        if not self.due(pool):
            return None
        n_jobs = len(prepared.considerable)
        if n_jobs == 0 or n_jobs > self.max_shadow_jobs:
            return None
        return self.shadow_solve(prepared, np.asarray(assignment), pool)

    def shadow_solve(self, prepared, assignment: np.ndarray,
                     pool: str) -> float:
        from cook_tpu.obs import data_plane
        from cook_tpu.ops import cpu_reference as ref

        n_jobs = len(prepared.considerable)
        problem = prepared.problem
        # the padded tensors were built for the kernel; fetch the unpadded
        # rows back (D2H via the one shared completion-observing fetch).
        # Detached + fallback-bucketed: these fetches are reference-
        # sampling overhead — they must neither inflate device-family
        # transfer numbers nor land on the driving cycle's record (a
        # speculation hit's only data-plane transfer stays the
        # assignment fetch)
        with data_plane.detached(), \
                data_plane.family(data_plane.FAM_FALLBACK):
            # f32 casts: quantized pools carry bf16 cost tensors, and
            # the reference solve + weight math must run at full width
            # (the ratio then measures exactly quantized-vs-f32 parity)
            demands = fetch_result(
                problem.demands)[:n_jobs].astype(np.float32)
            n_nodes = (prepared.nodes.n if prepared.nodes is not None
                       else fetch_result(problem.avail).shape[0])
            avail = fetch_result(
                problem.avail)[:n_nodes].astype(np.float32)
            totals = fetch_result(
                problem.totals)[:n_nodes].astype(np.float32)
        feasible = prepared.feasible
        # np_greedy_match is resource-count generic: pass every column
        # (mem, cpus, gpus, disk...) so feasibility matches the kernel's
        ref_assign = ref.np_greedy_match(
            demands, avail, totals,
            feasible_mask=(np.asarray(feasible)[:n_jobs, :n_nodes]
                           if feasible is not None else None))
        ratio = self._efficiency(demands, assignment[:n_jobs], ref_assign)
        self.record_sample(pool, ratio)
        self._shadow_counter.inc(labels={"pool": pool})
        return ratio

    @staticmethod
    def _efficiency(demands: np.ndarray, device_assign: np.ndarray,
                    ref_assign: np.ndarray) -> float:
        """Placed-demand-weight ratio, each resource normalized by the
        problem's mean demand so no single resource dominates (same
        weighting as the matcher's exact-kernel audit)."""
        scale = np.maximum(demands.mean(axis=0), 1e-9)
        weights = (demands[:, :3] / scale[:3]).sum(axis=-1)
        ref_w = float(weights[ref_assign >= 0].sum())
        dev_w = float(weights[device_assign >= 0].sum())
        if ref_w <= 0:
            # reference placed nothing: degenerate problem, not evidence
            return 1.0
        return dev_w / ref_w

    def add_listener(self, fn) -> None:
        """Register fn(pool, ratio), called on every recorded sample
        (outside the monitor lock; must not call back into the
        monitor)."""
        with self._lock:
            self._listeners.append(fn)

    def record_sample(self, pool: str, ratio: float) -> None:
        """Feed one efficiency sample (the shadow path calls this; tests
        and offline replays can inject samples directly).  Listener
        failures are logged, never propagated — a guard must not cost
        the monitor its sample."""
        from cook_tpu.utils.callbacks import notify_all

        notify_all(self._listeners, f"quality-sample pool={pool}",
                   pool, ratio)
        with self._lock:
            baseline = self._baselines.get(pool)
            if baseline is None:
                baseline = RollingBaseline(**self._baseline_args)
                self._baselines[pool] = baseline
            baseline.add(ratio)
            self._last[pool] = ratio
        self._gauge.set(ratio, {"pool": pool})
        # edge-trigger (like the observatory's storm onsets): a pool
        # sitting in drift for an hour is ONE event, not one per sample —
        # a rate() on this counter must read episodes, not sample cadence
        drifting = self._drift_detail(pool) is not None
        with self._lock:
            onset = drifting and not self._in_drift.get(pool, False)
            self._in_drift[pool] = drifting
        if onset:
            self._drift_counter.inc(labels={"pool": pool})

    def _drift_detail(self, pool: str) -> Optional[dict]:
        # the anomaly read iterates the baseline deque: it must happen
        # under the lock or a concurrent record_sample append (scheduler
        # thread vs REST health probe) raises RuntimeError
        with self._lock:
            baseline = self._baselines.get(pool)
            last = self._last.get(pool)
            if baseline is None or last is None:
                return None
            if last < self.floor:
                return {"pool": pool, "efficiency": last,
                        "floor": self.floor, "kind": "parity-floor"}
            anomaly = baseline.anomaly_low()
        if anomaly is not None:
            return {"pool": pool, "efficiency": last,
                    "kind": "rolling-baseline", **anomaly}
        return None

    def drifting_pools(self) -> dict[str, dict]:
        """Pools currently in quality drift, with evidence — the health
        verdict's quality-drift input."""
        with self._lock:
            pools = list(self._baselines)
        out = {}
        for pool in pools:
            detail = self._drift_detail(pool)
            if detail is not None:
                out[pool] = detail
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                pool: {"last": self._last.get(pool),
                       "samples": len(b),
                       **({"snapshot": b.snapshot()} if b.snapshot() else {})}
                for pool, b in self._baselines.items()
            }
