"""Fleet observatory: cross-process telemetry federation.

PR 14 made the control plane multi-node (sharded leader + journal-
replaying replicas), but every debug surface stayed a single-process
view: the operator of a 4-node deployment hand-polls N hosts and
mentally merges the verdicts.  `FleetObservatory` is the leader-side
merge: it polls every known peer — the `Settings.peers` list plus every
follower that identified itself (with its URL) through the replication
ack registry (control/replication.py -> rest/api.py) — for its health
verdict, per-shard staleness, contention summary, and a configurable
set of headline gauges, and serves one merged fleet verdict at
`GET /debug/fleet` (rendered by `cs fleet`):

  * one row per node (the leader itself included), each stamped with
    its poll age — a stale row is visibly stale, never silently fresh;
  * two new federation-level degradation reasons: `peer-unreachable`
    (transport failure / timeout) and `peer-degraded` (the peer's own
    verdict is degraded, its reasons attached verbatim);
  * worst-shard-across-nodes replication staleness, so "is any replica
    falling behind anywhere" is one field;
  * a peer's ok -> degraded edge observed by the poller captures a
    FEDERATED entry in the leader's incident ring referencing the
    peer's own newest bundle id — the leader's `/debug/incidents` is
    the one place to start any investigation.  Edges are cooldown-
    suppressed per peer, the same flap discipline as the incident
    recorder itself.

Cluster-wide, time-windowed telemetry is the input online scheduling
and capacity-loan decisions run on (arXiv:2501.05563; Aryl,
arXiv:2202.07896); this module is the collection plane for it.

Import discipline: stdlib + utils.metrics only (the REST layer and
control-plane-only nodes import this module).
"""
from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Optional

from cook_tpu.utils.metrics import global_registry, prometheus_name

log = logging.getLogger(__name__)

PEER_UNREACHABLE = "peer-unreachable"
PEER_DEGRADED = "peer-degraded"

FLEET_REASONS = (PEER_UNREACHABLE, PEER_DEGRADED)

# registry names whose current value every fleet row carries (parsed
# from the peer's /metrics exposition; the worst labeled value wins)
DEFAULT_HEADLINE_METRICS = ("obs.health.degraded", "incident.open",
                            "rest.in_flight", "rank.queue_len")


def parse_headline(metrics_text: str, names: tuple) -> dict:
    """Pull the named registry metrics out of a Prometheus exposition.
    A labeled family collapses to its MAX across label sets (headline =
    "how bad is the worst one"); histogram series are not headline
    material and never match (their rendered names carry suffixes)."""
    wanted = {prometheus_name(n): n for n in names}
    out: dict[str, float] = {}
    for line in metrics_text.splitlines():
        if not line or line.startswith("#"):
            continue
        try:
            ident, value_txt = line.rsplit(" ", 1)
        except ValueError:
            continue
        brace = ident.find("{")
        pname = ident if brace < 0 else ident[:brace]
        name = wanted.get(pname)
        if name is None:
            continue
        try:
            value = float(value_txt)
        except ValueError:
            continue
        out[name] = max(out.get(name, float("-inf")), value)
    return out


class FleetObservatory:
    """Leader-side peer poller + merged fleet verdict.

    `peers_fn` returns the live peer URL list each poll (config peers +
    the replication ack registry), so standbys that appear after boot
    are picked up without a restart.  `fetch_fn(url, timeout_s)` is the
    injectable transport (tests drive federation without sockets); the
    default is urllib with the admin dev header."""

    def __init__(self, *,
                 self_url: str = "",
                 peers: tuple = (),
                 peers_fn: Optional[Callable[[], list]] = None,
                 poll_s: float = 5.0,
                 timeout_s: float = 3.0,
                 incidents=None,
                 self_verdict_fn: Optional[Callable[[], dict]] = None,
                 cooldown_s: float = 30.0,
                 headline_metrics: tuple = DEFAULT_HEADLINE_METRICS,
                 as_user: str = "admin",
                 fetch_fn: Optional[Callable] = None):
        self.self_url = self_url.rstrip("/")
        self.peers = tuple(peers)
        self.peers_fn = peers_fn
        self.poll_s = poll_s
        self.timeout_s = timeout_s
        self.incidents = incidents
        self.self_verdict_fn = self_verdict_fn
        self.cooldown_s = cooldown_s
        self.headline_metrics = tuple(headline_metrics)
        self.as_user = as_user
        self.fetch_fn = fetch_fn or self._fetch
        self._lock = threading.Lock()
        self._rows: dict[str, dict] = {}
        # sticky peer registry: every peer EVER seen keeps being polled.
        # The dynamic half of peers_fn is the replication ack registry,
        # and a crashed standby's acks get liveness-pruned (~30s) — if
        # the peer list merely tracked it, the dead node would vanish
        # from /debug/fleet and flip the verdict back to ok exactly when
        # peer-unreachable matters most.  forget_peer() is the explicit
        # decommission path.
        self._known: set[str] = set()
        # per-peer edge state for federated incident capture:
        # state ("ok" | reason), last capture monotonic, deferred flag
        # (an edge inside the cooldown captures when it clears — the
        # incident-recorder pending discipline)
        self._peer_state: dict[str, dict] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._polls = global_registry.counter(
            "fleet.polls", "fleet peer polls attempted, per outcome")
        self._peers_gauge = global_registry.gauge(
            "fleet.peers", "peers the fleet observatory is polling")
        self._unreachable_gauge = global_registry.gauge(
            "fleet.peer_unreachable",
            "1 while the labeled peer is unreachable from the leader")
        self._degraded_gauge = global_registry.gauge(
            "fleet.peer_degraded",
            "1 while the labeled peer reports a degraded verdict")
        self._federated = global_registry.counter(
            "fleet.federated_incidents",
            "federated incident bundles captured from peer edges")
        self._suppressed = global_registry.counter(
            "fleet.federated_suppressed",
            "peer ok->degraded edges whose capture was deferred by the "
            "per-peer cooldown")

    # ----------------------------------------------------------- transport

    def _fetch(self, url: str, timeout_s: float):
        """GET one peer endpoint; JSON bodies parse, text bodies
        (the /metrics exposition) return as str.  Raises on transport
        errors — the poller turns that into peer-unreachable."""
        req = urllib.request.Request(
            url, headers={"X-Cook-Requesting-User": self.as_user})
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            body = r.read()
            content_type = r.headers.get("Content-Type", "")
        if "json" in content_type:
            return json.loads(body)
        return body.decode(errors="replace")

    # ------------------------------------------------------------- polling

    def peer_list(self) -> list[str]:
        urls = {u.rstrip("/") for u in self.peers if u}
        if self.peers_fn is not None:
            try:
                urls |= {u.rstrip("/") for u in self.peers_fn() if u}
            except Exception:  # noqa: BLE001 — a broken registry view
                # must not stop the configured peers from being polled
                log.exception("fleet peers_fn failed")
        urls.discard(self.self_url)
        urls.discard("")
        with self._lock:
            self._known |= urls
            return sorted(self._known)

    def forget_peer(self, url: str) -> None:
        """Explicitly decommission a peer: stop polling it and drop its
        row/gauges/edge state.  (Peers are otherwise STICKY — a dead
        node keeps reporting peer-unreachable rather than vanishing.)"""
        url = url.rstrip("/")
        with self._lock:
            self._known.discard(url)
            self._rows.pop(url, None)
        self._unreachable_gauge.remove({"peer": url})
        self._degraded_gauge.remove({"peer": url})
        self._peer_state.pop(url, None)

    def poll_once(self) -> dict[str, dict]:
        """Poll every peer once; returns the refreshed row map.  Peers
        poll CONCURRENTLY — serial polling would let a few black-holed
        peers (each a full transport timeout) stretch the cycle far past
        poll_s and break the within-one-poll detection promise for the
        healthy ones.  Each peer's ok->degraded edge (or reachability
        loss) lands a federated entry in the leader's incident ring,
        cooldown-suppressed per peer."""
        import concurrent.futures

        peers = self.peer_list()
        self._peers_gauge.set(len(peers))
        if not peers:
            with self._lock:
                self._rows = {}
            return {}
        if len(peers) == 1:
            rows = {peers[0]: self._poll_peer(peers[0])}
        else:
            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=min(8, len(peers)),
                    thread_name_prefix="fleet-poll") as pool:
                rows = dict(zip(peers, pool.map(self._poll_peer, peers)))
        with self._lock:
            self._rows = rows
        for url, row in rows.items():
            self._observe_edge(url, row)
        return rows

    def _poll_peer(self, url: str) -> dict:
        row: dict = {"url": url, "polled_at": time.monotonic(),
                     "wall_time": time.time()}
        try:
            verdict = self.fetch_fn(f"{url}/debug/health", self.timeout_s)
            row["ok"] = True
            row["healthy"] = bool(verdict.get("healthy", False))
            row["status"] = verdict.get("status", "unknown")
            row["reasons"] = list(verdict.get("reasons", []))
            # the contention summary rides the verdict's checks — keep
            # the headline facts, not the full per-site tables
            contention = (verdict.get("checks") or {}).get(
                "contention") or {}
            row["contention"] = {
                key: contention[key] for key in
                ("store_lock", "journal", "commit_ack")
                if key in contention}
            self._polls.inc(1, {"outcome": "ok"})
        except Exception as e:  # noqa: BLE001 — any transport/parse
            # failure is the same operational fact: the peer is not
            # observable from here
            row.update({"ok": False, "healthy": False,
                        "status": "unreachable", "reasons": [],
                        "error": f"{type(e).__name__}: {e}"})
            self._polls.inc(1, {"outcome": "unreachable"})
            self._unreachable_gauge.set(1.0, {"peer": url})
            self._degraded_gauge.set(0.0, {"peer": url})
            return row
        self._unreachable_gauge.set(0.0, {"peer": url})
        self._degraded_gauge.set(0.0 if row["healthy"] else 1.0,
                                 {"peer": url})
        # best-effort extras: a peer that serves health but trips on the
        # side endpoints still gets a row (with the facts we did get)
        try:
            replica = self.fetch_fn(f"{url}/debug/replica", self.timeout_s)
            row["staleness"] = {
                shard: r.get("staleness_ms")
                for shard, r in (replica.get("shards") or {}).items()}
        except Exception:  # noqa: BLE001
            row["staleness"] = {}
        try:
            exposition = self.fetch_fn(f"{url}/metrics", self.timeout_s)
            row["headline"] = parse_headline(str(exposition),
                                             self.headline_metrics)
        except Exception:  # noqa: BLE001
            row["headline"] = {}
        return row

    # ------------------------------------------------- federated incidents

    def _observe_edge(self, url: str, row: dict) -> None:
        reason = None
        if not row["ok"]:
            reason = PEER_UNREACHABLE
        elif not row["healthy"]:
            reason = PEER_DEGRADED
        state = self._peer_state.setdefault(
            url, {"state": "ok", "last_capture": float("-inf"),
                  "pending": False, "bundle": None})
        if reason is None:
            state["pending"] = False
            if state["state"] != "ok" and state["bundle"] is not None:
                # recovery closes the federated incident, same as the
                # recorder's own degraded->ok stamping
                state["bundle"].setdefault("recovered_time", None)
                if state["bundle"]["recovered_time"] is None:
                    state["bundle"]["recovered_time"] = time.time()
            state["state"] = "ok"
            return
        was_ok = state["state"] == "ok"
        state["state"] = reason
        if self.incidents is None:
            return
        now = time.monotonic()
        if now - state["last_capture"] < self.cooldown_s:
            if was_ok:
                # flap inside the cooldown: defer, don't drop — a
                # sustained peer outage must still get its bundle
                state["pending"] = True
                self._suppressed.inc()
            return
        if not (was_ok or state["pending"]):
            return
        state["last_capture"] = now
        state["pending"] = False
        state["bundle"] = self._capture_federated(url, row, reason)

    def _capture_federated(self, url: str, row: dict,
                           reason: str) -> Optional[dict]:
        """Land the peer's degradation in the LEADER's incident ring,
        referencing the peer's own newest bundle so the investigation
        can hop straight to the peer's evidence."""
        peer_incident_id = None
        if row["ok"]:
            try:
                index = self.fetch_fn(f"{url}/debug/incidents",
                                      self.timeout_s)
                bundles = index.get("incidents") or []
                if bundles:
                    peer_incident_id = bundles[-1].get("id")
            except Exception:  # noqa: BLE001 — the reference is a
                # convenience; the federated capture stands without it
                pass
        verdict = {
            "healthy": False,
            "status": "degraded",
            "reasons": [reason],
            "degradations": [{
                "reason": reason,
                "peer": url,
                "peer_reasons": list(row.get("reasons", [])),
                "peer_incident_id": peer_incident_id,
                "detail": (
                    f"peer {url} is unreachable from the leader "
                    f"({row.get('error', 'transport failure')})"
                    if reason == PEER_UNREACHABLE else
                    f"peer {url} reports a degraded verdict "
                    f"({', '.join(row.get('reasons', [])) or '?'}) — "
                    f"its own bundle: {peer_incident_id or 'none yet'}"),
            }],
            "federated": True,
            "peer": url,
        }
        try:
            bundle = self.incidents.capture(verdict, trigger="fleet-peer")
        except Exception:  # noqa: BLE001 — a broken collector on the
            # leader must not take the poll loop down
            log.exception("federated incident capture failed for %s", url)
            return None
        self._federated.inc()
        return bundle

    # --------------------------------------------------------------- reads

    def verdict(self) -> dict:
        """The merged fleet verdict `GET /debug/fleet` serves: one row
        per node (self first), poll-age staleness on every peer row,
        fleet-level reasons, and the worst replication shard across the
        fleet."""
        now = time.monotonic()
        with self._lock:
            rows = dict(self._rows)
        nodes = []
        if self.self_verdict_fn is not None:
            self_verdict = self.self_verdict_fn()
            nodes.append({
                "url": self.self_url or "self",
                "self": True,
                "ok": True,
                "healthy": bool(self_verdict.get("healthy", True)),
                "status": self_verdict.get("status", "unknown"),
                "reasons": list(self_verdict.get("reasons", [])),
                "poll_age_s": 0.0,
            })
        reasons: set[str] = set()
        worst_shard = None
        for url in sorted(rows):
            row = dict(rows[url])
            row["poll_age_s"] = max(0.0, now - row.pop("polled_at"))
            if not row["ok"]:
                reasons.add(PEER_UNREACHABLE)
            elif not row["healthy"]:
                reasons.add(PEER_DEGRADED)
            for shard, ms in (row.get("staleness") or {}).items():
                if ms is None:
                    continue
                if worst_shard is None \
                        or ms > worst_shard["staleness_ms"]:
                    worst_shard = {"node": url, "shard": shard,
                                   "staleness_ms": ms}
            nodes.append(row)
        for node in nodes:
            if node.get("self") and not node["healthy"]:
                reasons.update(node["reasons"])
        return {
            "enabled": True,
            "poll_s": self.poll_s,
            "self_url": self.self_url,
            "nodes": nodes,
            "peers": len(rows),
            "healthy": not reasons,
            "status": "ok" if not reasons else "degraded",
            "reasons": sorted(reasons),
            "worst_shard": worst_shard,
            "wall_time": time.time(),
        }

    # ------------------------------------------------------------- running

    def start(self) -> "FleetObservatory":
        if self.poll_s <= 0 or self._thread is not None:
            return self

        def loop():
            while not self._stop.wait(self.poll_s):
                try:
                    self.poll_once()
                except Exception:  # noqa: BLE001 — the fleet poller
                    # must survive any peer misbehavior
                    log.exception("fleet poll failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="fleet-observatory")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s + self.poll_s + 5)
            self._thread = None
