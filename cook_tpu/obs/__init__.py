"""Observability: device telemetry observatory + control-plane contention.

PR 2's flight recorder made the host-side scheduling cycle legible; this
package makes the DEVICE side and the CONTROL-PLANE write path legible:

  * `compile_observatory.CompileObservatory` — JIT-compilation accounting
    keyed by (op, shape-signature, backend), with recompile-storm
    detection over a sliding window of solves (padding-bucket churn is
    the storm generator: every new padded shape is a new XLA program).
  * `baseline.RollingBaseline` — rolling median/MAD anomaly detection,
    shared by the quality monitor (drift down) and the solve-latency
    tracker (drift up).
  * `quality_monitor.QualityMonitor` — shadow-solves a sampled fraction
    of match cycles with the CPU reference greedy and tracks
    packing-efficiency drift against the rolling baseline.
  * `device_monitor` — live device-memory gauges (`memory_stats()` on
    real accelerators) and the OOM-risk check.
  * `health.HealthMonitor` — folds the above into one machine-readable
    verdict served at `GET /debug/health` with four device degradation
    reasons: recompile-storm, quality-drift, solve-latency-regression,
    device-oom-risk.
  * `telemetry.DeviceTelemetry` — the facade the scheduler owns; match/
    rank/rebalance cycles report every device solve through it.
  * `contention.ContentionObservatory` — the control-plane side: store-
    lock wait/hold profiling, journal fsync telemetry, replication lag,
    per-endpoint REST latency, commit-ack SLO burn rate — served at
    `GET /debug/contention` and folded into `/debug/health` with five
    more reasons (store-lock-saturation, fsync-stall, replication-lag,
    commit-ack-slo-burn, job-starvation).
  * `incident.IncidentRecorder` — the diagnosis layer: every
    ok->degraded health transition snapshots an evidence bundle
    (verdict, contention, cycle records, span-ring chrome trace, armed
    faults, optional device profile) served at `GET /debug/incidents`;
    `incident.job_timeline` reconstructs one job's lifecycle for
    `GET /jobs/{uuid}/timeline`.
  * `tsdb.MetricsHistory` — durable multi-resolution metrics history:
    a background sampler turns the registry into per-series points
    (gauge values, counter rates, histogram p50/p99) retained in
    raw -> 1m -> 10m rings, persisted as bounded JSONL segments under
    `data_dir/metrics/`, recovered on restart, served at
    `GET /debug/history` and embedded (key-series slice) in every
    incident bundle.
  * `fleet.FleetObservatory` — cross-process federation: the leader
    polls every known peer (config + replication ack registry) for
    health/staleness/headline gauges and serves the merged fleet
    verdict at `GET /debug/fleet`, with peer ok->degraded edges landing
    federated entries in the leader's incident ring.
  * `profiling.ProfileCapturer` — single-flight, duration-bounded,
    cooldown-rate-limited `jax.profiler` capture behind
    `POST /debug/profile` and the incident auto-capture.
  * `data_plane.TransferLedger` — the device DATA-PLANE side: every
    host<->device crossing accounted per tensor family, the per-cycle
    residency ledger (`rebuild_fraction` — bytes re-transferred for
    unchanged encode rows), padding-waste per padded bucket, and
    roofline attribution via `compiled.cost_analysis()` — served at
    `GET /debug/device`; the measurement layer under ROADMAP item 2(a).

Exports resolve lazily (PEP 562): `models/store.py` and
`models/persistence.py` import `cook_tpu.obs.contention` at module
level for the lock/journal instruments, and that import must not drag
jax in through the device-side modules (quality_monitor imports
ops.common) — the same cheap-import discipline `cook_tpu/__init__.py`
keeps for REST-client-only consumers.
"""

_EXPORTS = {
    "RollingBaseline": ("cook_tpu.obs.baseline", "RollingBaseline"),
    "CompileObservatory": ("cook_tpu.obs.compile_observatory",
                           "CompileObservatory"),
    "device_memory_stats": ("cook_tpu.obs.device_monitor",
                            "device_memory_stats"),
    "update_device_memory_gauges": ("cook_tpu.obs.device_monitor",
                                    "update_device_memory_gauges"),
    "HealthMonitor": ("cook_tpu.obs.health", "HealthMonitor"),
    "RECOMPILE_STORM": ("cook_tpu.obs.health", "RECOMPILE_STORM"),
    "QUALITY_DRIFT": ("cook_tpu.obs.health", "QUALITY_DRIFT"),
    "SOLVE_LATENCY_REGRESSION": ("cook_tpu.obs.health",
                                 "SOLVE_LATENCY_REGRESSION"),
    "DEVICE_OOM_RISK": ("cook_tpu.obs.health", "DEVICE_OOM_RISK"),
    "QualityMonitor": ("cook_tpu.obs.quality_monitor", "QualityMonitor"),
    "DeviceTelemetry": ("cook_tpu.obs.telemetry", "DeviceTelemetry"),
    "ContentionObservatory": ("cook_tpu.obs.contention",
                              "ContentionObservatory"),
    "ContentionParams": ("cook_tpu.obs.contention", "ContentionParams"),
    "EndpointTelemetry": ("cook_tpu.obs.contention", "EndpointTelemetry"),
    "LockProfiler": ("cook_tpu.obs.contention", "LockProfiler"),
    "ProfiledRLock": ("cook_tpu.obs.contention", "ProfiledRLock"),
    "SloBurnTracker": ("cook_tpu.obs.contention", "SloBurnTracker"),
    "STORE_LOCK_SATURATION": ("cook_tpu.obs.contention",
                              "STORE_LOCK_SATURATION"),
    "FSYNC_STALL": ("cook_tpu.obs.contention", "FSYNC_STALL"),
    "REPLICATION_LAG": ("cook_tpu.obs.contention", "REPLICATION_LAG"),
    "COMMIT_ACK_SLO_BURN": ("cook_tpu.obs.contention",
                            "COMMIT_ACK_SLO_BURN"),
    "JOB_STARVATION": ("cook_tpu.obs.contention", "JOB_STARVATION"),
    "TransferLedger": ("cook_tpu.obs.data_plane", "TransferLedger"),
    "CycleDataPlane": ("cook_tpu.obs.data_plane", "CycleDataPlane"),
    "IncidentRecorder": ("cook_tpu.obs.incident", "IncidentRecorder"),
    "job_timeline": ("cook_tpu.obs.incident", "job_timeline"),
    "MetricsHistory": ("cook_tpu.obs.tsdb", "MetricsHistory"),
    "HistoryConfig": ("cook_tpu.obs.tsdb", "HistoryConfig"),
    "FairnessObservatory": ("cook_tpu.obs.fairness", "FairnessObservatory"),
    "FairnessConfig": ("cook_tpu.obs.fairness", "FairnessConfig"),
    "FAIRNESS_DRIFT": ("cook_tpu.obs.fairness", "FAIRNESS_DRIFT"),
    "jain_index": ("cook_tpu.obs.fairness", "jain_index"),
    "FleetObservatory": ("cook_tpu.obs.fleet", "FleetObservatory"),
    "PEER_UNREACHABLE": ("cook_tpu.obs.fleet", "PEER_UNREACHABLE"),
    "PEER_DEGRADED": ("cook_tpu.obs.fleet", "PEER_DEGRADED"),
    "ProfileCapturer": ("cook_tpu.obs.profiling", "ProfileCapturer"),
    "AUTO_PROFILE_REASONS": ("cook_tpu.obs.profiling",
                             "AUTO_PROFILE_REASONS"),
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    try:
        module_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module 'cook_tpu.obs' has no attribute {name!r}") from None
    import importlib

    return getattr(importlib.import_module(module_name), attr)


def __dir__():
    return __all__
