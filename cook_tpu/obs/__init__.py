"""Device telemetry observatory: the accelerator-side truth layer.

PR 2's flight recorder made the host-side scheduling cycle legible; this
package makes the DEVICE side legible:

  * `compile_observatory.CompileObservatory` — JIT-compilation accounting
    keyed by (op, shape-signature, backend), with recompile-storm
    detection over a sliding window of solves (padding-bucket churn is
    the storm generator: every new padded shape is a new XLA program).
  * `baseline.RollingBaseline` — rolling median/MAD anomaly detection,
    shared by the quality monitor (drift down) and the solve-latency
    tracker (drift up).
  * `quality_monitor.QualityMonitor` — shadow-solves a sampled fraction
    of match cycles with the CPU reference greedy and tracks
    packing-efficiency drift against the rolling baseline.
  * `device_monitor` — live device-memory gauges (`memory_stats()` on
    real accelerators) and the OOM-risk check.
  * `health.HealthMonitor` — folds the above into one machine-readable
    verdict served at `GET /debug/health` with four degradation reasons:
    recompile-storm, quality-drift, solve-latency-regression,
    device-oom-risk.
  * `telemetry.DeviceTelemetry` — the facade the scheduler owns; match/
    rank/rebalance cycles report every device solve through it.
"""
from cook_tpu.obs.baseline import RollingBaseline
from cook_tpu.obs.compile_observatory import CompileObservatory
from cook_tpu.obs.device_monitor import (
    device_memory_stats,
    update_device_memory_gauges,
)
from cook_tpu.obs.health import (
    DEVICE_OOM_RISK,
    HealthMonitor,
    QUALITY_DRIFT,
    RECOMPILE_STORM,
    SOLVE_LATENCY_REGRESSION,
)
from cook_tpu.obs.quality_monitor import QualityMonitor
from cook_tpu.obs.telemetry import DeviceTelemetry

__all__ = [
    "CompileObservatory",
    "DeviceTelemetry",
    "HealthMonitor",
    "QualityMonitor",
    "RollingBaseline",
    "RECOMPILE_STORM",
    "QUALITY_DRIFT",
    "SOLVE_LATENCY_REGRESSION",
    "DEVICE_OOM_RISK",
    "device_memory_stats",
    "update_device_memory_gauges",
]
