"""Incident observatory: evidence capture at the moment health degrades.

PRs 2/3/6 built *detection* (flight recorder, device telemetry,
contention + SLO burn rates) and PR 7 built *reaction* (breakers,
fallbacks, shedding).  This module is the *diagnosis* layer: when the
health verdict transitions ok → degraded, the evidence an operator needs
— the verdict itself, the contention snapshot, the last cycle records,
the span ring, the armed fault schedule, optionally a device profile —
is volatile ring state that will have rolled over by the time a human
looks.  `IncidentRecorder` snapshots it all into one bounded-retention
bundle at the transition, served at `GET /debug/incidents[/{id}]` and
optionally persisted under an `incidents/` directory.

Also here: `job_timeline`, the per-job lifecycle reconstruction behind
`GET /jobs/{uuid}/timeline` — txn/cycle/launch/preemption history
stitched into one causally-ordered story with waiting-time attribution
("12 cycles skipped: insufficient-resources").  Per-job lifecycle
histories are exactly what prediction-assisted scheduling needs as
training input (arXiv:2501.05563), and per-cycle wait/placement
attribution is the Aryl-style (arXiv:2202.07896) operability story.

Import discipline: stdlib + utils + models only — the REST layer and the
control-plane (no-jax) nodes import this module.
"""
from __future__ import annotations

import collections
import itertools
import json
import logging
import os
import threading
import time
from typing import Callable, Optional

from cook_tpu.utils.metrics import global_registry

log = logging.getLogger(__name__)


class IncidentRecorder:
    """Bounded ring of incident bundles, captured on ok → degraded
    health transitions (plus manual captures).

    `observe(verdict)` is the single entry point: every producer of a
    health verdict (the REST /debug/health handler, the health-watch
    trigger loop, `DeviceTelemetry.health()`) reports through it; the
    recorder tracks the previous status and captures exactly at the
    ok → degraded edge, cooldown-rate-limited so a flapping verdict
    cannot flood the retention window.  Recovery (degraded → ok) stamps
    the newest bundle's `recovered_time`, closing the incident.

    Evidence comes from named collectors (`add_collector`) so the
    recorder stays decoupled from its sources: the scheduler contributes
    cycle records + span ring + armed faults, the REST layer contributes
    the contention snapshot, and a collector raising degrades to an
    error note inside the bundle rather than losing the capture.
    """

    def __init__(self, *, capacity: int = 32, cooldown_s: float = 30.0,
                 dir: Optional[str] = None, profiler=None,
                 auto_profile: bool = False,
                 clock: Callable[[], float] = time.time):
        self.capacity = capacity
        self.cooldown_s = cooldown_s
        self.dir = dir or None
        self.profiler = profiler
        self.auto_profile = auto_profile
        self.clock = clock
        self._lock = threading.Lock()
        # resume numbering after any bundles already on disk: ids restart
        # at 1 on every boot otherwise, and the next incident's persist
        # would os.replace a crashed run's bundle of the same id — the
        # exact evidence the directory exists to preserve
        start = 1
        if self.dir:
            try:
                start = 1 + max(
                    (int(name[4:-5]) for name in os.listdir(self.dir)
                     if name.startswith("inc-") and name.endswith(".json")),
                    default=0)
            except (OSError, ValueError):
                pass
        self._ids = itertools.count(start)
        self._bundles: collections.deque = collections.deque(maxlen=capacity)
        self._prev_healthy = True
        self._last_capture = float("-inf")
        # an ok->degraded edge landed inside the cooldown: capture at the
        # first observation after it clears (a sustained incident must
        # not end up with no bundle just because it STARTED too soon
        # after the previous one)
        self._pending_capture = False
        self._collectors: dict[str, Callable[[], object]] = {}
        self._captured = global_registry.counter(
            "incident.captured", "incident bundles captured, per trigger")
        self._suppressed = global_registry.counter(
            "incident.suppressed",
            "ok->degraded transitions whose capture was suppressed by the "
            "cooldown")
        self._open_gauge = global_registry.gauge(
            "incident.open",
            "1 while the last observed health verdict is degraded")
        self._count_gauge = global_registry.gauge(
            "incident.bundles", "incident bundles currently retained")

    def add_collector(self, name: str, fn: Callable[[], object]) -> None:
        self._collectors[name] = fn

    # ------------------------------------------------------------- observe

    def observe(self, verdict: dict) -> Optional[dict]:
        """Report one health verdict; captures and returns a bundle when
        this verdict is the ok → degraded edge (and the cooldown allows),
        else returns None."""
        healthy = bool(verdict.get("healthy", True))
        now = time.monotonic()
        suppressed = False
        recovered = None
        with self._lock:
            was_healthy = self._prev_healthy
            self._prev_healthy = healthy
            capture = False
            if healthy:
                self._pending_capture = False
                if not was_healthy:
                    # stamp recovery INSIDE the transition lock: resolved
                    # outside it, a concurrent degraded-edge observer
                    # could append a fresh open bundle first and this
                    # recovery would stamp the LIVE incident as over
                    for bundle in reversed(self._bundles):
                        if bundle.get("recovered_time") is None:
                            bundle["recovered_time"] = self.clock()
                            recovered = bundle
                            break
            elif now - self._last_capture >= self.cooldown_s:
                if was_healthy or self._pending_capture:
                    self._last_capture = now
                    self._pending_capture = False
                    capture = True
            elif was_healthy:
                # edge inside the cooldown: defer, don't drop
                self._pending_capture = True
                suppressed = True
        self._open_gauge.set(0.0 if healthy else 1.0)
        if healthy:
            if recovered is not None:
                self._persist(recovered)
            return None
        if suppressed:
            self._suppressed.inc()
        if not capture:
            return None
        return self.capture(verdict, trigger="health-transition")

    # ------------------------------------------------------------- capture

    def capture(self, verdict: dict, *, trigger: str = "manual") -> dict:
        """Snapshot a bundle NOW from the current verdict + collectors.
        Collector failures are recorded inside the bundle, not raised —
        a broken evidence source must not lose the incident."""
        with self._lock:
            incident_id = f"inc-{next(self._ids):06d}"
        bundle: dict = {
            "id": incident_id,
            "wall_time": self.clock(),
            "trigger": trigger,
            "reasons": list(verdict.get("reasons", [])),
            "verdict": verdict,
            "recovered_time": None,
        }
        for name, fn in self._collectors.items():
            try:
                bundle[name] = fn()
            except Exception as e:  # noqa: BLE001 — evidence best-effort
                bundle[name] = {"error": f"{type(e).__name__}: {e}"}
        if self.profiler is not None and self.auto_profile \
                and trigger == "health-transition":
            bundle["profile"] = self.profiler.maybe_capture_auto(
                bundle["reasons"])
        with self._lock:
            self._bundles.append(bundle)
            count = len(self._bundles)
        self._captured.inc(1, {"trigger": trigger})
        self._count_gauge.set(count)
        self._persist(bundle)
        return bundle

    # ----------------------------------------------------------- retention

    def _persist(self, bundle: dict) -> None:
        if not self.dir:
            return
        try:
            os.makedirs(self.dir, exist_ok=True)
            path = os.path.join(self.dir, f"{bundle['id']}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(bundle, f, indent=1, default=str)
            os.replace(tmp, path)
            retained = sorted(
                n for n in os.listdir(self.dir)
                if n.startswith("inc-") and n.endswith(".json"))
            for name in retained[:-self.capacity]:
                os.unlink(os.path.join(self.dir, name))
        except OSError as e:
            # disk trouble while capturing an incident is itself likely
            # part of the incident: keep the in-memory bundle, say so
            log.warning("incident bundle %s not persisted to %s: %s",
                        bundle.get("id"), self.dir, e)

    # --------------------------------------------------------------- reads

    def bundles(self) -> list[dict]:
        """Newest-last summaries for GET /debug/incidents."""
        with self._lock:
            return [{
                "id": b["id"],
                "wall_time": b["wall_time"],
                "trigger": b["trigger"],
                "reasons": list(b["reasons"]),
                "recovered_time": b.get("recovered_time"),
            } for b in self._bundles]

    def get(self, incident_id: str) -> Optional[dict]:
        with self._lock:
            for bundle in self._bundles:
                if bundle["id"] == incident_id:
                    return bundle
        return None

    def dump(self) -> list[dict]:
        """Every retained bundle, full evidence included (the simulator's
        --incidents-out export)."""
        with self._lock:
            return list(self._bundles)


def add_default_collectors(recorder: IncidentRecorder, *,
                           trace_limit: int = 1024) -> IncidentRecorder:
    """Evidence every node can contribute regardless of role — the
    span-ring chrome trace and the armed fault schedule.  One registration
    site for both the scheduler-owned recorder (scheduler/core.py) and the
    control-plane-only one (rest/api.py), so the bundle schema cannot
    silently diverge between node roles."""
    from cook_tpu.utils import tracing

    recorder.add_collector(
        "trace", lambda: tracing.chrome_trace(limit=trace_limit))

    def _armed_faults():
        from cook_tpu import faults

        active = faults.ACTIVE
        return active.to_dict() if active is not None else None

    recorder.add_collector("faults", _armed_faults)
    return recorder


# ------------------------------------------------------------ job timeline

# flight-recorder codes that read as "still waiting" — runs of these are
# compressed into one waiting event with a cycle count (the attribution
# the timeline exists for)
_MATCHED = "matched"


def job_timeline(store, recorder, job, fairness=None) -> dict:
    """One job's causally-ordered lifecycle: submit, per-cycle rank/skip
    decisions (consecutive same-reason cycles compressed into one event
    with a count), launches, instance terminations (preemptions called
    out), re-queues — plus waiting-time attribution and phase latencies.

    `store` is the JobStore, `recorder` the FlightRecorder (None
    tolerated: the timeline then carries only store-derived events).
    `fairness` is the FairnessObservatory (None tolerated): when its
    preemption ledger knows a killed instance, the bare `preempted`
    event gains the ledger's detail — preemptor user/job, the victim's
    DRU at decision time, and the runtime destroyed.
    Times are store-clock milliseconds throughout (virtual in the
    simulator), the same clock `submit_time_ms` uses."""
    from cook_tpu.models.reasons import REASONS_BY_CODE

    events: list[dict] = [{
        "t_ms": job.submit_time_ms,
        "kind": "submitted",
        "pool": job.pool,
        "user": job.user,
        "priority": job.priority,
    }]

    history = recorder.job_history(job.uuid) if recorder is not None else []
    cycles_by_reason: collections.Counter = collections.Counter()
    run: list[dict] = []

    def flush_run() -> None:
        if not run:
            return
        first, last = run[0], run[-1]
        # the most recent cycle's detail is the live picture (a gang's
        # best-block shortfall shrinks as churn drains)
        detail = last.get("detail") or first.get("detail", "")
        summary = (f"{len(run)} cycle"
                   f"{'s' if len(run) != 1 else ''} skipped: "
                   f"{first['code']}")
        if first["code"] == "gang-incomplete" and detail:
            # surface WHY the gang is holding: "7 cycles skipped:
            # gang-incomplete, best block had 3/8 hosts free"
            summary += f", {detail}"
        event = {
            "t_ms": first.get("t_ms", 0),
            "kind": "waiting",
            "code": first["code"],
            "detail": detail,
            "cycles": len(run),
            "first_cycle": first["cycle"],
            "last_cycle": last["cycle"],
            "summary": summary,
        }
        for key in ("rank", "dru"):
            if last.get(key) is not None:
                event[f"last_{key}"] = last[key]
        events.append(event)
        run.clear()

    for entry in history:
        code = entry.get("code", "")
        # a history entry with no cycle timestamp (async launch-failure
        # noted after its record rolled out) must not sort before the
        # job existed
        if not entry.get("t_ms"):
            entry = {**entry, "t_ms": job.submit_time_ms}
        if code == _MATCHED:
            flush_run()
            event = {
                "t_ms": entry.get("t_ms", 0),
                "kind": "matched",
                "cycle": entry["cycle"],
                "detail": entry.get("detail", ""),
            }
            for key in ("rank", "dru", "host"):
                if entry.get(key) is not None:
                    event[key] = entry[key]
            events.append(event)
            continue
        cycles_by_reason[code] += 1
        if run and run[-1]["code"] != code:
            flush_run()
        run.append(entry)
    flush_run()

    instances = store.job_instances(job.uuid)
    run_ms_total = 0
    first_match_ms: Optional[int] = None
    for index, inst in enumerate(instances):
        if first_match_ms is None or inst.start_time_ms < first_match_ms:
            first_match_ms = inst.start_time_ms
        events.append({
            "t_ms": inst.start_time_ms,
            "kind": "launched",
            "task_id": inst.task_id,
            "host": inst.hostname,
            "cluster": inst.compute_cluster,
        })
        if not inst.status.terminal:
            continue
        run_ms_total += max(0, inst.end_time_ms - inst.start_time_ms)
        reason = REASONS_BY_CODE.get(inst.reason_code) \
            if inst.reason_code is not None else None
        preempted = inst.preempted or (
            reason is not None and "preempted" in reason.name)
        terminal = {
            "t_ms": inst.end_time_ms,
            "kind": ("completed" if inst.status.value == "success"
                     else "preempted" if preempted else "instance-failed"),
            "task_id": inst.task_id,
            "host": inst.hostname,
            "status": inst.status.value,
        }
        if reason is not None:
            terminal["reason"] = reason.name
            terminal["mea_culpa"] = reason.mea_culpa
        if preempted and fairness is not None:
            detail = fairness.victim_detail(inst.task_id)
            if detail is not None:
                terminal["preemption"] = detail
        events.append(terminal)
        # the job re-queued after this attempt died — true for every
        # failed non-final attempt (a later attempt exists), and for a
        # failed final attempt only while the job actually waits (a job
        # whose retries were exhausted, or that was killed, did not).
        # Timestamped at THIS attempt's end: last_waiting_start_time_ms
        # is re-stamped on every re-queue, so using it would time-shift
        # earlier attempts' re-queues onto the newest one.
        requeued = inst.status.value == "failed" and (
            index < len(instances) - 1 or job.state.value == "waiting")
        if requeued:
            events.append({
                "t_ms": inst.end_time_ms,
                "kind": "re-queued",
                "after_task": inst.task_id,
            })

    # stable causal order: same-timestamp ties resolve by event kind —
    # "submitted" first; a termination precedes its re-queue, which
    # precedes the skip cycles it caused; skip cycles precede the match
    # that ended them, which precedes its launch
    kind_order = {"submitted": 0, "completed": 1, "preempted": 1,
                  "instance-failed": 1, "re-queued": 2, "waiting": 3,
                  "matched": 4, "launched": 5}
    indexed = list(enumerate(events))
    indexed.sort(key=lambda pair: (pair[1]["t_ms"],
                                   kind_order.get(pair[1]["kind"], 9),
                                   pair[0]))
    events = [e for _, e in indexed]

    now_ms = store.clock()
    phases: dict = {"run_ms_total": run_ms_total}
    if first_match_ms is not None:
        phases["submit_to_first_match_ms"] = max(
            0, first_match_ms - job.submit_time_ms)
    if job.state.value == "waiting":
        start = job.last_waiting_start_time_ms or job.submit_time_ms
        phases["waiting_ms_current"] = max(0, now_ms - start)
    return {
        "uuid": job.uuid,
        "user": job.user,
        "pool": job.pool,
        "state": job.state.value,
        "priority": job.priority,
        "submit_time_ms": job.submit_time_ms,
        "events": events,
        "waiting": {
            "cycles_by_reason": dict(cycles_by_reason),
            "total_cycles": int(sum(cycles_by_reason.values())),
        },
        "phases": phases,
        "instances": len(instances),
        "wall_time": time.time(),
    }
