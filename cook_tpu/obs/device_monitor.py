"""Live device-memory gauges and the OOM-risk probe.

Real accelerator runtimes (TPU, GPU) expose per-device allocator stats
via `Device.memory_stats()`; CPU hosts return None (or raise), in which
case the gauges simply aren't set and the OOM-risk check reports
"unobservable" rather than healthy-by-default lying.

A 100k x 10k match problem's [J, N] constraint mask alone is ~2 GB of
HBM — the scheduler can genuinely OOM a shared device, and production
DL-cluster schedulers treat device headroom as a scheduling input
(Aryl; topology-aware preemptive scheduling for LLM workloads)."""
from __future__ import annotations

from typing import Optional

from cook_tpu.utils.metrics import global_registry


def device_memory_stats(device=None) -> Optional[dict]:
    """{bytes_in_use, bytes_limit, peak_bytes_in_use, utilization} for
    the first (or given) device, or None when the runtime doesn't expose
    allocator stats (CPU, some plugin backends)."""
    try:
        if device is None:
            import jax

            device = jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — a wedged device tunnel or a
        # plugin without memory_stats must degrade to "unobservable",
        # never take down the caller (this runs on the match path)
        return None
    if not stats:
        return None
    in_use = float(stats.get("bytes_in_use", 0.0))
    limit = float(stats.get("bytes_limit", 0.0))
    return {
        "bytes_in_use": in_use,
        "bytes_limit": limit,
        "peak_bytes_in_use": float(stats.get("peak_bytes_in_use", in_use)),
        "utilization": (in_use / limit) if limit > 0 else 0.0,
    }


def update_device_memory_gauges(stats_provider=device_memory_stats,
                                ) -> Optional[dict]:
    """Refresh the device-memory gauges from `stats_provider` and return
    its stats dict (None when unobservable).  Called after every device
    solve — one `memory_stats()` RPC, negligible next to the solve."""
    stats = stats_provider()
    if stats is None:
        return None
    g = global_registry.gauge
    g("obs.device.mem_bytes_in_use",
      "device allocator bytes currently in use").set(stats["bytes_in_use"])
    g("obs.device.mem_bytes_limit",
      "device allocator capacity in bytes").set(stats["bytes_limit"])
    g("obs.device.mem_peak_bytes",
      "high-water device allocator bytes").set(stats["peak_bytes_in_use"])
    g("obs.device.mem_utilization",
      "device memory fill fraction (in_use / limit)").set(
        stats["utilization"])
    return stats
