"""Rolling-baseline anomaly detection: median/MAD over a bounded window.

One detector serves both drift directions: the quality monitor flags a
packing-efficiency DROP, the latency tracker flags a solve-time RISE.
The baseline is the median of the window's older samples; the recent
median is compared against a band of `k_mad` median-absolute-deviations
(floored at `rel_floor` of the baseline, so a perfectly flat baseline —
MAD 0 — doesn't flag measurement noise)."""
from __future__ import annotations

import collections
import statistics
from typing import Optional


class RollingBaseline:
    """Bounded sample window with median/MAD deviation scoring.

    Not thread-safe by itself; owners serialize (DeviceTelemetry holds
    one per pool and feeds it from the cycle's driving thread)."""

    def __init__(self, window: int = 64, recent: int = 8,
                 min_samples: int = 12, k_mad: float = 6.0,
                 rel_floor: float = 0.05):
        assert recent < window, "recent span must leave baseline samples"
        self.window = window
        self.recent = recent
        self.min_samples = min_samples
        self.k_mad = k_mad
        self.rel_floor = rel_floor
        self._samples: collections.deque[float] = collections.deque(
            maxlen=window)

    def add(self, value: float) -> None:
        self._samples.append(float(value))

    def __len__(self) -> int:
        return len(self._samples)

    def snapshot(self) -> Optional[dict]:
        """{baseline, recent, mad, band, deviation, n} or None while the
        window is too small to judge.  `deviation` is the recent median's
        signed relative excursion past the anomaly band: 0 inside the
        band, positive above it, negative below it."""
        samples = list(self._samples)
        if len(samples) < self.min_samples:
            return None
        base = samples[:-self.recent]
        recent = samples[-self.recent:]
        baseline = statistics.median(base)
        recent_median = statistics.median(recent)
        mad = statistics.median(abs(s - baseline) for s in base)
        band = max(self.k_mad * mad, self.rel_floor * abs(baseline))
        excess = 0.0
        if recent_median > baseline + band:
            excess = recent_median - (baseline + band)
        elif recent_median < baseline - band:
            excess = recent_median - (baseline - band)
        scale = abs(baseline) if baseline else 1.0
        return {
            "baseline": baseline,
            "recent": recent_median,
            "mad": mad,
            "band": band,
            "deviation": excess / scale,
            "n": len(samples),
        }

    def anomaly_high(self) -> Optional[dict]:
        """Snapshot when the recent median sits ABOVE the band (latency
        regression direction); None otherwise."""
        snap = self.snapshot()
        return snap if snap is not None and snap["deviation"] > 0 else None

    def anomaly_low(self) -> Optional[dict]:
        """Snapshot when the recent median sits BELOW the band (quality
        drift direction); None otherwise."""
        snap = self.snapshot()
        return snap if snap is not None and snap["deviation"] < 0 else None
