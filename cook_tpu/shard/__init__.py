"""Sharded control plane: partitioned store + journal segments + replicas.

ROADMAP item 1: the solver side scales (hierarchical matcher,
pipelining, speculation, device residency) but every mutation still
serialized through ONE store RLock, ONE journal, and ONE leader — the
role Datomic's single transactor plays in the reference.  This package
partitions that control plane into N shards:

  * `ShardRouter` (router.py) — deterministic op -> shard mapping:
    per-pool routing with a hashed-user fallback for pool-less keys.
  * `ShardedStore` (store.py) — N `JobStore` shards behind the read
    facade the REST layer and scheduler already consume; each shard owns
    its own ProfiledRLock (labeled `store-s{i}`), event window, and
    idempotency table.  Pool-scoped reads route straight to the owning
    shard — the match cycle's per-pool iteration binds to per-shard
    snapshots with no cross-shard locking.
  * `ShardedTransactionLog` (txn.py) — the commit pipeline: single-shard
    ops commit exactly like today (apply under THAT shard's lock, group-
    fsync THAT shard's journal segment); cross-shard ops (pool-move
    across shards, a submit batch spanning pools) commit as an ordered
    multi-shard apply with one client-visible ack.
  * journal.py — per-shard journal segments + snapshots under
    `data_dir/shards/shard-NN/`, a versioned manifest, sharded recovery,
    and the exactly-once migration from the single-journal layout.
  * replica.py — `ShardStaleness` + `ShardedJournalFollower`: replica-
    served reads off the replayed per-shard journals with a bounded,
    monotonic staleness (`X-Cook-Staleness-Ms`), a freshness ceiling
    that falls back to the leader, and refusal when a replica stops
    applying.

Opt-in: `Settings.shards > 1` (components.py) or
`InprocessControlPlane(shards=N)` (rest/server.py).  With shards == 1
nothing here is constructed and the single-store path is byte-for-byte
what it was.
"""
from cook_tpu.shard.router import RoutePlan, ShardRouter
from cook_tpu.shard.store import ShardedStore
from cook_tpu.shard.txn import ShardedTransactionLog

__all__ = [
    "RoutePlan",
    "ShardRouter",
    "ShardedStore",
    "ShardedTransactionLog",
]
