"""ShardedStore: N JobStore shards behind the store facade.

Each shard is a full `JobStore` — its own ProfiledRLock (labeled
`store-s{i}` so /debug/contention attributes waits per shard), its own
event window and sequence numbering, its own idempotency table, and
(when persistence is attached) its own journal segment.  This facade
presents the read/write surface the REST layer, scheduler, and elastic
planner already consume:

  * pool-scoped calls (`pending_jobs`, `running_jobs`, `user_usage`,
    `get_share`, `get_quota`, ...) route straight to the owning shard —
    ONE lock touched, which is the whole point: the match cycle's
    per-pool iteration becomes a per-shard snapshot;
  * entity-keyed calls (`create_instance`, `update_instance_state`,
    `kill_jobs`, ...) resolve the owning shard by lookup;
  * global state (dynamic config, capacity ledger) lives on the META
    shard; pool metadata writes broadcast so per-shard validation and
    per-shard recovery are self-contained;
  * merged mapping views (`jobs`, `instances`, ...) serve the listing
    endpoints; they snapshot per-shard under each shard's lock, never
    holding two shard locks at once.

Cross-shard pool moves go through `move_job_pool`: source and
destination apply in ascending shard order (the fixed global order that
makes concurrent cross-shard commits deadlock-free), each emitting into
its own journal segment.
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, Optional, Sequence

from cook_tpu.models.entities import (
    Group,
    Instance,
    InstanceStatus,
    Job,
    JobState,
    Pool,
    Quota,
    Resources,
    Share,
)
from cook_tpu.models.reasons import Reason
from cook_tpu.models.store import Event, JobStore, TransactionVetoed, Watcher
from cook_tpu.shard.router import META_SHARD, ShardRouter


class _MergedView:
    """Read-only union of the shards' entity dicts.  Lookups probe
    shards in order (an entity lives on exactly one shard); iteration
    snapshots each shard's dict under that shard's lock."""

    def __init__(self, store: "ShardedStore",
                 pick: Callable[[JobStore], dict]):
        self._store = store
        self._pick = pick

    def _maps(self):
        return [self._pick(s) for s in self._store.shards]

    def get(self, key, default=None):
        for m in self._maps():
            found = m.get(key)
            if found is not None:
                return found
        return default

    def __getitem__(self, key):
        found = self.get(key)
        if found is None:
            raise KeyError(key)
        return found

    def __contains__(self, key) -> bool:
        return any(key in m for m in self._maps())

    def __len__(self) -> int:
        return sum(len(m) for m in self._maps())

    def __iter__(self):
        return iter(self.keys())

    def __bool__(self) -> bool:
        return any(self._maps())

    def keys(self):
        out = []
        for shard, m in zip(self._store.shards, self._maps()):
            with shard._lock:
                out.extend(m.keys())
        return out

    def values(self):
        out = []
        for shard, m in zip(self._store.shards, self._maps()):
            with shard._lock:
                out.extend(m.values())
        return out

    def items(self):
        out = []
        for shard, m in zip(self._store.shards, self._maps()):
            with shard._lock:
                out.extend(m.items())
        return out


class _PinnedPoolStore:
    """One pool's shard, seen as a full store (`store_for_pool`).

    Everything delegates to the shard — a per-pool cycle's reads and
    instance writes are pool-keyed, so they land on the right shard by
    construction — EXCEPT `groups`, which stays the owning facade's
    merged view: group entities ride the lowest shard of their
    submission plan and may not live on this pool's shard."""

    __slots__ = ("_shard", "_facade")

    def __init__(self, shard: JobStore, facade: "ShardedStore"):
        object.__setattr__(self, "_shard", shard)
        object.__setattr__(self, "_facade", facade)

    def __getattr__(self, name):
        if name == "groups":
            return self._facade.groups
        return getattr(self._shard, name)


class ShardedStore:
    """The partitioned control-plane store (see module docstring)."""

    def __init__(self, n_shards: int, *, mea_culpa_limit: int = 5,
                 clock: Callable[[], int] = None,
                 router: Optional[ShardRouter] = None,
                 shards: Optional[Sequence[JobStore]] = None):
        if n_shards < 2 and router is None:
            # a 1-shard facade is only meaningful with an explicit
            # router: the mp runtime's workers (cook_tpu/mp/) wrap ONE
            # global shard behind a group-scoped router so misrouted
            # keys are detected instead of silently applied locally
            raise ValueError("ShardedStore needs >= 2 shards; use a plain "
                             "JobStore for 1")
        self.n_shards = n_shards
        self.router = router or ShardRouter(n_shards)
        self.clock = clock or (lambda: 0)
        self.shards: list[JobStore] = list(shards) if shards else [
            JobStore(mea_culpa_limit=mea_culpa_limit, clock=self.clock,
                     lock_name=f"store-s{i}", shard_id=i)
            for i in range(n_shards)
        ]
        if len(self.shards) != n_shards:
            raise ValueError(f"{len(self.shards)} shards != {n_shards}")
        self.recovered_stats: dict[str, int] = {}
        # merged facade views (the REST layer reads these directly)
        self.jobs = _MergedView(self, lambda s: s.jobs)
        self.instances = _MergedView(self, lambda s: s.instances)
        self.groups = _MergedView(self, lambda s: s.groups)
        self.job_seq = _MergedView(self, lambda s: s.job_seq)
        self.shares = _MergedView(self, lambda s: s.shares)
        self.quotas = _MergedView(self, lambda s: s.quotas)
        self.txn_results = _MergedView(self, lambda s: s.txn_results)

    # --------------------------------------------------------- properties

    @property
    def mea_culpa_limit(self) -> int:
        return self.shards[0].mea_culpa_limit

    @mea_culpa_limit.setter
    def mea_culpa_limit(self, value: int) -> None:
        for shard in self.shards:
            shard.mea_culpa_limit = value

    @property
    def pools(self) -> dict[str, Pool]:
        # pool metadata is broadcast; any shard's copy is authoritative
        return self.shards[META_SHARD].pools

    @property
    def dynamic_config(self) -> dict[str, Any]:
        return self.shards[META_SHARD].dynamic_config

    @property
    def capacity_ledger(self):
        return self.shards[META_SHARD].capacity_ledger

    CAPACITY_DIMS = JobStore.CAPACITY_DIMS

    # ----------------------------------------------------------- routing

    def shard_for_pool(self, pool: str) -> JobStore:
        return self.shards[self.router.shard_for_pool(pool)]

    def store_for_pool(self, pool: str) -> "_PinnedPoolStore":
        """The pool's owning shard, pinned for a per-pool match/rank
        cycle (scheduler/core.py): snapshot reads and instance writes
        touch exactly one shard lock instead of the merged facade, so
        the cycle's encode cache / device-state mirror see one shard's
        event stream.  `groups` stays the merged view — a group entity
        rides the LOWEST shard of its submission plan, which may not be
        the pool's shard (matcher group-placement constraints)."""
        return _PinnedPoolStore(self.shard_for_pool(pool), self)

    def shard_of_job(self, job_uuid: str) -> Optional[JobStore]:
        for shard in self.shards:
            if job_uuid in shard.jobs:
                return shard
        return None

    def shard_of_instance(self, task_id: str) -> Optional[JobStore]:
        for shard in self.shards:
            if task_id in shard.instances:
                return shard
        return None

    def _job_shard(self, job_uuid: str) -> JobStore:
        shard = self.shard_of_job(job_uuid)
        if shard is None:
            raise TransactionVetoed(f"no such job {job_uuid}")
        return shard

    # ------------------------------------------------------------- infra

    def add_watcher(self, watcher: Watcher) -> None:
        for shard in self.shards:
            shard.add_watcher(watcher)

    def add_resync_listener(self, listener: Callable[[], None]) -> None:
        for shard in self.shards:
            shard.add_resync_listener(listener)

    def last_seqs(self) -> list[int]:
        """Per-shard committed-event heads (the replication/staleness
        vector — sequence numbers are only comparable within a shard)."""
        return [shard.last_seq() for shard in self.shards]

    def last_seq(self) -> int:
        """Scalar monotone commit counter (the sum of shard heads) for
        callers that only need 'did anything commit since'; replication
        and staleness use `last_seqs()`."""
        return sum(self.last_seqs())

    # ------------------------------------------------------------ writes

    def submit_jobs(self, jobs: Sequence[Job],
                    groups: Sequence[Group] = ()) -> list[str]:
        by_shard: dict[int, list[Job]] = {}
        for job in jobs:
            by_shard.setdefault(self.router.shard_for_pool(job.pool),
                                []).append(job)
        group_list = list(groups)
        for i in sorted(by_shard):
            self.shards[i].submit_jobs(by_shard[i], group_list)
            group_list = []  # groups ride with the lowest touched shard
        return [j.uuid for j in jobs]

    def create_instance(self, job_uuid: str, task_id: str, *,
                        hostname: str, node_id: str = "",
                        compute_cluster: str = "") -> Instance:
        return self._job_shard(job_uuid).create_instance(
            job_uuid, task_id, hostname=hostname, node_id=node_id,
            compute_cluster=compute_cluster)

    def update_instance_state(self, task_id: str,
                              new_status: InstanceStatus,
                              reason: Optional[Reason | int | str] = None):
        shard = self.shard_of_instance(task_id)
        if shard is None:
            from cook_tpu.models import state as state_mod

            return state_mod.StateUpdate(applied=False)
        return shard.update_instance_state(task_id, new_status, reason)

    def kill_jobs(self, job_uuids: Iterable[str]) -> list[str]:
        killed = []
        uuids = list(job_uuids)
        for shard in self.shards:
            mine = [u for u in uuids if u in shard.jobs]
            if mine:
                killed.extend(shard.kill_jobs(mine))
        return killed

    def mark_instance_cancelled(self, task_id: str) -> bool:
        shard = self.shard_of_instance(task_id)
        return shard.mark_instance_cancelled(task_id) if shard else False

    def retry_job(self, job_uuid: str, retries: int,
                  *, increment: bool = False) -> Job:
        return self._job_shard(job_uuid).retry_job(job_uuid, retries,
                                                   increment=increment)

    def move_job_pool(self, job_uuid: str, new_pool: str) -> bool:
        """Pool move, cross-shard when source and destination pools hash
        to different shards."""
        src = self.shard_of_job(job_uuid)
        if src is None or new_pool not in self.pools:
            return False
        dst = self.shard_for_pool(new_pool)
        if src is dst:
            return src.move_job_pool(job_uuid, new_pool)
        return self.move_job_cross_shard(src, dst, job_uuid, new_pool)

    def move_job_cross_shard(self, src: JobStore, dst: JobStore,
                             job_uuid: str, new_pool: str) -> bool:
        """THE cross-shard move sequence (shared by this facade and the
        sharded txn pipeline): shard-out on the source, shard-in on the
        destination, under both locks in ascending shard order (one
        fixed global order — concurrent cross-shard moves cannot
        deadlock; re-entrant under the txn pipeline's already-held
        locks).  Only WAITING jobs move (pool_mover.clj semantics)."""
        first, second = sorted((src, dst), key=lambda s: s.shard_id)
        with first._lock, second._lock:
            job = src.jobs.get(job_uuid)
            if job is None or job.state != JobState.WAITING:
                return False
            old_pool = job.pool
            moved_job, instances = src.shard_out_job(job_uuid)
            dst.shard_in_job(moved_job.with_(pool=new_pool), instances,
                             from_pool=old_pool)
            return True

    def update_instance_progress(self, task_id: str, progress: int,
                                 message: str = "") -> bool:
        shard = self.shard_of_instance(task_id)
        return (shard.update_instance_progress(task_id, progress, message)
                if shard else False)

    def set_instance_output(self, task_id: str, *,
                            exit_code: Optional[int] = None,
                            sandbox_directory: Optional[str] = None) -> None:
        shard = self.shard_of_instance(task_id)
        if shard is not None:
            shard.set_instance_output(task_id, exit_code=exit_code,
                                      sandbox_directory=sandbox_directory)

    # -------------------------------------------------- share/quota/pool

    def set_pool(self, pool: Pool) -> None:
        # broadcast: every shard validates submissions and recovers its
        # journal segment without consulting another shard
        for shard in self.shards:
            shard.set_pool(pool)

    def set_share(self, share: Share) -> None:
        self.shard_for_pool(share.pool).set_share(share)

    def retract_share(self, user: str, pool: str) -> None:
        self.shard_for_pool(pool).retract_share(user, pool)

    def get_share(self, user: str, pool: str) -> Resources:
        return self.shard_for_pool(pool).get_share(user, pool)

    def set_quota(self, quota: Quota) -> None:
        self.shard_for_pool(quota.pool).set_quota(quota)

    def retract_quota(self, user: str, pool: str) -> None:
        self.shard_for_pool(pool).retract_quota(user, pool)

    def get_quota(self, user: str, pool: str) -> Quota:
        return self.shard_for_pool(pool).get_quota(user, pool)

    def update_dynamic_config(self, updates: dict[str, Any]) -> None:
        self.shards[META_SHARD].update_dynamic_config(updates)

    # -------------------------------------------------- capacity ledger

    def apply_capacity_moves(self, moves: Sequence[dict]) -> dict:
        return self.shards[META_SHARD].apply_capacity_moves(moves)

    def encoded_capacity_ledger(self) -> list[dict]:
        return self.shards[META_SHARD].encoded_capacity_ledger()

    def set_capacity_ledger(self, entries: Sequence[dict]) -> None:
        self.shards[META_SHARD].set_capacity_ledger(entries)

    def net_capacity_adjustment(self, pool: str) -> dict[str, float]:
        return self.shards[META_SHARD].net_capacity_adjustment(pool)

    def outstanding_loans_from(self, pool: str) -> dict[str, dict[str, float]]:
        return self.shards[META_SHARD].outstanding_loans_from(pool)

    # ----------------------------------------------------------- queries

    def job_instances(self, job_uuid: str) -> list[Instance]:
        shard = self.shard_of_job(job_uuid)
        return shard.job_instances(job_uuid) if shard else []

    def pending_jobs(self, pool: str) -> list[Job]:
        return self.shard_for_pool(pool).pending_jobs(pool)

    def running_jobs(self, pool: str) -> list[Job]:
        return self.shard_for_pool(pool).running_jobs(pool)

    def running_instances(self, pool: str) -> list[Instance]:
        return self.shard_for_pool(pool).running_instances(pool)

    def live_instances_of_job(self, job_uuid: str) -> list[Instance]:
        shard = self.shard_of_job(job_uuid)
        return shard.live_instances_of_job(job_uuid) if shard else []

    def user_jobs(self, user: str) -> list[Job]:
        return list(itertools.chain.from_iterable(
            shard.user_jobs(user) for shard in self.shards))

    def user_usage(self, pool: str) -> dict[str, Resources]:
        return self.shard_for_pool(pool).user_usage(pool)

    def pending_count(self, pool: Optional[str] = None,
                      user: Optional[str] = None) -> int:
        if pool is not None:
            return self.shard_for_pool(pool).pending_count(pool, user)
        return sum(shard.pending_count(None, user)
                   for shard in self.shards)

    # --------------------------------------------------------- snapshots

    def snapshot_events(self) -> list[Event]:
        return list(itertools.chain.from_iterable(
            shard.snapshot_events() for shard in self.shards))
