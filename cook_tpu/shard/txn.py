"""ShardedTransactionLog: the partitioned commit pipeline.

Single-shard ops (the overwhelming majority — every op whose keys hash
to one shard) commit through that shard's own `TransactionLog`: apply
under THAT shard's lock, group-fsync THAT shard's journal segment,
dedupe against THAT shard's idempotency table.  Two shards never touch,
so N shards give N independent commit pipelines — the fsync barriers
that serialize the single-journal design proceed in parallel.

Cross-shard ops (a pool move whose source and destination pools hash
differently, a submit batch spanning pools, a kill naming jobs on
several shards) commit as an ORDERED MULTI-SHARD APPLY:

  1. acquire every touched shard's lock in ascending shard order (one
     fixed global order — concurrent cross-shard commits cannot
     deadlock);
  2. answer duplicates from the LOWEST touched shard's idempotency
     table (the coordinator), then pre-validate vetoes across all
     shards BEFORE any shard applies (all-or-nothing under the held
     locks);
  3. apply per shard — each shard emits into its own event window and
     journal segment;
  4. seal the SAME txn_id on every touched shard (each shard's journal
     replay dedupes independently; a promoted replica answers retries
     from any shard it recovered);
  5. release the locks, group-fsync each touched segment, acknowledge
     ONCE to the client.
"""
from __future__ import annotations

import contextlib
import time
from typing import Any, Optional, Sequence

from cook_tpu.models.store import TransactionVetoed
from cook_tpu.obs.contention import SloBurnTracker
from cook_tpu.shard.router import RoutePlan
from cook_tpu.shard.store import ShardedStore
from cook_tpu.txn.log import DurabilityPolicy, TransactionLog, _COMMIT_BUCKETS
from cook_tpu.txn.ops import OPS, UnknownOperation
from cook_tpu.txn.transaction import Transaction, TxnOutcome, new_txn_id
from cook_tpu.utils import tracing
from cook_tpu.utils.metrics import global_registry


class ShardedTransactionLog:
    """Drop-in for `TransactionLog` over a `ShardedStore` (rest/api.py
    consumes either through the same `commit()` seam)."""

    def __init__(self, store: ShardedStore, *,
                 journals: Optional[Sequence[Any]] = None,
                 policy: Optional[DurabilityPolicy] = None):
        self.store = store
        self.policy = policy or DurabilityPolicy()
        self.journals = list(journals) if journals else \
            [None] * store.n_shards
        if len(self.journals) != store.n_shards:
            raise ValueError(f"{len(self.journals)} journals != "
                             f"{store.n_shards} shards")
        self.logs = [
            TransactionLog(shard, journal=journal, policy=self.policy)
            for shard, journal in zip(store.shards, self.journals)
        ]
        # per-shard commit service-time windows: the /debug/contention
        # per-shard breakdown (and tools/loadtest.py's hottest-shard
        # attribution) reads these
        self.commit_ack = [SloBurnTracker(bucket_s=1.0,
                                          retention_s=3660.0 * 2)
                           for _ in range(store.n_shards)]
        self._commits = global_registry.counter(
            "shard.commits", "transactions committed per shard")
        self._cross = global_registry.counter(
            "shard.cross_shard_commits",
            "transactions that applied across more than one shard")
        self._commit_hist = global_registry.histogram(
            "shard.commit_seconds",
            "transaction commit wall seconds per shard (apply + fsync)",
            buckets=_COMMIT_BUCKETS)

    # the unsharded api reads txn.journal.telemetry; the sharded
    # pipeline's journals are per shard (ContentionObservatory shards_fn)
    journal = None

    def commit(self, op: str, payload: Optional[dict] = None, *,
               txn_id: Optional[str] = None) -> TxnOutcome:
        txn = Transaction(op=op, payload=payload or {},
                          txn_id=txn_id or new_txn_id())
        return self.commit_txn(txn)

    def commit_txn(self, txn: Transaction) -> TxnOutcome:
        if txn.op not in OPS:
            raise UnknownOperation(txn.op)
        plan = self.store.router.plan(txn.op, txn.payload, self.store)
        single = plan.single
        if single is not None:
            t0 = time.perf_counter()
            outcome = self.logs[single].commit_txn(txn)
            outcome.shard_seqs = {single: outcome.seq}
            self._note_commit(single, time.perf_counter() - t0,
                              duplicate=outcome.duplicate)
            return outcome
        return self._commit_multi(txn, plan)

    def _note_commit(self, shard: int, seconds: float, *,
                     duplicate: bool = False) -> None:
        labels = {"shard": str(shard)}
        self._commits.inc(1, labels)
        if not duplicate:
            self._commit_hist.observe(seconds, labels)
            self.commit_ack[shard].observe(seconds)

    # ------------------------------------------------------- multi-shard

    def _commit_multi(self, txn: Transaction,
                      plan: RoutePlan) -> TxnOutcome:
        t0 = time.perf_counter()
        shards = plan.shards
        stores = [self.store.shards[i] for i in shards]
        with contextlib.ExitStack() as stack:
            for store in stores:  # ascending shard order: deadlock-free
                stack.enter_context(store._lock)
            cached = stores[0].txn_results.get(txn.txn_id)
            if cached is not None:
                # every shard the original commit touched sealed the
                # txn_id with ITS OWN seq — reconstruct the per-shard
                # vector so batch callers never misattribute the
                # coordinator's seq to shard 0
                seqs = {}
                for i, store in zip(shards, stores):
                    rec = store.txn_results.get(txn.txn_id)
                    if rec is not None:
                        seqs[i] = rec.get("seq", 0)
                return TxnOutcome(
                    txn_id=txn.txn_id, op=cached.get("op", txn.op),
                    seq=cached.get("seq", 0), result=cached.get("result"),
                    duplicate=True, shard_seqs=seqs or None)
            with tracing.correlate(txn.txn_id), \
                    tracing.span("txn.apply_sharded", op=txn.op,
                                 shards=len(shards)):
                result = self._apply_multi(txn, plan)
                seqs = {i: store.note_txn(txn.txn_id, txn.op, result)
                        for i, store in zip(shards, stores)}
        t_sync = time.perf_counter()
        if self.policy.sync_journal:
            for i in shards:
                journal = self.journals[i]
                if journal is not None:
                    journal.sync()
        wall = time.perf_counter() - t0
        self._cross.inc()
        for i in shards:
            self._note_commit(i, wall)
        return TxnOutcome(txn_id=txn.txn_id, op=txn.op,
                          seq=max(seqs.values()), result=result,
                          shard_seqs=seqs,
                          phase_walls={
                              "apply": t_sync - t0,
                              "fsync": time.perf_counter() - t_sync})

    def _apply_multi(self, txn: Transaction, plan: RoutePlan) -> Any:
        """Apply one cross-shard transaction; caller holds every touched
        shard's lock.  Vetoes are raised BEFORE any shard mutates.

        LOCK DISCIPLINE: only PLANNED shards are touched.  An entity
        that migrated to an unplanned shard between plan and
        lock-acquire is simply not covered by this commit (the caller
        retries or observes a partial result) — reaching for an
        unplanned shard's lock here could deadlock against a concurrent
        cross-shard commit holding it while waiting on ours."""
        op, payload = txn.op, txn.payload
        planned = [self.store.shards[i] for i in plan.shards]
        if op == "jobs/submit":
            # all-or-nothing: validate duplicates across every target
            # shard first — shard A must not keep jobs a veto on shard B
            # rejected
            for i in plan.shards:
                sub = plan.per_shard.get(i, {})
                for job in sub.get("jobs", ()):
                    if job.uuid in self.store.shards[i].jobs:
                        raise TransactionVetoed(
                            f"job {job.uuid} already exists")
            for i in plan.shards:
                sub = plan.per_shard.get(i, {})
                self.store.shards[i].submit_jobs(sub.get("jobs", ()),
                                                 sub.get("groups", ()))
            return {"jobs": [j.uuid for j in payload.get("jobs", ())]}
        if op in ("jobs/kill", "group/kill"):
            if op == "group/kill":
                uuids = []
                for guuid in payload["groups"]:
                    group = self.store.groups.get(guuid)
                    if group is None:
                        raise TransactionVetoed(f"no such group {guuid}")
                    uuids.extend(group.job_uuids)
            else:
                uuids = list(payload["uuids"])
            killed = []
            for shard in planned:
                mine = [u for u in uuids if u in shard.jobs]
                if mine:
                    killed.extend(shard.kill_jobs(mine))
            return {"killed": killed}
        if op == "instance/cancel":
            cancelled = []
            for shard in planned:
                cancelled.extend(
                    tid for tid in payload["task_ids"]
                    if tid in shard.instances
                    and shard.mark_instance_cancelled(tid))
            return {"cancelled": cancelled}
        if op == "job/pool-move":
            moved = self._pool_move_planned(payload, plan)
            return {"uuid": payload["uuid"], "pool": payload["pool"],
                    "moved": moved}
        # a future op without a cross-shard rule: apply on the
        # coordinator shard (the router only multi-routes known ops)
        return OPS[op](planned[0], payload)

    def _pool_move_planned(self, payload: dict, plan: RoutePlan) -> bool:
        """Cross-shard pool move restricted to the planned (locked)
        shards; the move sequence itself is the facade's shared
        `move_job_cross_shard` (its lock acquisition is re-entrant
        under our held locks)."""
        uuid, new_pool = payload["uuid"], payload["pool"]
        dst_i = self.store.router.shard_for_pool(new_pool)
        if dst_i not in plan.shards or new_pool not in self.store.pools:
            return False
        src = next((self.store.shards[i] for i in plan.shards
                    if uuid in self.store.shards[i].jobs), None)
        if src is None:
            return False
        dst = self.store.shards[dst_i]
        if src is dst:
            return src.move_job_pool(uuid, new_pool)
        return self.store.move_job_cross_shard(src, dst, uuid, new_pool)

    # ------------------------------------------------------------- views

    def shard_view(self, params) -> list[dict]:
        """Per-shard contention rows for /debug/contention: lock
        profiler snapshot, journal telemetry, commit service-time
        percentiles/burn (`params` is the observatory's
        ContentionParams)."""
        rows = []
        for i, (store, journal) in enumerate(zip(self.store.shards,
                                                 self.journals)):
            profiler = getattr(store._lock, "profiler", None)
            telemetry = getattr(journal, "telemetry", None)
            rows.append({
                "shard": i,
                "last_seq": store.last_seq(),
                "jobs": len(store.jobs),
                "lock": (profiler.snapshot(top=5)
                         if profiler is not None else {"profiled": False}),
                "journal": (telemetry.snapshot()
                            if telemetry is not None else {}),
                "commit_ack": self.commit_ack[i].stats(
                    threshold_s=params.commit_ack_slo_s,
                    budget=params.commit_ack_budget,
                    fast_s=params.burn_fast_s,
                    slow_s=params.burn_slow_s),
            })
        return rows
