"""Replica-served reads: per-shard followers + the staleness contract.

`ShardedJournalFollower` runs one `JournalFollower` per shard, each
tailing its own `?shard=i` feed into its own shard store and journal
segment — shard streams replicate independently, so one slow segment
never holds back the others' reads.

The staleness contract replica reads advertise (rest/api.py serves it):

  * every replica-served heavy read carries `X-Cook-Staleness-Ms` — the
    worst shard's milliseconds since that shard last PROVED it held the
    leader's head — plus `X-Cook-Shard-Staleness` with the per-shard
    split; JSON-object bodies also carry a `staleness_ms` field;
  * staleness is MONOTONE per shard while the shard is behind (it
    counts from the newest freshness proof, so it can only grow until
    the next catch-up);
  * a staleness above the freshness ceiling falls back to the leader
    (307, the existing leader-proxy pattern);
  * a replica that STOPPED APPLYING (no successful leader poll within
    the refuse bound) refuses reads outright (503) — never served
    arbitrarily stale forever.
"""
from __future__ import annotations

from typing import Callable, Optional

from cook_tpu.control.replication import JournalFollower
from cook_tpu.shard.journal import shard_dir
from cook_tpu.shard.store import ShardedStore
from cook_tpu.utils.metrics import global_registry

_STALENESS_GAUGE_NAME = "shard.replica_staleness_ms"


class ShardedJournalFollower:
    """One JournalFollower per shard (same knobs, fanned out)."""

    def __init__(
        self,
        store: ShardedStore,
        *,
        leader_url_fn: Callable[[], str],
        self_url: str = "",
        data_dir: str = "",
        journals: Optional[list] = None,
        as_user: str = "admin",
        poll_s: float = 1.0,
        timeout_s: float = 10.0,
        long_poll_s: Optional[float] = None,
        member_id: str = "",
        on_leader_url: Optional[Callable[[str], None]] = None,
    ):
        self.store = store
        journals = journals or [None] * store.n_shards
        self.followers = [
            JournalFollower(
                shard,
                leader_url_fn=leader_url_fn,
                self_url=self_url,
                data_dir=shard_dir(data_dir, i) if data_dir else "",
                journal=journals[i],
                as_user=as_user,
                poll_s=poll_s,
                timeout_s=timeout_s,
                long_poll_s=long_poll_s,
                member_id=member_id or self_url or "standby",
                # one leader-url refresher is plenty; N followers
                # rewriting the same proxy target would just race
                on_leader_url=on_leader_url if i == 0 else None,
                shard=i,
            )
            for i, shard in enumerate(store.shards)
        ]
        self._staleness_gauge = global_registry.gauge(
            _STALENESS_GAUGE_NAME,
            "ms since this replica's shard last proved it held the "
            "leader's head (per shard)")

    def start(self) -> "ShardedJournalFollower":
        for follower in self.followers:
            follower.start()
        return self

    def stop(self) -> None:
        for follower in self.followers:
            follower.stop()

    def sync_once(self) -> int:
        return sum(f.sync_once() for f in self.followers)

    @property
    def synced_events(self) -> int:
        return sum(f.synced_events for f in self.followers)

    @property
    def full_resyncs(self) -> int:
        return sum(f.full_resyncs for f in self.followers)

    def staleness_view(self) -> dict[int, dict]:
        view: dict[int, dict] = {}
        for i, follower in enumerate(self.followers):
            row = follower.staleness_view()[i]
            staleness = row["staleness_ms"]
            self._staleness_gauge.set(
                staleness if staleness != float("inf") else -1.0,
                {"shard": str(i)})
            view[i] = row
        return view


def evaluate_staleness(view: dict[int, dict], *, ceiling_ms: float,
                       refuse_after_s: float) -> dict:
    """Fold a per-shard staleness view into the read decision:
    {"action": "serve"|"fallback"|"refuse", "staleness_ms": worst,
     "shards": {shard: ms}}.

    Refusal is reserved for a replica that STOPPED APPLYING (no
    successful leader poll within the refuse bound) — it must not serve
    stale forever, and it cannot vouch for a redirect target either.  A
    replica that is merely behind — including a fresh standby still
    catching up a backlog (staleness +inf, polls succeeding) — FALLS
    BACK to the leader instead: that keeps reads available through
    restarts exactly when clients need the redirect."""
    worst = 0.0
    shards: dict[int, float] = {}
    refusing = False
    for shard, row in sorted(view.items()):
        staleness = float(row.get("staleness_ms", float("inf")))
        shards[shard] = staleness
        worst = max(worst, staleness)
        if float(row.get("stalled_s", float("inf"))) >= refuse_after_s:
            refusing = True
    if refusing:
        action = "refuse"
    elif worst > ceiling_ms or worst == float("inf"):
        action = "fallback"
    else:
        action = "serve"
    return {"action": action, "staleness_ms": worst, "shards": shards}
