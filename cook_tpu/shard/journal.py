"""Per-shard journal segments: layout, recovery, snapshots, migration.

On-disk layout (versioned by `manifest.json` so a process can tell the
layouts apart before touching anything):

    data_dir/
      manifest.json                  {"schema": "cook-journal/v2",
                                      "layout": "sharded", "shards": N}
      shards/shard-00/snapshot.json  per-shard snapshot (persistence.py
      shards/shard-00/journal.jsonl   format, unchanged) + segment
      shards/shard-01/...

Each segment is an ordinary `JournalWriter` file — torn-tail truncation,
group fsync, rotation, and the fsync-policy machinery all apply per
shard, and the fault plane's `journal.fsync` point matches on the
segment PATH, which is how the chaos `wedged-shard` drill stalls exactly
one shard.

`migrate_single_journal` converts the original single-journal layout
(snapshot.json + journal.jsonl at the data_dir root) into this one
EXACTLY ONCE: the manifest is the idempotency marker, and the original
files are renamed to `*.premigrate` so a later unsharded process cannot
silently resurrect the pre-migration state.
"""
from __future__ import annotations

import json
import logging
import os
from typing import Optional

from cook_tpu.models import persistence
from cook_tpu.models.store import JobStore
from cook_tpu.shard.router import META_SHARD, ShardRouter
from cook_tpu.shard.store import ShardedStore

log = logging.getLogger(__name__)

MANIFEST_NAME = "manifest.json"
MANIFEST_SCHEMA = "cook-journal/v2"


def shard_dir(data_dir: str, shard: int) -> str:
    return os.path.join(data_dir, "shards", f"shard-{shard:02d}")


def read_manifest(data_dir: str) -> Optional[dict]:
    path = os.path.join(data_dir, MANIFEST_NAME)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        manifest = json.load(f)
    if manifest.get("schema") != MANIFEST_SCHEMA:
        raise ValueError(f"unknown manifest schema in {path}: "
                         f"{manifest.get('schema')!r}")
    return manifest


def write_manifest(data_dir: str, n_shards: int, *,
                   migrated_from: str = "") -> dict:
    manifest = {"schema": MANIFEST_SCHEMA, "layout": "sharded",
                "shards": n_shards}
    if migrated_from:
        manifest["migrated_from"] = migrated_from
    path = os.path.join(data_dir, MANIFEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return manifest


def has_single_journal_layout(data_dir: str) -> bool:
    """An UNMIGRATED single-journal data_dir: root snapshot/journal
    present, no sharded manifest."""
    if read_manifest(data_dir) is not None:
        return False
    return (os.path.exists(os.path.join(data_dir, "snapshot.json"))
            or os.path.exists(os.path.join(data_dir, "journal.jsonl")))


def attach_shard_journals(store: ShardedStore, data_dir: str,
                          **journal_kw) -> list:
    """One JournalWriter per shard, watching ONLY its shard's event
    feed — shard i's commits never touch shard j's file or fsync
    barrier.  Returns the writers in shard order (feed these to
    ShardedTransactionLog)."""
    writers = []
    for i, shard in enumerate(store.shards):
        directory = shard_dir(data_dir, i)
        os.makedirs(directory, exist_ok=True)
        writer = persistence.JournalWriter(
            os.path.join(directory, "journal.jsonl"), **journal_kw)
        shard.add_watcher(writer)
        writers.append(writer)
    write_manifest(data_dir, store.n_shards)
    return writers


def _shard_factory(i: int, clock):
    return lambda: JobStore(clock=clock, lock_name=f"store-s{i}",
                            shard_id=i)


def recover_sharded(data_dir: str, n_shards: int, *,
                    clock=None) -> Optional[ShardedStore]:
    """Rebuild a ShardedStore from the per-shard segments.  The manifest
    shard count wins over the caller's (resharding an existing data_dir
    is a migration, not a config edit).  Returns None on a fresh dir."""
    manifest = read_manifest(data_dir)
    if manifest is not None:
        disk_shards = int(manifest.get("shards", n_shards))
        if disk_shards != n_shards:
            log.warning("data_dir %s holds %d shards; configured %d — "
                        "using the on-disk count (reshard via "
                        "tools/migrate_journal.py)", data_dir,
                        disk_shards, n_shards)
            n_shards = disk_shards
    shards: list[JobStore] = []
    anything = False
    stats = {"snapshot_seq": 0, "journal_replayed": 0}
    for i in range(n_shards):
        recovered = persistence.recover(
            shard_dir(data_dir, i), clock=clock,
            store_factory=_shard_factory(i, clock))
        if recovered is None:
            recovered = _shard_factory(i, clock)()
        else:
            anything = True
            for key in stats:
                stats[key] += recovered.recovered_stats.get(key, 0)
        shards.append(recovered)
    if not anything:
        return None
    store = ShardedStore(n_shards, clock=clock or (lambda: 0),
                         shards=shards)
    store.recovered_stats = stats
    return store


def snapshot_sharded(store: ShardedStore, data_dir: str) -> None:
    """Atomic per-shard snapshots (each shard's journal may then rotate
    independently)."""
    for i, shard in enumerate(store.shards):
        directory = shard_dir(data_dir, i)
        os.makedirs(directory, exist_ok=True)
        persistence.snapshot(shard, os.path.join(directory,
                                                 "snapshot.json"))
    write_manifest(data_dir, store.n_shards)


# ---------------------------------------------------------------- migration


def migrate_single_journal(data_dir: str, n_shards: int, *,
                           clock=None) -> dict:
    """Convert a single-journal data_dir to the per-shard segment layout
    EXACTLY ONCE.  Idempotent: a manifest already on disk means the dir
    is sharded — re-running changes nothing and says so.  The original
    snapshot/journal files are renamed `*.premigrate` (kept for rollback
    and audit, never replayed)."""
    manifest = read_manifest(data_dir)
    if manifest is not None:
        return {"migrated": False, "reason": "already-sharded",
                "shards": int(manifest.get("shards", n_shards))}
    if n_shards < 2:
        raise ValueError("migration target must be >= 2 shards")
    os.makedirs(data_dir, exist_ok=True)
    source = persistence.recover(data_dir, clock=clock)
    if source is None:
        # fresh dir: stamp the layout so every later open agrees
        for i in range(n_shards):
            os.makedirs(shard_dir(data_dir, i), exist_ok=True)
        write_manifest(data_dir, n_shards, migrated_from="fresh")
        return {"migrated": True, "reason": "fresh", "jobs": 0,
                "shards": n_shards}
    router = ShardRouter(n_shards)
    shards = [_shard_factory(i, clock)() for i in range(n_shards)]
    partition = _partition(source, router, shards)
    for i, shard in enumerate(shards):
        directory = shard_dir(data_dir, i)
        os.makedirs(directory, exist_ok=True)
        persistence.snapshot(shard, os.path.join(directory,
                                                 "snapshot.json"))
    for name in ("snapshot.json", "journal.jsonl", "journal.jsonl.1"):
        path = os.path.join(data_dir, name)
        if os.path.exists(path):
            os.replace(path, path + ".premigrate")
    write_manifest(data_dir, n_shards, migrated_from="single")
    log.info("migrated %s to %d journal segments (%d jobs, %d instances)",
             data_dir, n_shards, len(source.jobs), len(source.instances))
    return {"migrated": True, "reason": "single-journal",
            "jobs": len(source.jobs), "instances": len(source.instances),
            "shards": n_shards, **partition}


def _partition(source: JobStore, router: ShardRouter,
               shards: list[JobStore]) -> dict:
    """Scatter a recovered single store's entities onto shard stores by
    the router's rules.  Direct dict fills (no events — the per-shard
    snapshot written right after IS the durable record); per-shard
    submission order preserves the source's job_seq order so DRU
    tie-breaks survive the migration."""
    for pool in source.pools.values():
        for shard in shards:
            shard.pools[pool.name] = pool
    per_shard_jobs = [0] * len(shards)
    for uuid in sorted(source.jobs,
                       key=lambda u: source.job_seq.get(u, 0)):
        job = source.jobs[uuid]
        i = router.shard_for_pool(job.pool)
        shard = shards[i]
        shard.jobs[uuid] = job
        shard.job_seq[uuid] = len(shard.job_seq)
        shard._index_job(job, None)
        per_shard_jobs[i] += 1
    for task_id, inst in source.instances.items():
        owner = None
        for shard in shards:
            if inst.job_uuid in shard.jobs:
                owner = shard
                break
        (owner or shards[META_SHARD]).instances[task_id] = inst
    for guuid, group in source.groups.items():
        owner = shards[META_SHARD]
        for member in group.job_uuids:
            job = source.jobs.get(member)
            if job is not None:
                owner = shards[router.shard_for_pool(job.pool)]
                break
        owner.groups[guuid] = group
    for (user, pool), share in source.shares.items():
        shards[router.shard_for_pool(pool)].shares[(user, pool)] = share
    for (user, pool), quota in source.quotas.items():
        shards[router.shard_for_pool(pool)].quotas[(user, pool)] = quota
    meta = shards[META_SHARD]
    meta.dynamic_config = dict(source.dynamic_config)
    meta.capacity_ledger = {k: dict(v)
                            for k, v in source.capacity_ledger.items()}
    # the idempotency table replicates to EVERY shard: a retried commit
    # routes by its op's keys, and whichever shard it lands on must
    # answer from the recorded outcome, not re-apply
    for shard in shards:
        shard.txn_results.update(source.txn_results)
    return {"per_shard_jobs": per_shard_jobs}
