"""ShardRouter: deterministic txn-op -> shard mapping.

Routing policy (the ISSUE-14 contract):

  * pool-scoped keys (a job's pool, a share/quota's pool) route by a
    stable hash of the pool name — the match cycle iterates pools, so
    binding a pool to one shard gives every per-pool read a single-shard
    snapshot;
  * pool-less keys fall back to a stable hash of the user;
  * global state (dynamic config, the elastic capacity ledger, pool
    metadata writes) lives on the META shard (shard 0) — tiny, rarely
    written, and a single owner keeps replay trivial.

Hashes are `zlib.crc32` (NOT Python's salted `hash()`): the mapping
must be identical across processes and restarts, or journal-segment
recovery would scatter entities onto the wrong shards.
"""
from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Optional, Sequence

# the shard that owns global state: dynamic config, capacity ledger,
# and pool metadata writes (pool metadata is also mirrored to every
# shard so per-shard validation never crosses shards)
META_SHARD = 0


class MisroutedKey(Exception):
    """A key whose owning shard this process does not serve.

    Raised by the multi-process runtime's group-scoped router
    (cook_tpu/mp/topology.py) when a request reaches a worker that owns
    only a subset of the global shard space — the symptom of a stale
    front-end route map or a client bypassing the front end with an old
    shard map.  The REST layer answers it with 421 Misdirected Request
    plus the owning shard, so the caller can refresh its map and retry
    instead of silently writing the key into the wrong journal segment.
    """

    def __init__(self, key: str, owner_shard: int,
                 owned: Sequence[int] = ()):
        self.key = key
        self.owner_shard = owner_shard
        self.owned = tuple(owned)
        super().__init__(
            f"{key} routes to shard {owner_shard}, which this process "
            f"does not serve (serving shards {list(self.owned)})")


def _stable_hash(key: str) -> int:
    return zlib.crc32(key.encode("utf-8")) & 0xFFFFFFFF


@dataclass(frozen=True)
class RoutePlan:
    """Where one transaction applies.

    `shards` is ascending and deduplicated; a single-element plan is the
    common fast path (one lock, one journal segment).  Multi-shard plans
    apply in shard order (the fixed global order that makes two
    concurrent cross-shard commits deadlock-free) and acknowledge once.
    `per_shard` optionally carries the payload split (e.g. a submit
    batch partitioned by pool).
    """

    shards: tuple[int, ...]
    per_shard: dict = field(default_factory=dict)

    @property
    def single(self) -> Optional[int]:
        return self.shards[0] if len(self.shards) == 1 else None


class ShardRouter:
    def __init__(self, n_shards: int):
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.n_shards = n_shards

    # ------------------------------------------------------------- keys

    def shard_for_pool(self, pool: str) -> int:
        return _stable_hash(f"pool:{pool}") % self.n_shards

    def shard_for_user(self, user: str) -> int:
        """Fallback for pool-less keys."""
        return _stable_hash(f"user:{user}") % self.n_shards

    def pools_for_distinct_shards(self, prefix: str = "pool",
                                  n: Optional[int] = None) -> list[str]:
        """n pool names that each land on a DIFFERENT shard (shard i gets
        one pool), found by probing the stable hash.  The chaos
        `wedged-shard` drill and the sharded loadtest use this so a
        per-pool traffic split is also a per-shard split."""
        n = self.n_shards if n is None else n
        if n > self.n_shards:
            raise ValueError(f"cannot spread {n} pools over "
                             f"{self.n_shards} shards distinctly")
        found: dict[int, str] = {}
        i = 0
        while len(found) < n:
            name = f"{prefix}{i}"
            found.setdefault(self.shard_for_pool(name), name)
            i += 1
        return [found[s] for s in sorted(found)][:n]

    # ------------------------------------------------------------- plans

    def plan(self, op: str, payload: dict, store) -> RoutePlan:
        """The shard set one transaction touches.  `store` resolves
        entity -> pool lookups (a kill names job uuids, not pools)."""
        if op == "jobs/submit":
            by_shard: dict[int, dict] = {}
            for job in payload.get("jobs", ()):
                shard = self.shard_for_pool(job.pool)
                entry = by_shard.setdefault(shard,
                                            {"jobs": [], "groups": []})
                entry["jobs"].append(job)
            groups = list(payload.get("groups", ()))
            if not by_shard:
                return RoutePlan(shards=(META_SHARD,))
            # groups ride with the lowest shard their jobs touch: a
            # group's jobs may span shards, but group metadata is small
            # and group-kill resolves membership per job anyway
            first = min(by_shard)
            by_shard[first]["groups"] = groups
            return RoutePlan(shards=tuple(sorted(by_shard)),
                             per_shard=by_shard)
        if op in ("jobs/kill", "group/kill"):
            shards = self._shards_for_jobs(
                self._kill_job_uuids(op, payload, store), store)
            return RoutePlan(shards=shards or (META_SHARD,))
        if op == "job/retry":
            return RoutePlan(shards=self._shards_for_jobs(
                [payload["uuid"]], store) or (META_SHARD,))
        if op == "job/pool-move":
            # the cross-shard case: the job's CURRENT shard plus the
            # destination pool's shard, applied in shard order with one
            # client-visible ack (txn.py)
            src = self._shards_for_jobs([payload["uuid"]], store)
            dst = self.shard_for_pool(payload["pool"])
            shards = tuple(sorted(set(src) | {dst}))
            return RoutePlan(shards=shards or (dst,))
        if op in ("share/set", "share/retract", "quota/set",
                  "quota/retract"):
            pool = self._share_quota_pool(op, payload)
            if pool is not None:
                return RoutePlan(shards=(self.shard_for_pool(pool),))
            user = self._share_quota_user(op, payload)
            return RoutePlan(shards=(self.shard_for_user(user or ""),))
        if op == "instance/cancel":
            jobs = []
            for task_id in payload.get("task_ids", ()):
                inst = store.instances.get(task_id)
                if inst is not None:
                    jobs.append(inst.job_uuid)
            return RoutePlan(shards=self._shards_for_jobs(jobs, store)
                             or (META_SHARD,))
        # global ops: config/update, pool/capacity-delta, and anything a
        # future op registers without a routing rule — one owner, the
        # meta shard, keeps ordering and replay trivial
        return RoutePlan(shards=(META_SHARD,))

    # ---------------------------------------------------------- helpers

    def _kill_job_uuids(self, op: str, payload: dict, store) -> list[str]:
        if op == "jobs/kill":
            return list(payload.get("uuids", ()))
        uuids: list[str] = []
        for guuid in payload.get("groups", ()):
            group = store.groups.get(guuid)
            if group is not None:
                uuids.extend(group.job_uuids)
        return uuids

    def _shards_for_jobs(self, uuids: Sequence[str],
                         store) -> tuple[int, ...]:
        shards = set()
        for uuid in uuids:
            job = store.jobs.get(uuid)
            if job is not None:
                shards.add(self.shard_for_pool(job.pool))
            else:
                # unknown job: the op handler will veto; route it
                # somewhere deterministic so the veto is consistent
                shards.add(self.shard_for_user(uuid))
        return tuple(sorted(shards))

    @staticmethod
    def _share_quota_pool(op: str, payload: dict) -> Optional[str]:
        if op == "share/set":
            return payload["share"].pool
        if op == "quota/set":
            return payload["quota"].pool
        return payload.get("pool")

    @staticmethod
    def _share_quota_user(op: str, payload: dict) -> Optional[str]:
        if op == "share/set":
            return payload["share"].user
        if op == "quota/set":
            return payload["quota"].user
        return payload.get("user")
