"""Journal replication: standbys tail the leader's committed-event feed.

Reference: Datomic is an external, REPLICATED source of truth — every
scheduler node sees the same transaction log, and leader failover simply
replays state from the DB (datomic.clj:45-127, the tx-report mult at
:49; kubernetes/compute_cluster.clj:269).  This rebuild's store persists
to the leader's local disk, so without replication a dead leader machine
takes the cluster state with it.  `JournalFollower` closes that gap: a
standby polls the leader's `/replication/journal` feed (rest/api.py),
applies the events to its own in-memory store, and appends them to its
OWN on-disk journal — so promotion works entirely from the standby's
local copy, and the old leader's data directory can be lost outright.

Bootstrap / gap handling: when the leader reports `snapshot_required`
(the follower is behind the leader's retained event window — e.g. a
fresh standby, or a leader that itself just recovered from disk), the
follower fetches `/replication/snapshot`, rebuilds its store in place,
rewrites its local snapshot file, and rotates its journal — then resumes
tailing from the snapshot's sequence number.

The follower also refreshes `api.leader_url` each poll so a standby's
REST layer always proxies to the CURRENT leader (the reference's
leader-proxying, rest/api.clj:2408).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Optional

from cook_tpu import faults
from cook_tpu.models import persistence
from cook_tpu.models.store import JobStore
from cook_tpu.utils.metrics import global_registry
from cook_tpu.utils.retry import RetryPolicy, backoff_s

log = logging.getLogger(__name__)

# follower-side apply walls: a batch is normally sub-ms, but a snapshot-
# sized backlog page can take seconds
_APPLY_BUCKETS = (1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                  30.0, float("inf"))


class JournalFollower:
    def __init__(
        self,
        store: JobStore,
        *,
        leader_url_fn: Callable[[], str],
        self_url: str = "",
        data_dir: str = "",
        journal: Optional[persistence.JournalWriter] = None,
        as_user: str = "admin",
        poll_s: float = 1.0,
        timeout_s: float = 10.0,
        long_poll_s: Optional[float] = None,
        member_id: str = "",
        on_leader_url: Optional[Callable[[str], None]] = None,
        reconnect_policy: Optional[RetryPolicy] = None,
        shard: Optional[int] = None,
    ):
        self.store = store
        # sharded control plane (cook_tpu/shard/): this follower tails
        # ONE shard's journal segment (`?shard=` on the feed/snapshot
        # endpoints, `shard` in every ack).  None = the unsharded feed.
        self.shard = shard
        self.leader_url_fn = leader_url_fn
        self.self_url = self_url.rstrip("/")
        self.data_dir = data_dir
        self.journal = journal
        self.as_user = as_user
        self.poll_s = poll_s
        self.timeout_s = timeout_s
        # long-poll window: the journal request parks on the leader until
        # the next commit, so replication is push-like.  Must stay under
        # timeout_s or an idle long-poll reads as a transport error.
        self.long_poll_s = (max(0.0, timeout_s - 2.0)
                            if long_poll_s is None else long_poll_s)
        self.member_id = member_id or self.self_url or "standby"
        self._last_acked = -1
        self.on_leader_url = on_leader_url
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # the leader incarnation the feed we're tailing belongs to: event
        # sequence numbers are only comparable within one leader history,
        # so a change (failover, or a leader restarted from its own disk)
        # forces a snapshot bootstrap rather than risking silent
        # divergence (a deposed leader may hold committed events the new
        # leader never saw)
        self._leader_incarnation: Optional[str] = None
        # observability for tests/debug endpoints
        self.synced_events = 0
        self.full_resyncs = 0
        self.last_error: str = ""
        # correlation: txn_id of the newest txn/committed event applied —
        # rides in every ack so the leader can tie a replication ack back
        # to the mutation it makes durable (docs/observability.md)
        self.last_txn_id: str = ""
        # reconnect backoff: on leader transport errors the poll loop
        # backs off with jittered exponential delays (capped) instead of
        # retrying tight at poll_s — a dead leader with N standbys must
        # not eat N tight retry loops of connection attempts.  The
        # max_attempts bound is irrelevant here (the loop retries until
        # stopped); only the delay curve is used.
        self.reconnect_policy = reconnect_policy or RetryPolicy(
            base_s=max(poll_s, 0.2), multiplier=2.0, cap_s=30.0,
            jitter=0.5)
        self._consecutive_failures = 0
        self._transport_error = False
        self.reconnect_attempts = 0  # lifetime total, tests/chaos read it
        # replica-read staleness (cook_tpu/shard/replica.py): when this
        # follower last PROVED it held the leader's head (applied >= the
        # feed's last_seq on a successful poll), and when it last made
        # any successful poll at all.  Replica-served reads bound their
        # staleness from the first and refuse off the second.
        self._fresh_at: Optional[float] = None
        self._last_progress: Optional[float] = None
        self._reconnects = global_registry.counter(
            "replication.reconnects",
            "follower reconnect attempts after leader transport errors")

    # ------------------------------------------------------------- transport

    def _get(self, url: str, *, timeout_s: Optional[float] = None
             ) -> Optional[dict]:
        req = urllib.request.Request(
            url, headers={"X-Cook-Requesting-User": self.as_user})
        try:
            # fault point: a dropped fetch (error mode) takes the exact
            # transport-failure path below; a delay rule is a slow link
            # or wedged follower
            fault_schedule = faults.ACTIVE
            if fault_schedule is not None:
                fault_schedule.hit(faults.REPLICATION_FETCH,
                                   follower=self.member_id)
            with urllib.request.urlopen(
                    req, timeout=timeout_s or self.timeout_s) as r:
                return json.loads(r.read())
        except (urllib.error.URLError, OSError, ValueError) as e:
            self.last_error = str(e)
            self._transport_error = True
            return None

    def _post(self, url: str, payload: dict) -> Optional[dict]:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"X-Cook-Requesting-User": self.as_user,
                     "Content-Type": "application/json"}, method="POST")
        try:
            fault_schedule = faults.ACTIVE
            if fault_schedule is not None:
                fault_schedule.hit(faults.REPLICATION_ACK,
                                   follower=self.member_id)
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return json.loads(r.read())
        except (urllib.error.URLError, OSError, ValueError) as e:
            self.last_error = str(e)
            self._transport_error = True
            return None

    # ------------------------------------------------------------------ sync

    def sync_once(self) -> int:
        """One poll: fetch and apply everything the leader has past our
        sequence number.  Returns the number of events applied."""
        leader = (self.leader_url_fn() or "").rstrip("/")
        if self.on_leader_url is not None:
            self.on_leader_url(leader)
        if not leader or leader == self.self_url:
            return 0
        applied = 0
        first_fetch = True
        while not self._stop.is_set():
            after = self.store.last_seq()
            # only the first fetch of a cycle long-polls: follow-up pages
            # of a backlog should stream back-to-back
            wait_s = self.long_poll_s if first_fetch else 0.0
            first_fetch = False
            shard_q = f"&shard={self.shard}" if self.shard is not None \
                else ""
            resp = self._get(
                f"{leader}/replication/journal?after_seq={after}"
                f"&wait_s={wait_s}{shard_q}",
                timeout_s=self.timeout_s + wait_s)
            # a response landing after stop() is the promotion race: we
            # may already be (about to be) the leader, and a reply from a
            # still-alive deposed leader must not clobber our state
            if resp is None or self._stop.is_set():
                break
            import time as _time

            self._last_progress = _time.monotonic()
            incarnation = resp.get("incarnation")
            if incarnation and self._leader_incarnation not in (
                    None, incarnation):
                log.info("replication: leader incarnation changed %s -> %s;"
                         " forcing snapshot bootstrap",
                         self._leader_incarnation, incarnation)
                if not self._full_resync(leader):
                    break
                continue
            if incarnation:
                self._leader_incarnation = incarnation
            if resp.get("snapshot_required"):
                if not self._full_resync(leader):
                    break
                continue
            events = resp.get("events", [])
            if events:
                applied += self._apply(events)
            # freshness proof: our applied head covers the feed's head
            # at the moment the leader answered — staleness_ms() counts
            # from the newest such proof
            if self.store.last_seq() >= int(resp.get("last_seq", 0)):
                self._fresh_at = _time.monotonic()
            if not resp.get("more"):
                break
        # confirm what we hold: sync-ack commits on the leader block
        # until a standby's ack covers them (rest/api.py:_await_replication).
        # Only a follower with a local journal/data_dir may claim the
        # durable flag — "applied AND journaled locally" — a memory-only
        # follower's ack must not satisfy the leader's durability bound
        # (the leader skips non-durable acks when counting min_acks).
        if not self._stop.is_set():
            seq = self.store.last_seq()
            if seq != self._last_acked and leader:
                durable = self.is_durable()
                if durable and self.journal is not None:
                    # the durable claim is "on OUR disk": group-fsync the
                    # journal BEFORE the ack leaves, or an OS crash after
                    # the ack could still lose the write the leader just
                    # told its client was replicated
                    self.journal.sync()
                ack = {"follower": self.member_id, "seq": seq,
                       "durable": durable,
                       "last_txn_id": self.last_txn_id,
                       # fleet federation (obs/fleet.py): the ack doubles
                       # as peer registration — the leader's fleet
                       # observatory polls this URL for health/staleness
                       "url": self.self_url}
                if self.shard is not None:
                    ack["shard"] = self.shard
                if self._post(f"{leader}/replication/ack", ack):
                    self._last_acked = seq
                    # one correlation event per txn: later acks driven by
                    # non-txn events (status updates) must not keep
                    # re-attributing themselves to this transaction
                    self.last_txn_id = ""
        return applied

    def is_durable(self) -> bool:
        """Whether acks may claim "journaled locally": this follower
        persists what it applies (an attached journal writer, or a
        data_dir it snapshots into)."""
        return self.journal is not None or bool(self.data_dir)

    def _apply(self, events: list[dict]) -> int:
        import time as _time

        # live mode: each entry becomes an ordinary committed event on our
        # store — retained in the event window and fanned out to watchers
        # (columnar index, attached journal writer, passport), so the
        # standby's derived state tracks the leader continuously and
        # promotion needs no rebuild.  Journal persistence rides the
        # watcher fan-out (persistence.attach_journal), same as a local
        # transaction.
        t0 = _time.perf_counter()
        with self.store._lock:
            applied = persistence.apply_journal(self.store, events,
                                                live=True)
        global_registry.histogram(
            "replication.apply_seconds",
            "follower wall seconds applying one replicated event batch",
            buckets=_APPLY_BUCKETS).observe(_time.perf_counter() - t0)
        global_registry.counter(
            "replication.events_applied",
            "events this follower applied from the leader's feed").inc(
            applied)
        for e in reversed(events):
            if e.get("kind") == "txn/committed":
                txn_id = (e.get("data") or {}).get("txn_id")
                if txn_id:
                    self.last_txn_id = txn_id
                break
        self.synced_events += applied
        return applied

    def _full_resync(self, leader: str) -> bool:
        shard_q = f"?shard={self.shard}" if self.shard is not None else ""
        state = self._get(f"{leader}/replication/snapshot{shard_q}")
        if state is None or "seq" not in state or self._stop.is_set():
            return False
        if state.get("incarnation"):
            self._leader_incarnation = state["incarnation"]
        # the pre-resync correlation id belongs to a history this snapshot
        # supersedes; carrying it into the next ack would misattribute
        # which txn the ack makes durable
        self.last_txn_id = ""
        global_registry.counter(
            "replication.full_resyncs",
            "snapshot bootstraps this follower performed").inc()
        persistence.restore_into(self.store, state)
        if self.data_dir:
            # the local snapshot now IS the bootstrap point; the journal
            # restarts from here (the rotated segment only held pre-resync
            # entries that the new snapshot supersedes)
            persistence.snapshot(self.store,
                                 os.path.join(self.data_dir,
                                              "snapshot.json"))
            if self.journal is not None:
                self.journal.rotate()
        self.full_resyncs += 1
        import time as _time

        # the snapshot IS the leader's head as of the fetch
        now = _time.monotonic()
        self._fresh_at = now
        self._last_progress = now
        log.info("replication: full resync from %s at seq %s", leader,
                 state["seq"])
        return True

    # ------------------------------------------------------- staleness
    # Replica-served reads (cook_tpu/shard/replica.py, rest/api.py):
    # how stale is the state this follower serves, and is it still
    # applying at all.

    def staleness_ms(self, now: Optional[float] = None) -> float:
        """Milliseconds since this follower last PROVED it held the
        leader's head.  +inf before the first proof (a replica that
        never synced must not serve 'slightly stale' reads).  Monotone
        while the follower is behind; resets on catch-up."""
        import time as _time

        if self._fresh_at is None:
            return float("inf")
        now = _time.monotonic() if now is None else now
        return max(0.0, (now - self._fresh_at) * 1000.0)

    def stalled_s(self, now: Optional[float] = None) -> float:
        """Seconds since the last successful leader poll — the
        stopped-applying signal replica reads refuse on."""
        import time as _time

        if self._last_progress is None:
            return float("inf")
        now = _time.monotonic() if now is None else now
        return max(0.0, now - self._last_progress)

    def staleness_view(self) -> dict[int, dict]:
        """Per-shard staleness rows ({shard: row}); an unsharded
        follower is shard 0 of a 1-shard view."""
        shard = self.shard if self.shard is not None else 0
        return {shard: {
            "staleness_ms": self.staleness_ms(),
            "stalled_s": self.stalled_s(),
            "applied_seq": self.store.last_seq(),
        }}

    # --------------------------------------------------------------- running

    def _next_wait_s(self, cycle_elapsed_s: float = 0.0) -> float:
        """Poll interval for the next cycle: poll_s while healthy,
        jittered exponential backoff (capped) after leader transport
        errors — the follower must not hammer a dead or flapping leader
        at full poll rate.  The delay is measured from cycle START: a
        fetch that burned `timeout_s` before failing already served as
        its own backoff (the tight-retry risk only exists for cycles
        that fail fast, e.g. connection-refused from a dead leader)."""
        if self._consecutive_failures == 0:
            return self.poll_s
        delay = backoff_s(self.reconnect_policy,
                          self._consecutive_failures)
        return max(self.poll_s, delay - cycle_elapsed_s)

    def _note_cycle_outcome(self) -> None:
        if self._transport_error:
            self._transport_error = False
            self._consecutive_failures += 1
            self.reconnect_attempts += 1
            self._reconnects.inc()
        else:
            self._consecutive_failures = 0

    def start(self) -> "JournalFollower":
        import time as _time

        def loop():
            wait_s = self.poll_s
            while not self._stop.wait(wait_s):
                self._transport_error = False
                t0 = _time.monotonic()
                try:
                    self.sync_once()
                except OSError:
                    # a transport failure that escaped _get/_post's own
                    # handling: back off like any other reconnect
                    log.exception("journal follower sync failed "
                                  "(transport)")
                    self._transport_error = True
                except Exception:  # noqa: BLE001 — a standby's sync loop
                    # must survive any leader hiccup; an APPLY failure is
                    # not a transport error, so it retries at the normal
                    # poll cadence and stays out of the reconnect
                    # counter (the backoff would stretch replication lag
                    # to cap_s while pointing operators at the network)
                    log.exception("journal follower sync failed (apply)")
                self._note_cycle_outcome()
                wait_s = self._next_wait_s(_time.monotonic() - t0)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="journal-follower")
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop tailing and JOIN the sync thread fully.  The join timeout
        must cover the longest possible in-flight fetch — a long-poll
        parks on the leader for long_poll_s on top of the transport
        timeout (sync_once passes timeout_s + wait_s to urlopen) — plus
        slack: promotion calls this before taking writes, and a late
        response from a deposed leader applying after promotion would
        clobber the new leader's state (the sync loop also re-checks
        _stop after every fetch as a second line of defense)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s + self.long_poll_s + 5)
