"""Standalone lease service: the ZooKeeper role in leader election.

Reference: Cook elects a single leader through Curator/ZooKeeper
(/root/reference/scheduler/src/cook/mesos.clj:153-328,
components.clj:154) — an *external* coordination service holds a
session-scoped lease; whichever scheduler holds it runs the scheduling
loops and the rest hot-stand-by.  This module is the TPU-native
deployment's equivalent coordination point: a tiny HTTP lease service
(deployed once per cell, like ZK) that grants TTL leases with fencing
tokens.  Schedulers talk to it via `HttpLeaseElector`
(cook_tpu.control.leader), which needs only outbound HTTP — two
schedulers on different machines with nothing shared but this service's
address elect exactly one leader.

Design points (vs the FileLeaseElector it supersedes):
  * TTLs are measured on the SERVER's monotonic clock — client clock
    skew cannot extend or shorten a lease.
  * Every grant carries a monotonically increasing fencing token
    (`epoch`); heartbeats must present it, so a deposed leader whose
    heartbeat raced a takeover is told "lost", never silently re-seated.
  * State is in-memory: if the lease service restarts, leases lapse and
    the sitting leader re-acquires within one heartbeat — the same
    availability story as a ZK session bounce.

Run standalone:  python -m cook_tpu.control.lease_server --port 12340
"""
from __future__ import annotations

import argparse
import json
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


# server-side TTL ceiling (seconds): leases must lapse fast enough for
# failover to be useful no matter what a client asks for
MAX_TTL_S = 60.0


@dataclass
class _Lease:
    leader: str
    url: str
    epoch: int
    deadline: float  # server-monotonic expiry


@dataclass
class LeaseTable:
    """The lease state machine, transport-independent (tested directly)."""

    clock: callable = time.monotonic
    _leases: dict[str, _Lease] = field(default_factory=dict)
    _epoch: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def acquire(self, group: str, member: str, url: str,
                ttl_s: float) -> dict:
        with self._lock:
            now = self.clock()
            lease = self._leases.get(group)
            if lease is not None and lease.deadline > now \
                    and lease.leader != member:
                return {"acquired": False, "leader": lease.leader,
                        "url": lease.url}
            # fresh grant OR the sitting leader re-acquiring: both bump
            # the epoch so stale heartbeats from a previous incarnation
            # of the same member (a restarted process) are fenced off
            self._epoch += 1
            self._leases[group] = _Lease(leader=member, url=url,
                                         epoch=self._epoch,
                                         deadline=now + ttl_s)
            # the EFFECTIVE (possibly clamped) TTL goes back to the
            # client: the elector's unreachable-service grace window must
            # match what the server actually granted, or a clamped lease
            # leaves the old leader believing it holds a longer one — a
            # two-leader window
            return {"acquired": True, "leader": member, "url": url,
                    "epoch": self._epoch, "ttl_s": ttl_s}

    def heartbeat(self, group: str, member: str, epoch: int,
                  ttl_s: float) -> dict:
        with self._lock:
            now = self.clock()
            lease = self._leases.get(group)
            if lease is None or lease.leader != member \
                    or lease.epoch != epoch or lease.deadline <= now:
                current = lease.leader if lease is not None \
                    and lease.deadline > now else None
                return {"ok": False, "leader": current}
            lease.deadline = now + ttl_s
            return {"ok": True, "leader": member, "ttl_s": ttl_s}

    def release(self, group: str, member: str, epoch: int) -> dict:
        with self._lock:
            lease = self._leases.get(group)
            if lease is not None and lease.leader == member \
                    and lease.epoch == epoch:
                del self._leases[group]
                return {"released": True}
            return {"released": False}

    def current(self, group: str) -> dict:
        with self._lock:
            lease = self._leases.get(group)
            if lease is None or lease.deadline <= self.clock():
                return {"leader": None, "url": ""}
            return {"leader": lease.leader, "url": lease.url,
                    "epoch": lease.epoch}


class _Handler(BaseHTTPRequestHandler):
    table: LeaseTable  # set by serve()

    def log_message(self, fmt, *args):  # quiet
        pass

    def _json(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        from urllib.parse import parse_qs, urlparse

        parsed = urlparse(self.path)
        if parsed.path == "/leader":
            q = parse_qs(parsed.query)
            group = (q.get("group") or ["cook"])[0]
            return self._json(200, self.table.current(group))
        if parsed.path == "/healthz":
            return self._json(200, {"ok": True})
        return self._json(404, {"error": "unknown path"})

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0) or 0)
        try:
            body = json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError:
            return self._json(400, {"error": "malformed JSON"})
        group = str(body.get("group", "cook"))
        member = str(body.get("member", ""))
        if not member:
            return self._json(400, {"error": "member required"})
        try:
            ttl = float(body.get("ttl_s", 10.0))
            epoch = int(body.get("epoch", 0))
        except (TypeError, ValueError):
            return self._json(400, {"error": "malformed ttl_s/epoch"})
        # clamp: one buggy/malicious acquire with a huge TTL would lock
        # the group to a dead member until the service restarts,
        # defeating the fail-fast design
        ttl = max(0.5, min(ttl, MAX_TTL_S))
        if self.path == "/acquire":
            return self._json(200, self.table.acquire(
                group, member, str(body.get("url", "")), ttl))
        if self.path == "/heartbeat":
            return self._json(200, self.table.heartbeat(
                group, member, epoch, ttl))
        if self.path == "/release":
            return self._json(200, self.table.release(group, member, epoch))
        return self._json(404, {"error": "unknown path"})


class LeaseServer:
    """In-process harness (tests / embedded deployments)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 clock=time.monotonic):
        self.table = LeaseTable(clock=clock)
        handler = type("BoundHandler", (_Handler,), {"table": self.table})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "LeaseServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="lease-server")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=12340)
    args = parser.parse_args(argv)
    server = LeaseServer(args.host, args.port)
    print(f"lease server listening on {server.url}", flush=True)
    try:
        server._thread = threading.current_thread()
        server._httpd.serve_forever()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
