"""Leader election: single active scheduler with hot standbys.

Reference: Curator/ZooKeeper LeaderSelector
(/root/reference/scheduler/src/cook/mesos.clj:153-328 +
components.clj:154): one instance leads and runs the scheduling loops;
standbys wait; on leadership loss the process fail-fast exits so a
supervisor restarts it clean (mesos.clj:296-313 — restarting state is
error-prone, a fresh process is safer).

Implementations:
  * InMemoryElector — single-process/tests.
  * FileLeaseElector — multi-process on one filesystem: an O_EXCL lease
    file with heartbeat timestamps; standbys take over when the lease
    goes stale.
  * HttpLeaseElector — the production path (the ZK-session analog):
    leases held by an external lease service
    (cook_tpu.control.lease_server) over plain HTTP, so two schedulers
    on different machines with NO shared filesystem elect exactly one
    leader.  Server-side TTLs + fencing epochs; network partitions from
    the lease service dethrone the leader after one TTL (fail-fast,
    mesos.clj:296-313).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.error
import urllib.request
from abc import ABC, abstractmethod
from typing import Callable, Optional

from cook_tpu import faults

log = logging.getLogger(__name__)


class LeaderElector(ABC):
    """`start` runs until leadership is lost, then calls on_loss — the
    caller is expected to exit the process (fail-fast)."""

    @abstractmethod
    def try_acquire(self) -> bool: ...

    @abstractmethod
    def heartbeat(self) -> bool:
        """Refresh the lease; False if leadership was lost."""

    @abstractmethod
    def release(self) -> None: ...

    @abstractmethod
    def current_leader(self) -> Optional[str]: ...


class InMemoryElector(LeaderElector):
    _leaders: dict[str, str] = {}
    _lock = threading.Lock()

    def __init__(self, group: str, member_id: str):
        self.group = group
        self.member_id = member_id

    def try_acquire(self) -> bool:
        with self._lock:
            if self._leaders.get(self.group) in (None, self.member_id):
                self._leaders[self.group] = self.member_id
                return True
            return False

    def heartbeat(self) -> bool:
        with self._lock:
            return self._leaders.get(self.group) == self.member_id

    def release(self) -> None:
        with self._lock:
            if self._leaders.get(self.group) == self.member_id:
                del self._leaders[self.group]

    def current_leader(self) -> Optional[str]:
        with self._lock:
            return self._leaders.get(self.group)


class FileLeaseElector(LeaderElector):
    def __init__(self, lease_path: str, member_id: str,
                 *, ttl_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic,
                 advertised_url: str = ""):
        self.lease_path = lease_path
        self.member_id = member_id
        self.ttl_s = ttl_s
        self.clock = clock
        # published in the lease so standbys can proxy to the leader
        self.advertised_url = advertised_url

    def _read(self) -> Optional[dict]:
        try:
            with open(self.lease_path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    def _write(self) -> None:
        tmp = f"{self.lease_path}.{self.member_id}.tmp"
        with open(tmp, "w") as f:
            json.dump({"leader": self.member_id, "t": self.clock(),
                       "url": self.advertised_url}, f)
        os.replace(tmp, self.lease_path)

    def current_leader_url(self) -> str:
        lease = self._read()
        if lease is None or self.clock() - lease["t"] > self.ttl_s:
            return ""
        return lease.get("url", "")

    def try_acquire(self) -> bool:
        lease = self._read()
        now = self.clock()
        if lease is None or lease["leader"] == self.member_id \
                or now - lease["t"] > self.ttl_s:
            self._write()
            # re-read to detect a concurrent writer that beat us
            lease = self._read()
            return lease is not None and lease["leader"] == self.member_id
        return False

    def heartbeat(self) -> bool:
        lease = self._read()
        if lease is None or lease["leader"] != self.member_id:
            return False
        self._write()
        return True

    def release(self) -> None:
        lease = self._read()
        if lease is not None and lease["leader"] == self.member_id:
            try:
                os.unlink(self.lease_path)
            except FileNotFoundError:
                pass

    def current_leader(self) -> Optional[str]:
        lease = self._read()
        if lease is None or self.clock() - lease["t"] > self.ttl_s:
            return None
        return lease["leader"]


class HttpLeaseElector(LeaderElector):
    """Lease-service-backed elector (cook_tpu.control.lease_server).

    Loss semantics mirror a ZK session: a heartbeat the service answers
    with ok=false (someone else holds the lease, or our fencing epoch is
    stale) is a DEFINITIVE loss.  A heartbeat that cannot reach the
    service at all is indeterminate — the lease may still be ours — so
    leadership survives transient partitions up to one TTL past the last
    confirmed renewal; beyond that the service may have re-granted the
    lease, and we must fail fast rather than risk two leaders.

    The TTL used for that grace window is the EFFECTIVE one the service
    reports back in /acquire and /heartbeat responses (the server clamps
    requested TTLs, lease_server.MAX_TTL_S): grace-checking against a
    configured-but-clamped TTL would keep a partitioned leader seated
    after the service already re-granted the lease — a two-leader
    window.
    """

    def __init__(self, endpoint: str, group: str, member_id: str,
                 *, ttl_s: float = 10.0, advertised_url: str = "",
                 timeout_s: float = 3.0,
                 clock: Callable[[], float] = time.monotonic):
        self.endpoint = endpoint.rstrip("/")
        self.group = group
        self.member_id = member_id
        self.ttl_s = ttl_s
        self.advertised_url = advertised_url
        self.timeout_s = timeout_s
        self.clock = clock
        self._epoch = 0
        self._last_renewal: Optional[float] = None
        # effective TTL granted by the service (it may clamp ttl_s);
        # adopted from every /acquire and /heartbeat response
        self.effective_ttl_s = ttl_s

    def _post(self, path: str, payload: dict) -> Optional[dict]:
        req = urllib.request.Request(
            self.endpoint + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return json.loads(r.read())
        except (urllib.error.URLError, OSError, ValueError):
            return None

    def _get_leader(self) -> Optional[dict]:
        try:
            with urllib.request.urlopen(
                    f"{self.endpoint}/leader?group={self.group}",
                    timeout=self.timeout_s) as r:
                return json.loads(r.read())
        except (urllib.error.URLError, OSError, ValueError):
            return None

    def try_acquire(self) -> bool:
        # measure the lease from BEFORE the request leaves: the server
        # starts the TTL when it processes the request, so stamping the
        # renewal at response time would extend our grace window up to one
        # RTT past server-side expiry — a two-leader window
        t0 = self.clock()
        resp = self._post("/acquire", {
            "group": self.group, "member": self.member_id,
            "url": self.advertised_url, "ttl_s": self.ttl_s})
        if resp is None or not resp.get("acquired"):
            return False
        self._epoch = int(resp.get("epoch", 0))
        self._adopt_ttl(resp)
        self._last_renewal = t0
        return True

    def _adopt_ttl(self, resp: dict) -> None:
        try:
            granted = float(resp.get("ttl_s", self.ttl_s))
        except (TypeError, ValueError):
            return
        if granted > 0:
            self.effective_ttl_s = granted

    def heartbeat(self) -> bool:
        t0 = self.clock()
        resp = self._post("/heartbeat", {
            "group": self.group, "member": self.member_id,
            "epoch": self._epoch, "ttl_s": self.ttl_s})
        if resp is None:
            # indeterminate: the service is unreachable, not lost — keep
            # leading until the lease could actually have lapsed, per the
            # TTL the service actually granted (not the configured ask)
            last = self._last_renewal
            return last is not None and \
                self.clock() - last < self.effective_ttl_s
        if not resp.get("ok"):
            return False
        self._adopt_ttl(resp)
        self._last_renewal = t0
        return True

    def release(self) -> None:
        self._post("/release", {"group": self.group,
                                "member": self.member_id,
                                "epoch": self._epoch})

    def current_leader(self) -> Optional[str]:
        resp = self._get_leader()
        return resp.get("leader") if resp else None

    def current_leader_url(self) -> str:
        resp = self._get_leader()
        return (resp.get("url") or "") if resp else ""


class LeaderSelector:
    """Blocks until leadership, runs `on_leadership`, watches the lease, and
    invokes `on_loss` (default: os._exit — the reference's System/exit 0)
    when it goes away."""

    def __init__(
        self,
        elector: LeaderElector,
        *,
        poll_s: float = 1.0,
        on_loss: Optional[Callable[[], None]] = None,
    ):
        self.elector = elector
        self.poll_s = poll_s
        self.on_loss = on_loss or (lambda: os._exit(0))
        self._stop = threading.Event()
        self._lost = threading.Event()
        self._loss_lock = threading.Lock()
        self.is_leader = False

    def wait_for_leadership(self) -> None:
        while not self._stop.is_set():
            if self.elector.try_acquire():
                self.is_leader = True
                return
            self._stop.wait(self.poll_s)

    def _heartbeat(self) -> bool:
        """One lease renewal, with the `leader.heartbeat` fault point in
        front: an injected error IS a lease loss (the chaos suite drives
        failover through the same fail-fast path a real expiry takes);
        a delay rule is a slow lease service."""
        try:
            fault_schedule = faults.ACTIVE
            if fault_schedule is not None:
                fault_schedule.hit(
                    faults.LEADER_HEARTBEAT,
                    member=getattr(self.elector, "member_id", ""))
        except faults.FaultInjected:
            return False
        return self.elector.heartbeat()

    def start_heartbeat_thread(self) -> threading.Thread:
        def loop():
            while not self._stop.is_set():
                if not self._heartbeat():
                    self.is_leader = False
                    self._fire_loss()
                    return
                self._stop.wait(self.poll_s)

        t = threading.Thread(target=loop, daemon=True, name="leader-heartbeat")
        t.start()
        return t

    def _fire_loss(self) -> None:
        # a voluntary demotion racing a heartbeat failure must not run
        # on_loss twice: the test-and-set must be atomic (Event alone
        # lets both threads pass the is_set check)
        with self._loss_lock:
            if self._lost.is_set():
                return
            self._lost.set()
        self.on_loss()

    def demote(self) -> None:
        """Voluntarily surrender a HELD lease (fail-stop on a journal
        fsync error): stop renewing, release the lease so a standby with
        a working disk can acquire it before the TTL runs out, then fire
        on_loss once.  The heartbeat-failure path never releases — there
        the lease is already lost."""
        self.is_leader = False
        self._stop.set()  # heartbeat loop exits without firing on_loss
        try:
            self.elector.release()
        except Exception:  # noqa: BLE001 — the lease still expires by TTL
            log.exception("lease release during demotion failed")
        self._fire_loss()

    def stop(self) -> None:
        self._stop.set()
        self.elector.release()
