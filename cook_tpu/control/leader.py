"""Leader election: single active scheduler with hot standbys.

Reference: Curator/ZooKeeper LeaderSelector
(/root/reference/scheduler/src/cook/mesos.clj:153-328 +
components.clj:154): one instance leads and runs the scheduling loops;
standbys wait; on leadership loss the process fail-fast exits so a
supervisor restarts it clean (mesos.clj:296-313 — restarting state is
error-prone, a fresh process is safer).

Implementations:
  * InMemoryElector — single-process/tests.
  * FileLeaseElector — multi-process on one filesystem: an O_EXCL lease
    file with heartbeat timestamps; standbys take over when the lease
    goes stale.  (The production analog would be an etcd/ZK lease; the
    protocol boundary is what matters here.)
"""
from __future__ import annotations

import json
import os
import threading
import time
from abc import ABC, abstractmethod
from typing import Callable, Optional


class LeaderElector(ABC):
    """`start` runs until leadership is lost, then calls on_loss — the
    caller is expected to exit the process (fail-fast)."""

    @abstractmethod
    def try_acquire(self) -> bool: ...

    @abstractmethod
    def heartbeat(self) -> bool:
        """Refresh the lease; False if leadership was lost."""

    @abstractmethod
    def release(self) -> None: ...

    @abstractmethod
    def current_leader(self) -> Optional[str]: ...


class InMemoryElector(LeaderElector):
    _leaders: dict[str, str] = {}
    _lock = threading.Lock()

    def __init__(self, group: str, member_id: str):
        self.group = group
        self.member_id = member_id

    def try_acquire(self) -> bool:
        with self._lock:
            if self._leaders.get(self.group) in (None, self.member_id):
                self._leaders[self.group] = self.member_id
                return True
            return False

    def heartbeat(self) -> bool:
        with self._lock:
            return self._leaders.get(self.group) == self.member_id

    def release(self) -> None:
        with self._lock:
            if self._leaders.get(self.group) == self.member_id:
                del self._leaders[self.group]

    def current_leader(self) -> Optional[str]:
        with self._lock:
            return self._leaders.get(self.group)


class FileLeaseElector(LeaderElector):
    def __init__(self, lease_path: str, member_id: str,
                 *, ttl_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic,
                 advertised_url: str = ""):
        self.lease_path = lease_path
        self.member_id = member_id
        self.ttl_s = ttl_s
        self.clock = clock
        # published in the lease so standbys can proxy to the leader
        self.advertised_url = advertised_url

    def _read(self) -> Optional[dict]:
        try:
            with open(self.lease_path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    def _write(self) -> None:
        tmp = f"{self.lease_path}.{self.member_id}.tmp"
        with open(tmp, "w") as f:
            json.dump({"leader": self.member_id, "t": self.clock(),
                       "url": self.advertised_url}, f)
        os.replace(tmp, self.lease_path)

    def current_leader_url(self) -> str:
        lease = self._read()
        if lease is None or self.clock() - lease["t"] > self.ttl_s:
            return ""
        return lease.get("url", "")

    def try_acquire(self) -> bool:
        lease = self._read()
        now = self.clock()
        if lease is None or lease["leader"] == self.member_id \
                or now - lease["t"] > self.ttl_s:
            self._write()
            # re-read to detect a concurrent writer that beat us
            lease = self._read()
            return lease is not None and lease["leader"] == self.member_id
        return False

    def heartbeat(self) -> bool:
        lease = self._read()
        if lease is None or lease["leader"] != self.member_id:
            return False
        self._write()
        return True

    def release(self) -> None:
        lease = self._read()
        if lease is not None and lease["leader"] == self.member_id:
            try:
                os.unlink(self.lease_path)
            except FileNotFoundError:
                pass

    def current_leader(self) -> Optional[str]:
        lease = self._read()
        if lease is None or self.clock() - lease["t"] > self.ttl_s:
            return None
        return lease["leader"]


class LeaderSelector:
    """Blocks until leadership, runs `on_leadership`, watches the lease, and
    invokes `on_loss` (default: os._exit — the reference's System/exit 0)
    when it goes away."""

    def __init__(
        self,
        elector: LeaderElector,
        *,
        poll_s: float = 1.0,
        on_loss: Optional[Callable[[], None]] = None,
    ):
        self.elector = elector
        self.poll_s = poll_s
        self.on_loss = on_loss or (lambda: os._exit(0))
        self._stop = threading.Event()
        self.is_leader = False

    def wait_for_leadership(self) -> None:
        while not self._stop.is_set():
            if self.elector.try_acquire():
                self.is_leader = True
                return
            self._stop.wait(self.poll_s)

    def start_heartbeat_thread(self) -> threading.Thread:
        def loop():
            while not self._stop.is_set():
                if not self.elector.heartbeat():
                    self.is_leader = False
                    self.on_loss()
                    return
                self._stop.wait(self.poll_s)

        t = threading.Thread(target=loop, daemon=True, name="leader-heartbeat")
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()
        self.elector.release()
