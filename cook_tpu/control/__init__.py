"""Control plane: leader election."""
from cook_tpu.control.leader import (  # noqa: F401
    FileLeaseElector,
    InMemoryElector,
    LeaderElector,
    LeaderSelector,
)
