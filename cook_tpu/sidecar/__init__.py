"""Pod sidecar: sandbox file server + progress reporting
(reference: sidecar/)."""
from cook_tpu.sidecar.fileserver import FileServer  # noqa: F401
