"""Sandbox file server: the pod sidecar for `cs ls/cat/tail`.

Reference: sidecar/ (/root/reference/sidecar/file_server.py:45-233 — a
small HTTP server replicating the Mesos `files/` API inside the pod:
/files/browse, /files/read, /files/download, rooted at COOK_WORKDIR).
Serves the same three endpoints with path traversal protection.
"""
from __future__ import annotations

import os
import stat as stat_mod
from typing import BinaryIO, Optional

from aiohttp import web


class FileServer:
    def __init__(self, workdir: str):
        self.workdir = os.path.realpath(workdir)

    def _resolve(self, path: str) -> Optional[str]:
        """Resolve a requested path inside the sandbox; None if it escapes.

        realpath (not abspath) on both ends: a task could otherwise plant a
        symlink inside its sandbox pointing outside COOK_WORKDIR and read
        arbitrary pod-readable files through it.
        """
        if not path:
            return None
        full = os.path.realpath(
            path if os.path.isabs(path) else os.path.join(self.workdir, path)
        )
        if full != self.workdir and not full.startswith(self.workdir + os.sep):
            return None
        return full

    def _open_contained(self, path: str) -> Optional[BinaryIO]:
        """Open a sandbox file with the containment verified on the OPENED
        fd, not just the pre-open path: _resolve alone is check-then-use —
        a task can swap a directory for an outside-pointing symlink between
        the realpath check and the open.  After opening, the fd's real path
        (via /proc/self/fd) tells us what was actually opened; if that
        escaped the sandbox, the handle is discarded."""
        full = self._resolve(path)
        if full is None:
            return None
        try:
            # O_NONBLOCK: opening a task-planted FIFO read-only must not
            # block the event loop waiting for a writer (harmless for
            # regular files).  O_NOFOLLOW: the realpath above already
            # resolved symlinks, so a symlink at the final component now
            # means a race — reject it.
            fd = os.open(full, os.O_RDONLY | os.O_NONBLOCK
                         | getattr(os, "O_NOFOLLOW", 0))
        except OSError:
            return None
        f = os.fdopen(fd, "rb")
        if not stat_mod.S_ISREG(os.fstat(fd).st_mode):
            f.close()
            return None
        try:
            actual = os.path.realpath(f"/proc/self/fd/{fd}")
        except OSError:
            # non-Linux fallback: re-resolve the path post-open (narrows
            # but does not fully close the race window)
            actual = os.path.realpath(full)
        if (actual != self.workdir
                and not actual.startswith(self.workdir + os.sep)):
            f.close()
            return None
        return f

    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/files/browse", self.browse)
        app.router.add_get("/files/read", self.read)
        app.router.add_get("/files/download", self.download)
        return app

    async def browse(self, request: web.Request) -> web.Response:
        path = self._resolve(request.query.get("path", self.workdir))
        if path is None or not os.path.exists(path):
            return web.json_response({"error": "no such path"}, status=404)
        if not os.path.isdir(path):
            return web.json_response({"error": "not a directory"}, status=400)
        entries = []
        for name in sorted(os.listdir(path)):
            full = os.path.join(path, name)
            stat = os.stat(full)
            entries.append({
                "path": full,
                "size": stat.st_size,
                "nlink": stat.st_nlink,
                "mtime": int(stat.st_mtime),
                "mode": ("d" if os.path.isdir(full) else "-"),
            })
        return web.json_response(entries)

    async def read(self, request: web.Request) -> web.Response:
        """Mesos-style paged read: ?path=&offset=&length=.
        offset=-1 returns just the file size (how `cs tail` seeks)."""
        f = self._open_contained(request.query.get("path", ""))
        if f is None:
            return web.json_response({"error": "no such file"}, status=404)
        import asyncio

        with f:
            size = os.fstat(f.fileno()).st_size
            offset = int(request.query.get("offset", 0))
            if offset == -1:
                return web.json_response({"offset": size, "data": ""})
            if offset < 0:
                return web.json_response({"error": "bad offset"}, status=400)
            # clamp below as well: length=-1 would turn f.read into
            # read-whole-file and OOM the sidecar on a large log
            length = min(max(int(request.query.get("length", 64 * 1024)), 0),
                         1024 * 1024)

            def _read() -> bytes:
                f.seek(offset)
                return f.read(length)

            data = await asyncio.get_event_loop().run_in_executor(None, _read)
        return web.json_response({
            "offset": offset,
            "data": data.decode(errors="replace"),
        })

    async def download(self, request: web.Request) -> web.StreamResponse:
        f = self._open_contained(request.query.get("path", ""))
        if f is None:
            return web.json_response({"error": "no such file"}, status=404)
        import asyncio
        import re

        loop = asyncio.get_event_loop()
        with f:
            # sanitized: filenames are task-controlled, and quotes/control
            # chars would malform the header (or make aiohttp 500)
            name = re.sub(r"[^\w.+-]", "_", os.path.basename(
                request.query.get("path", "file"))) or "file"
            response = web.StreamResponse(headers={
                "Content-Type": "application/octet-stream",
                "Content-Disposition": f'attachment; filename="{name}"',
            })
            await response.prepare(request)
            while True:
                chunk = await loop.run_in_executor(None, f.read, 256 * 1024)
                if not chunk:
                    break
                await response.write(chunk)
            await response.write_eof()
        return response


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="cook-sidecar-fileserver")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--workdir",
                        default=os.environ.get("COOK_WORKDIR", "."))
    args = parser.parse_args(argv)
    web.run_app(FileServer(args.workdir).build_app(), port=args.port)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
