"""Sandbox file server: the pod sidecar for `cs ls/cat/tail`.

Reference: sidecar/ (/root/reference/sidecar/file_server.py:45-233 — a
small HTTP server replicating the Mesos `files/` API inside the pod:
/files/browse, /files/read, /files/download, rooted at COOK_WORKDIR).
Serves the same three endpoints with path traversal protection.
"""
from __future__ import annotations

import os
from typing import Optional

from aiohttp import web


class FileServer:
    def __init__(self, workdir: str):
        self.workdir = os.path.realpath(workdir)

    def _resolve(self, path: str) -> Optional[str]:
        """Resolve a requested path inside the sandbox; None if it escapes.

        realpath (not abspath) on both ends: a task could otherwise plant a
        symlink inside its sandbox pointing outside COOK_WORKDIR and read
        arbitrary pod-readable files through it.
        """
        if not path:
            return None
        full = os.path.realpath(
            path if os.path.isabs(path) else os.path.join(self.workdir, path)
        )
        if full != self.workdir and not full.startswith(self.workdir + os.sep):
            return None
        return full

    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/files/browse", self.browse)
        app.router.add_get("/files/read", self.read)
        app.router.add_get("/files/download", self.download)
        return app

    async def browse(self, request: web.Request) -> web.Response:
        path = self._resolve(request.query.get("path", self.workdir))
        if path is None or not os.path.exists(path):
            return web.json_response({"error": "no such path"}, status=404)
        if not os.path.isdir(path):
            return web.json_response({"error": "not a directory"}, status=400)
        entries = []
        for name in sorted(os.listdir(path)):
            full = os.path.join(path, name)
            stat = os.stat(full)
            entries.append({
                "path": full,
                "size": stat.st_size,
                "nlink": stat.st_nlink,
                "mtime": int(stat.st_mtime),
                "mode": ("d" if os.path.isdir(full) else "-"),
            })
        return web.json_response(entries)

    async def read(self, request: web.Request) -> web.Response:
        """Mesos-style paged read: ?path=&offset=&length=.
        offset=-1 returns just the file size (how `cs tail` seeks)."""
        path = self._resolve(request.query.get("path", ""))
        if path is None or not os.path.isfile(path):
            return web.json_response({"error": "no such file"}, status=404)
        size = os.path.getsize(path)
        offset = int(request.query.get("offset", 0))
        if offset == -1:
            return web.json_response({"offset": size, "data": ""})
        length = min(int(request.query.get("length", 64 * 1024)), 1024 * 1024)
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(length)
        return web.json_response({
            "offset": offset,
            "data": data.decode(errors="replace"),
        })

    async def download(self, request: web.Request) -> web.Response:
        path = self._resolve(request.query.get("path", ""))
        if path is None or not os.path.isfile(path):
            return web.json_response({"error": "no such file"}, status=404)
        return web.FileResponse(path)


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="cook-sidecar-fileserver")
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--workdir",
                        default=os.environ.get("COOK_WORKDIR", "."))
    args = parser.parse_args(argv)
    web.run_app(FileServer(args.workdir).build_app(), port=args.port)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
