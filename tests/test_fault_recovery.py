"""Elastic-recovery end-to-end: node loss, task faults, heartbeat loss —
work still completes via mea-culpa retries (reference: failure-detection
subsystems, SURVEY §5)."""
from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import InstanceStatus, JobState, Pool
from cook_tpu.models.store import JobStore
from cook_tpu.scheduler.core import Scheduler
from cook_tpu.sim.simulator import SimConfig, Simulator, TraceHost, TraceJob
from tests.conftest import FakeClock, make_job


def test_node_loss_mid_run_recovers():
    jobs = [
        TraceJob(uuid=f"j{i}", user=f"u{i % 3}", submit_time_ms=0,
                 runtime_ms=120_000, mem=200, cpus=2)
        for i in range(12)
    ]
    hosts = [TraceHost(node_id=f"n{i}", hostname=f"n{i}", mem=2000, cpus=8)
             for i in range(4)]
    sim = Simulator(jobs, hosts, SimConfig(cycle_ms=15_000, max_cycles=200))
    # run some cycles, then kill a node with work on it
    steps = 0
    original_run = sim.run

    # drive manually: advance 3 cycles, remove a node, then finish
    sim.cluster.advance_to(sim.now_ms)
    submitted = 0
    pool = sim.store.pools["default"]
    for cycle in range(3):
        while (submitted < len(sim.trace_jobs)
               and sim.trace_jobs[submitted].submit_time_ms <= sim.now_ms):
            tj = sim.trace_jobs[submitted]
            from cook_tpu.models.entities import Job, Resources

            sim.store.submit_jobs([Job(
                uuid=tj.uuid, user=tj.user, pool=tj.pool,
                resources=Resources(mem=tj.mem, cpus=tj.cpus),
                expected_runtime_ms=tj.runtime_ms, command="sim",
                max_retries=5,
            )])
            submitted += 1
        sim.scheduler.rank_cycle(pool)
        sim.scheduler.match_cycle(pool)
        sim.now_ms += 15_000
        sim.cluster.advance_to(sim.now_ms)

    victims = sim.cluster.remove_host("n0")
    assert victims, "expected tasks on the removed node"
    # mea-culpa: victims' jobs back to waiting, no retry consumed
    for tid in victims:
        job = sim.store.jobs[sim.store.instances[tid].job_uuid]
        assert job.state == JobState.WAITING
        assert sim.store.instances[tid].reason_code == 4000

    # keep simulating to completion on the remaining 3 nodes
    while sim.now_ms < 3_000_000:
        sim.scheduler.rank_cycle(pool)
        sim.scheduler.match_cycle(pool)
        sim.now_ms += 15_000
        sim.cluster.advance_to(sim.now_ms)
        if all(sim.store.jobs[j.uuid].state == JobState.COMPLETED
               for j in jobs):
            break
    assert all(sim.store.jobs[j.uuid].state == JobState.COMPLETED
               for j in jobs)
    # the victims retried on surviving nodes
    for tid in victims:
        job_uuid = sim.store.instances[tid].job_uuid
        insts = sim.store.job_instances(job_uuid)
        assert len(insts) >= 2
        assert insts[-1].status == InstanceStatus.SUCCESS
        assert insts[-1].hostname != "n0"


def test_repeated_flaky_failures_eventually_exhaust():
    """Non-mea-culpa failures consume retries and complete the job failed."""
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "m", [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=4000, cpus=8)
              for i in range(4)],
        clock=clock)
    scheduler = Scheduler(store, [cluster])
    job = make_job(max_retries=3)
    store.submit_jobs([job])
    pool = store.pools["default"]
    hosts_used = []
    for attempt in range(3):
        scheduler.rank_cycle(pool)
        outcome = scheduler.match_cycle(pool)
        assert len(outcome.matched) == 1
        [tid] = outcome.launched_task_ids
        hosts_used.append(store.instances[tid].hostname)
        cluster.fail_task(tid, "command-executor-failed")
    assert store.jobs[job.uuid].state == JobState.COMPLETED
    assert len(store.job_instances(job.uuid)) == 3
    # novel-host: every retry went to a fresh host
    assert len(set(hosts_used)) == 3
