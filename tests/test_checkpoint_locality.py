"""Checkpoint-locality steering + offensive-job quarantine."""
from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import Checkpoint, JobState, Pool
from cook_tpu.models.store import JobStore
from cook_tpu.scheduler.core import Scheduler
from tests.conftest import FakeClock, make_job


def two_region_setup():
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    east = MockCluster(
        "east", [MockHost(node_id="e0", hostname="e0", mem=4000, cpus=8)],
        clock=clock)
    east.location = "us-east"
    west = MockCluster(
        "west", [MockHost(node_id="w0", hostname="w0", mem=4000, cpus=8)],
        clock=clock)
    west.location = "us-west"
    scheduler = Scheduler(store, [east, west])
    return clock, store, scheduler


def test_checkpointed_job_pinned_to_its_region():
    clock, store, scheduler = two_region_setup()
    job = make_job(
        checkpoint=Checkpoint(mode="auto", location="us-west"))
    store.submit_jobs([job])
    pool = store.pools["default"]
    for _ in range(3):  # repeated cycles must keep choosing west
        scheduler.rank_cycle(pool)
        outcome = scheduler.match_cycle(pool)
        if outcome.matched:
            break
    [inst] = store.job_instances(job.uuid)
    assert inst.compute_cluster == "west"


def test_uncheckpointed_job_unrestricted():
    clock, store, scheduler = two_region_setup()
    jobs = [make_job(mem=3000, cpus=6) for _ in range(2)]
    store.submit_jobs(jobs)
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    assert len(outcome.matched) == 2  # spread over both regions


def test_offensive_job_quarantined_from_queue():
    clock, store, scheduler = two_region_setup()
    monster = make_job(mem=999_999, cpus=1)  # larger than any host
    normal = make_job(mem=100, cpus=1)
    store.submit_jobs([monster, normal])
    pool = store.pools["default"]
    queue = scheduler.rank_cycle(pool)
    queued = {j.uuid for j in queue.jobs}
    assert normal.uuid in queued
    assert monster.uuid not in queued  # never clogs the queue head
    outcome = scheduler.match_cycle(pool)
    assert {j.uuid for j, _ in outcome.matched} == {normal.uuid}
    assert store.jobs[monster.uuid].state == JobState.WAITING


def test_disk_constrained_matching():
    """Disk is a packed resource: a job needing disk only lands on hosts
    with enough of it (constraints.clj disk constraint)."""
    from cook_tpu.models.entities import Resources

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "m",
        [MockHost(node_id="small", hostname="small", mem=4000, cpus=8,
                  disk=10.0),
         MockHost(node_id="big", hostname="big", mem=4000, cpus=8,
                  disk=500.0)],
        clock=clock)
    scheduler = Scheduler(store, [cluster])
    job = make_job()
    job = job.with_(resources=Resources(mem=100, cpus=1, disk=100.0))
    store.submit_jobs([job])
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    assert len(outcome.matched) == 1
    [inst] = store.job_instances(job.uuid)
    assert inst.hostname == "big"
    # disk accounting shows in offers
    offers = {o.hostname: o for o in cluster.pending_offers("default")}
    assert offers["big"].disk == 400.0
