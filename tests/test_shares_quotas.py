"""Share/quota semantics (reference: share.clj, quota.clj): default-user
fallback, partial shares, quota resource+count caps."""
from cook_tpu.models.entities import DEFAULT_USER, Quota, Resources, Share


def test_share_default_user_fallback(store):
    store.set_share(Share(user=DEFAULT_USER, pool="default",
                          resources=Resources(mem=1000.0, cpus=10.0, gpus=1.0)))
    s = store.get_share("alice", "default")
    assert (s.mem, s.cpus, s.gpus) == (1000.0, 10.0, 1.0)


def test_share_partial_override_falls_back_per_resource(store):
    store.set_share(Share(user=DEFAULT_USER, pool="default",
                          resources=Resources(mem=1000.0, cpus=10.0, gpus=1.0)))
    store.set_share(Share(user="bob", pool="default",
                          resources=Resources(mem=4000.0)))
    s = store.get_share("bob", "default")
    assert s.mem == 4000.0
    assert s.cpus == 10.0  # falls back to default user
    assert s.gpus == 1.0


def test_share_no_defaults_is_infinite(store):
    s = store.get_share("carol", "default")
    assert s.mem == float("inf")


def test_quota_fallback_and_retract(store):
    store.set_quota(Quota(user=DEFAULT_USER, pool="default",
                          resources=Resources(mem=100.0, cpus=1.0), count=5))
    q = store.get_quota("alice", "default")
    assert q.count == 5 and q.resources.mem == 100.0
    store.set_quota(Quota(user="alice", pool="default",
                          resources=Resources(mem=999.0, cpus=9.0), count=7))
    q = store.get_quota("alice", "default")
    assert q.count == 7 and q.resources.mem == 999.0
    store.retract_quota("alice", "default")
    assert store.get_quota("alice", "default").count == 5


def test_usage_accounting(store, job_factory):
    j1 = job_factory(user="alice", mem=100, cpus=2)
    j2 = job_factory(user="alice", mem=50, cpus=1)
    j3 = job_factory(user="bob", mem=10, cpus=1)
    store.submit_jobs([j1, j2, j3])
    store.create_instance(j1.uuid, "t1", hostname="h1")
    store.create_instance(j2.uuid, "t2", hostname="h2")
    usage = store.user_usage("default")
    assert usage["alice"].mem == 150 and usage["alice"].cpus == 3
    assert "bob" not in usage
    assert store.pending_count("default") == 1
    assert store.pending_count("default", user="bob") == 1


# ---------------------------------------------------------------- match-time

def _quota_scheduler():
    from cook_tpu.cluster.mock import MockCluster, MockHost
    from cook_tpu.models.entities import Pool
    from cook_tpu.models.store import JobStore
    from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
    from tests.conftest import FakeClock

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    hosts = [MockHost(node_id=f"h{i}", hostname=f"h{i}", mem=8000, cpus=16)
             for i in range(4)]
    cluster = MockCluster("mock", hosts, clock=clock)
    return store, cluster, Scheduler(store, [cluster], SchedulerConfig())


def test_match_refilters_quota_lowered_mid_interval(job_factory):
    """Reference pending-jobs->considerable-jobs (scheduler.clj:729):
    quota is re-checked at MATCH time, so a quota change between rank
    ticks takes effect on the very next match."""
    from cook_tpu.models.entities import DEFAULT_USER, JobState, Quota, Resources

    store, cluster, scheduler = _quota_scheduler()
    store.set_quota(Quota(user="alice", pool="default",
                          resources=Resources(mem=1e9, cpus=1e9, gpus=1e9), count=2))
    jobs = [job_factory(user="alice") for _ in range(2)]
    store.submit_jobs(jobs)
    pool = store.pools["default"]
    queue = scheduler.rank_cycle(pool)
    assert len(queue.jobs) == 2  # both under quota at rank time
    # admin lowers the quota between the rank tick and the match tick
    store.set_quota(Quota(user="alice", pool="default",
                          resources=Resources(mem=1e9, cpus=1e9, gpus=1e9), count=1))
    outcome = scheduler.match_cycle(pool)
    assert len(outcome.matched) == 1
    running = [j for j in store.jobs.values()
               if j.state is JobState.RUNNING]
    assert len(running) == 1


def test_match_refilters_usage_grown_mid_interval(job_factory):
    """A launch that lands through another path (reconciliation, another
    scheduler instance) after the rank tick consumes quota budget at
    match time."""
    from cook_tpu.models.entities import JobState, Quota, Resources

    store, cluster, scheduler = _quota_scheduler()
    store.set_quota(Quota(user="alice", pool="default",
                          resources=Resources(mem=1e9, cpus=1e9, gpus=1e9), count=2))
    jobs = [job_factory(user="alice") for _ in range(2)]
    store.submit_jobs(jobs)
    pool = store.pools["default"]
    queue = scheduler.rank_cycle(pool)
    assert len(queue.jobs) == 2
    # out-of-band launch after the rank snapshot: a third job starts
    # running, filling one quota slot
    extra = job_factory(user="alice")
    store.submit_jobs([extra])
    store.create_instance(extra.uuid, "t-extra", hostname="h0")
    outcome = scheduler.match_cycle(pool)
    assert len(outcome.matched) == 1
    # and nothing further matches while the quota stays full
    outcome2 = scheduler.match_cycle(pool)
    assert len(outcome2.matched) == 0


def test_match_skips_jobs_killed_since_rank(job_factory):
    """A job killed between rank and match must neither match nor consume
    the user's quota budget in the match-time walk."""
    from cook_tpu.models.entities import JobState, Quota, Resources

    store, cluster, scheduler = _quota_scheduler()
    store.set_quota(Quota(user="alice", pool="default",
                          resources=Resources(mem=1e9, cpus=1e9, gpus=1e9), count=1))
    j1 = job_factory(user="alice")
    j2 = job_factory(user="alice")
    store.submit_jobs([j1, j2])
    pool = store.pools["default"]
    queue = scheduler.rank_cycle(pool)
    assert [j.uuid for j in queue.jobs] == [j1.uuid]  # j2 quota-capped
    store.kill_jobs([j1.uuid])
    outcome = scheduler.match_cycle(pool)
    # j1 is dead; j2 is not in the (stale) queue, so nothing matches —
    # but j1 must not have consumed the budget either way
    assert len(outcome.matched) == 0
    queue = scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    assert [j.uuid for j, _ in outcome.matched] == [j2.uuid]
