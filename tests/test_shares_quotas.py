"""Share/quota semantics (reference: share.clj, quota.clj): default-user
fallback, partial shares, quota resource+count caps."""
from cook_tpu.models.entities import DEFAULT_USER, Quota, Resources, Share


def test_share_default_user_fallback(store):
    store.set_share(Share(user=DEFAULT_USER, pool="default",
                          resources=Resources(mem=1000.0, cpus=10.0, gpus=1.0)))
    s = store.get_share("alice", "default")
    assert (s.mem, s.cpus, s.gpus) == (1000.0, 10.0, 1.0)


def test_share_partial_override_falls_back_per_resource(store):
    store.set_share(Share(user=DEFAULT_USER, pool="default",
                          resources=Resources(mem=1000.0, cpus=10.0, gpus=1.0)))
    store.set_share(Share(user="bob", pool="default",
                          resources=Resources(mem=4000.0)))
    s = store.get_share("bob", "default")
    assert s.mem == 4000.0
    assert s.cpus == 10.0  # falls back to default user
    assert s.gpus == 1.0


def test_share_no_defaults_is_infinite(store):
    s = store.get_share("carol", "default")
    assert s.mem == float("inf")


def test_quota_fallback_and_retract(store):
    store.set_quota(Quota(user=DEFAULT_USER, pool="default",
                          resources=Resources(mem=100.0, cpus=1.0), count=5))
    q = store.get_quota("alice", "default")
    assert q.count == 5 and q.resources.mem == 100.0
    store.set_quota(Quota(user="alice", pool="default",
                          resources=Resources(mem=999.0, cpus=9.0), count=7))
    q = store.get_quota("alice", "default")
    assert q.count == 7 and q.resources.mem == 999.0
    store.retract_quota("alice", "default")
    assert store.get_quota("alice", "default").count == 5


def test_usage_accounting(store, job_factory):
    j1 = job_factory(user="alice", mem=100, cpus=2)
    j2 = job_factory(user="alice", mem=50, cpus=1)
    j3 = job_factory(user="bob", mem=10, cpus=1)
    store.submit_jobs([j1, j2, j3])
    store.create_instance(j1.uuid, "t1", hostname="h1")
    store.create_instance(j2.uuid, "t2", hostname="h2")
    usage = store.user_usage("default")
    assert usage["alice"].mem == 150 and usage["alice"].cpus == 3
    assert "bob" not in usage
    assert store.pending_count("default") == 1
    assert store.pending_count("default", user="bob") == 1
