"""Replication durability + divergence semantics.

Reference bar: a write is durable the moment the REST call returns —
Datomic is an external replicated store (datomic.clj:79
transact-with-retries) and failover replays from it.  Here durability
comes from standby replication, so these tests pin:

  * sync-ack mode: POST /jobs blocks until a standby confirmed the
    write — kill the leader right after the 201 and the job exists on
    the standby (the acked-write loss window is CLOSED, not just small).
  * ack-timeout honesty: with no standby alive, a sync-ack submission
    still commits but says "replicated": false.
  * follower-ahead divergence: a deposed leader rejoining as a standby
    with a LONGER history than the new leader is told snapshot_required
    and converges (never silently skips).
  * incarnation fencing: a follower that switches to a different leader
    process forces a snapshot bootstrap even when sequence numbers look
    contiguous — seqs are only comparable within one leader history.
  * restore_into clears the retained event window, so a promoted
    standby never serves pre-resync events under post-resync numbering.
  * long-poll: a parked journal request returns as soon as a write
    commits (replication is push-like, not 1s-poll-bounded).
"""
import threading
import time

import requests

from cook_tpu.components import build_process, shutdown, start_leader_duties
from cook_tpu.control.lease_server import LeaseServer
from cook_tpu.control.replication import JournalFollower
from cook_tpu.models import persistence
from cook_tpu.models.entities import JobState
from cook_tpu.rest.server import free_port
from cook_tpu.utils.config import Settings

H = {"X-Cook-Requesting-User": "u"}
ADMIN = {"X-Cook-Requesting-User": "admin"}


def _settings(port, data_dir, lease_url, **kw):
    return Settings(
        port=port, data_dir=data_dir,
        leader_endpoint=lease_url, leader_ttl_s=3.0,
        clusters=[{
            "kind": "mock", "name": "m1",
            "hosts": [{"node_id": "h0", "mem": 4000, "cpus": 8}],
        }],
        pools=[{"name": "default"}],
        rank_interval_s=3600, match_interval_s=3600,
        **kw,
    )


def _wait(pred, timeout_s, what):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def test_standby_self_registers_as_fleet_peer(tmp_path):
    """The replication ack doubles as fleet-peer registration: a
    standby that tails the leader lands its URL in the leader's ack
    registry, the leader's fleet observatory (started with leadership)
    discovers it with NO `peers` config, and one poll yields a healthy
    fleet row for it (obs/fleet.py; docs/observability.md)."""
    lease = LeaseServer().start()
    p1 = p2 = None
    try:
        s1 = _settings(free_port(), str(tmp_path / "n1"), lease.url)
        p1 = build_process(s1)
        start_leader_duties(p1, block=False, on_loss=lambda: None)
        assert p1.is_leader()
        assert p1.fleet is not None and p1.api.fleet is p1.fleet

        s2 = _settings(free_port(), str(tmp_path / "n2"), lease.url)
        p2 = build_process(s2)
        standby = threading.Thread(
            target=start_leader_duties, args=(p2,),
            kwargs={"block": False, "on_loss": lambda: None}, daemon=True)
        standby.start()
        standby_url = f"http://127.0.0.1:{s2.port}"
        _wait(lambda: standby_url in p1.fleet.peer_list(), 15,
              "standby url in the leader's fleet peer registry")

        rows = p1.fleet.poll_once()
        row = rows[standby_url]
        assert row["ok"], row
        verdict = p1.api.fleet.verdict()
        assert standby_url in [n["url"] for n in verdict["nodes"]]
        # the standby runs its own history sampler (every node role)
        assert p2.history is not None
        # served over the leader's REST surface too
        r = requests.get(f"http://127.0.0.1:{s1.port}/debug/fleet",
                         headers=ADMIN, timeout=10)
        assert r.status_code == 200
        body = r.json()
        assert body["enabled"]
        assert standby_url in [n["url"] for n in body["nodes"]]
    finally:
        for p in (p1, p2):
            if p is not None:
                shutdown(p)
        lease.stop()


# ------------------------------------------------------------------ sync-ack


def test_sync_ack_submission_durable_on_standby_at_201(tmp_path):
    """Kill the leader IMMEDIATELY after the 201: in sync-ack mode the
    job must already be on the standby — no async poll window."""
    lease = LeaseServer().start()
    p1 = p2 = None
    try:
        s1 = _settings(free_port(), str(tmp_path / "n1"), lease.url,
                       replication_sync_ack=True,
                       replication_ack_timeout_s=10.0)
        p1 = build_process(s1)
        start_leader_duties(p1, block=False, on_loss=lambda: None)
        assert p1.is_leader()

        s2 = _settings(free_port(), str(tmp_path / "n2"), lease.url)
        p2 = build_process(s2)
        standby = threading.Thread(
            target=start_leader_duties, args=(p2,),
            kwargs={"block": False, "on_loss": lambda: None}, daemon=True)
        standby.start()
        # wait for the standby's follower to register with the leader
        _wait(lambda: p1.api.replication_acks, 15, "standby ack presence")

        uuid = "d0000000-0000-0000-0000-000000000001"
        r = requests.post(f"http://127.0.0.1:{s1.port}/jobs", json={
            "jobs": [{"command": "x", "mem": 100, "cpus": 1, "uuid": uuid}],
        }, headers=H, timeout=15)
        assert r.status_code == 201
        assert "replicated" not in r.json(), "ack timeout despite standby"
        # the durability claim: at this instant, with the leader frozen,
        # the standby already holds the job in ITS store
        assert uuid in p2.store.jobs
        # and on its own disk (a cold recover of the standby's dir works)
        shutdown(p1)
        p1 = None
        recovered = persistence.recover(s2.data_dir)
        assert recovered is not None and uuid in recovered.jobs
    finally:
        for p in (p1, p2):
            if p is not None:
                shutdown(p)
        lease.stop()


def test_sync_ack_timeout_commits_but_reports(tmp_path):
    """No standby at all: the write still commits locally, but the
    response is honest about the durability bound."""
    lease = LeaseServer().start()
    s = _settings(free_port(), str(tmp_path / "n1"), lease.url,
                  replication_sync_ack=True,
                  replication_ack_timeout_s=0.3)
    p = build_process(s)
    try:
        start_leader_duties(p, block=False, on_loss=lambda: None)
        uuid = "d0000000-0000-0000-0000-000000000002"
        r = requests.post(f"http://127.0.0.1:{s.port}/jobs", json={
            "jobs": [{"command": "x", "mem": 100, "cpus": 1, "uuid": uuid}],
        }, headers=H, timeout=10)
        assert r.status_code == 201
        assert r.json().get("replicated") is False
        assert uuid in p.store.jobs  # committed regardless
    finally:
        shutdown(p)
        lease.stop()


# ------------------------------------------------------- divergence handling


def test_follower_ahead_gets_snapshot_required(tmp_path):
    """A standby that outlived a deposed leader can be AHEAD of the new
    leader's history; the journal feed must answer snapshot_required, and
    the follower must converge to the new leader's state."""
    s1 = _settings(free_port(), str(tmp_path / "n1"), "")
    s1.leader_endpoint = ""
    p1 = build_process(s1)
    try:
        url = f"http://127.0.0.1:{s1.port}"
        assert requests.post(f"{url}/jobs", json={"jobs": [
            {"command": "x", "mem": 100, "cpus": 1,
             "uuid": "d0000000-0000-0000-0000-000000000003"},
        ]}, headers=H).status_code == 201
        leader_seq = p1.store.last_seq()

        # ask for events past a seq the leader never reached
        r = requests.get(
            f"{url}/replication/journal?after_seq={leader_seq + 50}",
            headers=ADMIN)
        assert r.status_code == 200
        assert r.json().get("snapshot_required") is True

        # a full follower with a diverged (longer) history converges
        from cook_tpu.models.store import JobStore

        diverged = JobStore()
        diverged.reset_seq(leader_seq + 50)
        follower = JournalFollower(diverged, leader_url_fn=lambda: url)
        follower.sync_once()
        assert follower.full_resyncs == 1
        assert diverged.last_seq() == leader_seq
        assert "d0000000-0000-0000-0000-000000000003" in diverged.jobs
    finally:
        shutdown(p1)


def test_incarnation_change_forces_snapshot_bootstrap(tmp_path):
    """Two leader processes with equal-length but different histories:
    switching the follower between them must trigger a full resync (seq
    numbers alone cannot detect the divergence)."""
    pa = pb = None
    try:
        sa = _settings(free_port(), str(tmp_path / "na"), "")
        sa.leader_endpoint = ""
        pa = build_process(sa)
        sb = _settings(free_port(), str(tmp_path / "nb"), "")
        sb.leader_endpoint = ""
        pb = build_process(sb)
        url_a = f"http://127.0.0.1:{sa.port}"
        url_b = f"http://127.0.0.1:{sb.port}"
        for url, uuid in ((url_a, "d0000000-0000-0000-0000-00000000000a"),
                          (url_b, "d0000000-0000-0000-0000-00000000000b")):
            assert requests.post(f"{url}/jobs", json={"jobs": [
                {"command": "x", "mem": 100, "cpus": 1, "uuid": uuid},
            ]}, headers=H).status_code == 201
        assert pa.store.last_seq() == pb.store.last_seq()

        from cook_tpu.models.store import JobStore

        store = JobStore()
        current = {"url": url_a}
        follower = JournalFollower(store, leader_url_fn=lambda: current["url"])
        follower.sync_once()
        assert "d0000000-0000-0000-0000-00000000000a" in store.jobs
        # switch leaders: same seq, different incarnation + history
        current["url"] = url_b
        follower.sync_once()
        assert follower.full_resyncs >= 1, \
            "incarnation change did not force a snapshot bootstrap"
        assert "d0000000-0000-0000-0000-00000000000b" in store.jobs
        assert "d0000000-0000-0000-0000-00000000000a" not in store.jobs
    finally:
        for p in (pa, pb):
            if p is not None:
                shutdown(p)


def test_restore_into_clears_event_window():
    """After a snapshot bootstrap the pre-resync event window is gone: a
    promoted standby must never serve old events under new numbering."""
    from cook_tpu.models.store import JobStore
    from tests.conftest import make_job

    src = JobStore()
    from cook_tpu.models.entities import Pool

    src.set_pool(Pool(name="default"))
    src.submit_jobs([make_job(user="u")])
    state = persistence.snapshot_state(src)

    dst = JobStore()
    dst.set_pool(Pool(name="default"))
    dst.submit_jobs([make_job(user="w")])  # pre-resync events
    assert dst.events_since(0)
    persistence.restore_into(dst, state)
    assert dst.events_since(0) == []
    assert dst.last_seq() == src.last_seq()


def test_live_apply_events_enter_window_and_journal(tmp_path):
    """Replicated events become ordinary committed events: retained in
    the window (a promoted standby serves them) and journaled via the
    watcher fan-out (exactly once)."""
    from cook_tpu.models.entities import Pool
    from cook_tpu.models.store import JobStore
    from tests.conftest import make_job

    leader = JobStore()
    leader.set_pool(Pool(name="default"))
    leader.submit_jobs([make_job(user="u"), make_job(user="v")])
    entries = [__import__("json").loads(e.to_json())
               for e in leader.events_since(0)]

    standby = JobStore()
    journal = persistence.attach_journal(
        standby, str(tmp_path / "journal.jsonl"))
    with standby._lock:
        applied = persistence.apply_journal(standby, entries, live=True)
    assert applied == len(entries)
    # the window now serves the same events
    assert [e.seq for e in standby.events_since(0)] == \
        [e["seq"] for e in entries]
    # journaled exactly once, replayable
    journal.close()
    replayed = persistence.read_journal(str(tmp_path / "journal.jsonl"))
    assert [e["seq"] for e in replayed] == [e["seq"] for e in entries]
    cold = JobStore()
    persistence.apply_journal(cold, replayed)
    assert set(cold.jobs) == set(leader.jobs)


# ---------------------------------------------------- ack honesty/liveness


def test_memory_only_follower_ack_does_not_satisfy_sync_ack(tmp_path):
    """A follower with no journal/data_dir applies events but cannot
    claim "journaled locally": its acks arrive flagged durable=false and
    must not satisfy the sync-ack bound — replicated:true has to mean a
    second DISK holds the write."""
    s = _settings(free_port(), str(tmp_path / "n1"), "",
                  replication_sync_ack=True,
                  replication_ack_timeout_s=0.5)
    s.leader_endpoint = ""
    p = build_process(s)
    follower = None
    try:
        url = f"http://127.0.0.1:{s.port}"
        from cook_tpu.models.store import JobStore

        follower = JournalFollower(JobStore(), leader_url_fn=lambda: url,
                                   poll_s=0.05, timeout_s=5.0,
                                   member_id="mem-only")
        assert not follower.is_durable()
        follower.start()
        _wait(lambda: "mem-only" in p.api.replication_ack_meta, 10,
              "non-durable ack arrival")

        uuid = "d0000000-0000-0000-0000-000000000010"
        r = requests.post(f"{url}/jobs", json={
            "jobs": [{"command": "x", "mem": 100, "cpus": 1, "uuid": uuid}],
        }, headers=H, timeout=10)
        assert r.status_code == 201
        assert r.json().get("replicated") is False, \
            "a memory-only follower's ack satisfied the durability bound"
        meta = p.api.replication_ack_meta["mem-only"]
        assert meta["durable"] is False
        assert "mem-only" not in p.api.replication_acks
    finally:
        if follower is not None:
            follower.stop()
        shutdown(p)


def test_decommissioned_standby_ack_pruned_from_min_acks(tmp_path):
    """replication_min_acks=2 with one live standby and one
    decommissioned one: while the dead standby's last ack is fresh it
    still counts, but past the liveness window it is pruned and the
    bound is honestly reported unmet."""
    lease = LeaseServer().start()
    p1 = p2 = None
    try:
        s1 = _settings(free_port(), str(tmp_path / "n1"), lease.url,
                       replication_sync_ack=True,
                       replication_min_acks=2,
                       replication_ack_timeout_s=3.0,
                       replication_ack_liveness_s=2.5)
        p1 = build_process(s1)
        start_leader_duties(p1, block=False, on_loss=lambda: None)
        s2 = _settings(free_port(), str(tmp_path / "n2"), lease.url)
        p2 = build_process(s2)
        standby = threading.Thread(
            target=start_leader_duties, args=(p2,),
            kwargs={"block": False, "on_loss": lambda: None}, daemon=True)
        standby.start()
        _wait(lambda: p1.api.replication_acks, 15, "live standby acks")

        url = f"http://127.0.0.1:{s1.port}"
        # the "decommissioned" standby: one durable ack claiming a huge
        # seq (e.g. from a diverged pre-failover history), then silence
        r = requests.post(f"{url}/replication/ack", json={
            "follower": "ghost", "seq": 10**9, "durable": True,
        }, headers=ADMIN, timeout=5)
        assert r.status_code == 200 and r.json()["counted"] is True

        # fresh ghost ack + live standby = bound met (2 acks)
        uuid1 = "d0000000-0000-0000-0000-000000000011"
        r = requests.post(f"{url}/jobs", json={
            "jobs": [{"command": "x", "mem": 100, "cpus": 1,
                      "uuid": uuid1}]}, headers=H, timeout=10)
        assert r.status_code == 201
        assert "replicated" not in r.json(), r.json()

        # past the liveness window the ghost is pruned: only the live
        # standby acks, min_acks=2 is unmet, and the response says so
        time.sleep(3.5)
        uuid2 = "d0000000-0000-0000-0000-000000000012"
        r = requests.post(f"{url}/jobs", json={
            "jobs": [{"command": "x", "mem": 100, "cpus": 1,
                      "uuid": uuid2}]}, headers=H, timeout=10)
        assert r.status_code == 201
        assert r.json().get("replicated") is False, \
            "a decommissioned standby's stale ack satisfied min_acks"
        assert "ghost" not in p1.api.replication_acks
        assert "ghost" not in p1.api.replication_ack_meta
    finally:
        for p in (p1, p2):
            if p is not None:
                shutdown(p)
        lease.stop()


# ------------------------------------------------------------------ long-poll


def test_journal_long_poll_returns_on_commit(tmp_path):
    """A parked long-poll unblocks as soon as a write commits — the
    push-like path sync-ack latency depends on."""
    s = _settings(free_port(), str(tmp_path / "n1"), "")
    s.leader_endpoint = ""
    p = build_process(s)
    try:
        url = f"http://127.0.0.1:{s.port}"
        seq0 = p.store.last_seq()
        results = {}

        def poll():
            t0 = time.monotonic()
            r = requests.get(
                f"{url}/replication/journal?after_seq={seq0}&wait_s=10",
                headers=ADMIN, timeout=15)
            results["elapsed"] = time.monotonic() - t0
            results["events"] = r.json().get("events", [])

        t = threading.Thread(target=poll, daemon=True)
        t.start()
        time.sleep(0.5)  # let the poll park
        assert requests.post(f"{url}/jobs", json={"jobs": [
            {"command": "x", "mem": 100, "cpus": 1,
             "uuid": "d0000000-0000-0000-0000-000000000004"},
        ]}, headers=H).status_code == 201
        t.join(timeout=10)
        assert not t.is_alive()
        assert results["events"], "long-poll returned no events"
        # returned well before the 10s window: woke on the commit
        assert results["elapsed"] < 5.0
    finally:
        shutdown(p)
