"""Pluggable auth (SPNEGO seam), per-cluster launch rate limiter,
FileUrlGenerator seam, and admin negative paths across every gated route
(reference: rest/spnego.clj, rate_limit.clj:44, plugins/definitions.clj:56,
rest/authorization.clj)."""
import base64

import pytest
import requests

from cook_tpu.cluster.mock import MockCluster, MockHost
from cook_tpu.models.entities import Pool
from cook_tpu.models.store import JobStore
from cook_tpu.rest.api import ApiConfig, CookApi
from cook_tpu.rest.auth import (
    BasicAuthenticator,
    CompositeAuthenticator,
    DevHeaderAuthenticator,
    SpnegoAuthenticator,
    authenticator_from_config,
)
from cook_tpu.scheduler.core import Scheduler
from cook_tpu.scheduler.ratelimit import TokenBucketRateLimiter
from tests.conftest import FakeClock, make_job


# ------------------------------------------------------------ unit level


def make_request(headers=None):
    """A minimal request stand-in: authenticators only read .headers."""
    class R:
        pass

    r = R()
    r.headers = headers or {}
    return r


def test_basic_authenticator_verify_rejects_bad_password():
    auth = BasicAuthenticator(verify=lambda u, p: p == "sekrit")
    ok = base64.b64encode(b"alice:sekrit").decode()
    bad = base64.b64encode(b"alice:nope").decode()
    assert auth.authenticate(
        make_request({"Authorization": f"Basic {ok}"})) == "alice"
    assert auth.authenticate(
        make_request({"Authorization": f"Basic {bad}"})) is None
    assert auth.authenticate(make_request({})) is None
    assert "WWW-Authenticate" in auth.challenge()


def test_spnego_authenticator_flow():
    def gss_accept(token: bytes):
        return "alice/host@EXAMPLE.COM" if token == b"valid" else None

    auth = SpnegoAuthenticator(gss_accept=gss_accept)
    good = base64.b64encode(b"valid").decode()
    bad = base64.b64encode(b"forged").decode()
    # principal's primary component becomes the user
    assert auth.authenticate(
        make_request({"Authorization": f"Negotiate {good}"})) == "alice"
    assert auth.authenticate(
        make_request({"Authorization": f"Negotiate {bad}"})) is None
    assert auth.authenticate(make_request({})) is None
    assert auth.authenticate(
        make_request({"Authorization": "Negotiate !!!notb64"})) is None
    assert auth.challenge() == {"WWW-Authenticate": "Negotiate"}


def test_spnego_closed_by_default():
    """No GSS acceptor configured -> nobody authenticates (closed, not
    open, when the KDC plumbing is missing)."""
    auth = SpnegoAuthenticator()
    token = base64.b64encode(b"anything").decode()
    assert auth.authenticate(
        make_request({"Authorization": f"Negotiate {token}"})) is None


def test_gssapi_acceptor_real_library_rejects_garbage():
    """The ctypes GSSAPI acceptor binds the real libgssapi_krb5 and
    cleanly rejects malformed/unauthenticated tokens (no KDC or keytab
    exists here, so rejection IS the correct behavior — the point is the
    call reaches the real library and comes back as a clean None)."""
    from cook_tpu.rest.gssapi import make_gssapi_acceptor

    acceptor = make_gssapi_acceptor()
    if acceptor is None:
        pytest.skip("libgssapi_krb5 not present in this image")
    assert acceptor(b"\x00garbage-token") is None
    assert acceptor(b"") is None
    # a structurally plausible but unauthenticated SPNEGO header
    assert acceptor(b"\x60\x28\x06\x06\x2b\x06\x01\x05\x05\x02") is None
    # end to end through the authenticator: garbage -> 401 path
    auth = SpnegoAuthenticator(gss_accept=acceptor)
    token = base64.b64encode(b"not-a-ticket").decode()
    assert auth.authenticate(
        make_request({"Authorization": f"Negotiate {token}"})) is None


def test_gssapi_config_wireup():
    """{"kind": "spnego", "gssapi": true} builds the real acceptor (or
    stays closed when the library is missing)."""
    from cook_tpu.rest import gssapi

    auth = authenticator_from_config({"kind": "spnego", "gssapi": True})
    assert isinstance(auth, SpnegoAuthenticator)
    if gssapi._load_lib() is not None:
        assert auth.gss_accept is not None
    # unknown library path -> closed, not an exception
    closed = authenticator_from_config(
        {"kind": "spnego", "gssapi": True, "gssapi_lib": "libnope.so.0"})
    assert closed.gss_accept is None


def test_composite_merges_challenges():
    auth = CompositeAuthenticator([SpnegoAuthenticator(),
                                   BasicAuthenticator()])
    challenge = auth.challenge()
    # later members override: basic wins the header slot, but both kinds
    # were consulted for authentication
    assert "WWW-Authenticate" in challenge
    assert auth.authenticate(make_request({})) is None


def test_authenticator_from_config():
    assert isinstance(authenticator_from_config({"kind": "spnego"}),
                      SpnegoAuthenticator)
    assert isinstance(authenticator_from_config({"kind": "basic"}),
                      BasicAuthenticator)
    dev = authenticator_from_config({"kind": "dev"})
    assert dev.authenticate(make_request({})) == "anonymous"
    with pytest.raises(ValueError):
        authenticator_from_config({"kind": "ldap"})


# ----------------------------------------------------------- HTTP level


@pytest.fixture()
def store():
    store = JobStore(clock=FakeClock())
    store.set_pool(Pool(name="default"))
    return store


def serve(api: CookApi):
    from cook_tpu.rest.server import ServerThread

    return ServerThread(api).start()


def test_spnego_http_401_challenge_and_success(store):
    def gss_accept(token: bytes):
        return "alice@EXAMPLE.COM" if token == b"tkt" else None

    api = CookApi(store, config=ApiConfig(
        authenticator=SpnegoAuthenticator(gss_accept=gss_accept)))
    srv = serve(api)
    try:
        resp = requests.get(f"{srv.url}/pools")
        assert resp.status_code == 401
        assert resp.headers["WWW-Authenticate"] == "Negotiate"
        # dev header is NOT honored under spnego-only auth
        resp = requests.get(f"{srv.url}/pools",
                            headers={"X-Cook-Requesting-User": "mallory"})
        assert resp.status_code == 401
        token = base64.b64encode(b"tkt").decode()
        resp = requests.get(
            f"{srv.url}/pools",
            headers={"Authorization": f"Negotiate {token}"})
        assert resp.status_code == 200
    finally:
        srv.stop()


def test_machine_endpoints_exempt_under_strict_auth(store):
    """LB health probes and executor heartbeat/progress posts carry no
    user credentials; strict auth must not 401 them (the reference takes
    these over the backend channel, outside the authed REST stack)."""
    api = CookApi(store, config=ApiConfig(
        authenticator=SpnegoAuthenticator()))  # closed: nobody auths
    srv = serve(api)
    try:
        assert requests.get(f"{srv.url}/debug").status_code == 200
        assert requests.get(f"{srv.url}/metrics").status_code == 200
        r = requests.post(f"{srv.url}/heartbeat/nope")
        assert r.status_code != 401
        r = requests.post(f"{srv.url}/progress/nope",
                          json={"progress_percent": 10, "sequence": 1})
        assert r.status_code != 401
        # everything else stays locked
        assert requests.get(f"{srv.url}/pools").status_code == 401
    finally:
        srv.stop()


def test_executor_token_gates_machine_posts(store):
    """With executor_token configured, heartbeat/progress posts need the
    shared secret — an unauthenticated peer can no longer spoof executor
    liveness for someone else's task."""
    api = CookApi(store, config=ApiConfig(
        authenticator=SpnegoAuthenticator(), executor_token="s3cret"))
    srv = serve(api)
    try:
        r = requests.post(f"{srv.url}/heartbeat/nope")
        assert r.status_code == 401
        r = requests.post(f"{srv.url}/heartbeat/nope",
                          headers={"X-Cook-Executor-Token": "wrong"})
        assert r.status_code == 401
        r = requests.post(f"{srv.url}/heartbeat/nope",
                          headers={"X-Cook-Executor-Token": "s3cret"})
        assert r.status_code != 401
        # health stays open regardless
        assert requests.get(f"{srv.url}/debug").status_code == 200
    finally:
        srv.stop()


ADMIN_GATED = [
    ("POST", "/compute-clusters", {"name": "x", "kind": "mock"}),
    ("DELETE", "/compute-clusters/m", None),
    ("POST", "/incremental-config", {"x": 1}),
    ("POST", "/shutdown-leader", None),
    ("POST", "/share", {"user": "bob", "share": {"mem": 1}}),
    ("DELETE", "/share?user=bob", None),
    ("POST", "/quota", {"user": "bob", "quota": {"mem": 1}}),
    ("DELETE", "/quota?user=bob", None),
]


def test_admin_gated_routes(store):
    """EVERY admin-gated route 403s for a non-admin and admits an admin
    (the reference's is-authorized? checks, rest/authorization.clj)."""
    api = CookApi(store)
    srv = serve(api)
    try:
        for method, path, body in ADMIN_GATED:
            resp = requests.request(
                method, f"{srv.url}{path}", json=body,
                headers={"X-Cook-Requesting-User": "mallory"})
            assert resp.status_code == 403, f"{method} {path} as mallory"
        for method, path, body in ADMIN_GATED:
            resp = requests.request(
                method, f"{srv.url}{path}", json=body,
                headers={"X-Cook-Requesting-User": "admin"})
            assert resp.status_code != 403, f"{method} {path} as admin"
    finally:
        srv.stop()


def test_file_url_generator_seam(store):
    """The FileUrlGenerator plugin overrides the backend's sandbox URL
    in instance JSON (plugins/definitions.clj:56)."""
    from cook_tpu.scheduler.plugins import PluginRegistry

    clock = store.clock
    cluster = MockCluster(
        "m", [MockHost(node_id="h", hostname="h", mem=1000, cpus=4)],
        clock=clock, sandbox_url_fn=lambda tid: f"http://backend/{tid}")
    scheduler = Scheduler(store, [cluster])

    class Generator:
        def file_url(self, instance):
            return f"https://files.corp/{instance.task_id}"

    job = make_job()
    store.submit_jobs([job])
    store.create_instance(job.uuid, "t1", hostname="h", node_id="h",
                          compute_cluster="m")
    plugins = PluginRegistry(file_url_generator=Generator())
    api = CookApi(store, scheduler, plugins=plugins)
    d = api._instance_json(store.instances["t1"])
    assert d["output_url"] == "https://files.corp/t1"
    # without the plugin, the backend's own URL is served
    d = CookApi(store, scheduler)._instance_json(store.instances["t1"])
    assert d["output_url"] == "http://backend/t1"


# ------------------------------------------- per-cluster launch limiter


def test_per_cluster_launch_rate_limiter():
    """A cluster whose launch bucket holds 2 tokens launches at most 2
    tasks per refill window, regardless of matches (rate_limit.clj:44)."""
    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "m", [MockHost(node_id="h", hostname="h", mem=64000, cpus=64)],
        clock=clock)
    cluster.launch_rate_limiter = TokenBucketRateLimiter(
        tokens_replenished_per_minute=2.0, bucket_size=2.0, clock=clock)
    scheduler = Scheduler(store, [cluster])
    jobs = [make_job(mem=100, cpus=1) for _ in range(5)]
    store.submit_jobs(jobs)
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    assert len(outcome.matched) == 2
    assert len(outcome.unmatched) == 3
    # no refill yet: nothing launches
    scheduler.rank_cycle(pool)
    assert len(scheduler.match_cycle(pool).matched) == 0
    # one minute replenishes two tokens
    clock.advance(60_000)
    scheduler.rank_cycle(pool)
    assert len(scheduler.match_cycle(pool).matched) == 2


def test_factory_attaches_launch_limiter():
    from cook_tpu.components import CLUSTER_FACTORIES

    clock = FakeClock()
    cluster = CLUSTER_FACTORIES["mock"](
        {"name": "m", "hosts": [{"node_id": "h", "mem": 100, "cpus": 1}],
         "launch_rate_per_minute": 10, "launch_burst": 3}, clock)
    assert cluster.launch_rate_limiter is not None
    assert cluster.launch_rate_limiter.tokens_available("m") == 3.0
