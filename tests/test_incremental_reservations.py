"""Incremental config rollout, rebalancer host reservations, pool moves."""
import pytest
import numpy as np

from cook_tpu.models.entities import (
    DEFAULT_USER,
    JobState,
    Pool,
    Resources,
    Share,
)
from cook_tpu.utils.incremental import (
    resolve_incremental,
    select_from_values,
    write_incremental,
)
from tests.conftest import make_job


def test_select_from_values_distribution():
    values = [{"value": "a", "portion": 0.3}, {"value": "b", "portion": 0.7}]
    picks = [select_from_values(values, f"entity-{i}") for i in range(2000)]
    frac_a = picks.count("a") / len(picks)
    assert 0.25 < frac_a < 0.35
    # deterministic per entity
    assert select_from_values(values, "x") == select_from_values(values, "x")


def test_incremental_roundtrip(store):
    write_incremental(store, "container-default",
                      [{"value": "img:v2", "portion": 1.0}])
    assert resolve_incremental(store, "container-default", "job-1") == "img:v2"
    assert resolve_incremental(store, "missing", "job-1", "fallback") == "fallback"


def test_pool_move(store):
    store.set_pool(Pool(name="other"))
    job = make_job()
    store.submit_jobs([job])
    assert store.move_job_pool(job.uuid, "other")
    assert store.jobs[job.uuid].pool == "other"
    assert store.pending_jobs("other")[0].uuid == job.uuid
    assert not store.pending_jobs("default")
    # running jobs may not move
    store.create_instance(job.uuid, "t1", hostname="h1")
    assert not store.move_job_pool(job.uuid, "default")


def test_reservation_steers_matcher():
    """A host reserved for job X must reject other jobs and accept X."""
    from cook_tpu.cluster.mock import MockCluster, MockHost
    from cook_tpu.models.store import JobStore
    from cook_tpu.scheduler.core import Scheduler
    from tests.conftest import FakeClock

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    cluster = MockCluster(
        "m", [MockHost(node_id="h0", hostname="h0", mem=1000, cpus=8),
              MockHost(node_id="h1", hostname="h1", mem=1000, cpus=8)],
        clock=clock)
    scheduler = Scheduler(store, [cluster])
    target = make_job(user="vip", cpus=1)
    other = make_job(user="other", cpus=1, priority=99)  # would match first
    store.submit_jobs([target, other])
    scheduler.host_reservations["h0"] = target.uuid
    scheduler.host_reservations["h1"] = target.uuid  # reserve everything
    pool = store.pools["default"]
    scheduler.rank_cycle(pool)
    outcome = scheduler.match_cycle(pool)
    matched = {j.uuid: o.hostname for j, o in outcome.matched}
    assert target.uuid in matched
    assert other.uuid not in matched
    # reservation released once the job launched
    assert not scheduler.host_reservations


@pytest.mark.parametrize("fast", [False, True])
def test_rebalancer_multi_task_decision_creates_reservation(fast):
    from cook_tpu.cluster.mock import MockCluster, MockHost
    from cook_tpu.models.store import JobStore
    from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
    from cook_tpu.scheduler.rebalancer import RebalancerParams
    from tests.conftest import FakeClock

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    store.set_share(Share(user=DEFAULT_USER, pool="default",
                          resources=Resources(mem=400, cpus=4, gpus=1)))
    # the starved user has a large share, so its pending dru is low and the
    # hog's tasks exceed it by more than min-dru-diff
    store.set_share(Share(user="starved", pool="default",
                          resources=Resources(mem=1600, cpus=16, gpus=1)))
    cluster = MockCluster(
        "m", [MockHost(node_id="h0", hostname="h0", mem=800, cpus=8)],
        clock=clock)
    scheduler = Scheduler(
        store, [cluster],
        SchedulerConfig(rebalancer=RebalancerParams(
            safe_dru_threshold=0.0, min_dru_diff=0.01, max_preemption=5,
            fast_cycle=fast)),
    )
    pool = store.pools["default"]
    # hog runs two tasks filling the host
    for i in range(2):
        job = make_job(user="hog", mem=400, cpus=4)
        store.submit_jobs([job])
        scheduler.rank_cycle(pool)
        scheduler.match_cycle(pool)
    # starved user's big job needs BOTH slots -> multi-task preemption
    big = make_job(user="starved", mem=800, cpus=8)
    store.submit_jobs([big])
    scheduler.rank_cycle(pool)
    decisions = scheduler.rebalance_cycle(pool)
    assert decisions and len(decisions[0].task_ids) == 2
    assert scheduler.host_reservations == {"h0": big.uuid}


def test_rebalancer_respects_novel_host():
    """A pending job that already failed on a host never preempts there
    (make-rebalancer-job-constraints includes novel-host)."""
    from cook_tpu.cluster.mock import MockCluster, MockHost
    from cook_tpu.models.entities import InstanceStatus
    from cook_tpu.models.store import JobStore
    from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
    from cook_tpu.scheduler.rebalancer import RebalancerParams
    from tests.conftest import FakeClock

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    store.set_share(Share(user=DEFAULT_USER, pool="default",
                          resources=Resources(mem=400, cpus=4, gpus=1)))
    store.set_share(Share(user="starved", pool="default",
                          resources=Resources(mem=1600, cpus=16, gpus=1)))
    cluster = MockCluster(
        "m", [MockHost(node_id="h0", hostname="h0", mem=800, cpus=8)],
        clock=clock)
    scheduler = Scheduler(
        store, [cluster],
        SchedulerConfig(rebalancer=RebalancerParams(
            safe_dru_threshold=0.0, min_dru_diff=0.01, max_preemption=5)),
    )
    pool = store.pools["default"]
    for i in range(2):
        job = make_job(user="hog", mem=400, cpus=4)
        store.submit_jobs([job])
        scheduler.rank_cycle(pool)
        scheduler.match_cycle(pool)
    big = make_job(user="starved", mem=800, cpus=8)
    store.submit_jobs([big])
    # big already failed on h0 -> novel-host forbids preempting there
    store.create_instance(big.uuid, "prior", hostname="h0")
    store.update_instance_state("prior", InstanceStatus.FAILED, 99000)
    scheduler.rank_cycle(pool)
    decisions = scheduler.rebalance_cycle(pool)
    assert decisions == []


def test_rebalancer_params_runtime_mutable():
    """Dynamic-config overrides take effect without restart (reference:
    Datomic-stored rebalancer config)."""
    from cook_tpu.cluster.mock import MockCluster, MockHost
    from cook_tpu.models.store import JobStore
    from cook_tpu.scheduler.core import Scheduler, SchedulerConfig
    from cook_tpu.scheduler.rebalancer import RebalancerParams
    from tests.conftest import FakeClock

    clock = FakeClock()
    store = JobStore(clock=clock)
    store.set_pool(Pool(name="default"))
    scheduler = Scheduler(
        store, [MockCluster("m", [], clock=clock)],
        SchedulerConfig(rebalancer=RebalancerParams(max_preemption=100)),
    )
    assert scheduler._rebalancer_params().max_preemption == 100
    store.dynamic_config["rebalancer"] = {"max_preemption": 7,
                                          "min_dru_diff": 0.25}
    params = scheduler._rebalancer_params()
    assert params.max_preemption == 7
    assert params.min_dru_diff == 0.25
    assert params.safe_dru_threshold == 1.0  # untouched default
